"""HTTP front end for the serve subsystem (docs/SERVING.md).

A threaded ``http.server`` endpoint (one thread per connection — request
parsing/hashing runs concurrently on connection threads; actual scoring
is serialized through the MicroBatcher's single dispatch thread, which is
exactly what makes concurrent requests coalesce):

- ``POST /predict`` — body ``{"rows": [["f1:1", "f2:0.5"], ...]}`` (or
  ``{"features": [...]}`` for one row; FFM rows use
  ``"field:index:value"`` tokens), optional ``"deadline_ms"``. Features
  hash through the trainer's own ftvec/mhash path. Response:
  ``{"scores": [...], "model_step": N, "n": N}``. Shed requests get 503,
  expired deadlines 504, parse errors 400.
- ``GET /healthz`` — READINESS: 200 once warmup completed, 503 while
  warming (so the fleet router / an external LB can gate cold replicas),
  with model step, model/bundle age, queue depth and the cheap serving
  counters.
- ``POST /retrieve`` — the retrieval plane (docs/SERVING.md "Retrieval
  plane"): body ``{"queries": [{"user": 3, "k": 10}, {"item": 7,
  "tier": "lsh"}, ...]}`` (or one bare query object), optional
  ``"deadline_ms"``. Response ``{"results": [{"ids": [...], "scores":
  [...]}], "model_step": N, "n": N}`` (+ per-row ``"words"`` when the
  factor table carries a vocab). 404 unless the server was built with
  a retrieval engine; queries coalesce through their OWN MicroBatcher
  so ranking never queues behind predict scoring.
- ``POST /reload`` — force a hot-reload check (body optionally
  ``{"path": "...npz"}`` to load an explicit bundle).

Clients sending ``Accept: application/x-hivemall-frame`` get
``/predict`` and ``/retrieve`` responses as compact HMR1 binary frames
(serve.wire) instead of JSON — top-k responses are dominated by JSON
float encode at high k.
- ``GET /slo`` — the SLO engine's windowed burn rates + drift state
  (docs/OBSERVABILITY.md "Serving traces and SLOs").
- ``GET /promotion`` — the promotion control plane's status: the watched
  directory's ``PROMOTED`` pointer manifest, the engine's follow mode,
  and the live ``promotion`` registry section (docs/RELIABILITY.md
  "Promotion and rollback").
- ``GET /snapshot`` / ``GET /metrics`` / ``GET /trace`` — the central
  obs registry (the ``serve`` section rides next to
  pipeline/train/mix/checkpoint/spans) and the process span ring,
  inherited from the obs HTTP handler.

Request tracing + per-hop breakdown: a request carrying an
``x-hivemall-trace`` header (client-supplied, or minted by the fleet
router's sampler) has its id tagged onto the ``serve.enqueue`` /
``serve.batch`` / ``serve.predict`` spans and echoed on the response.
EVERY ``/predict`` response additionally carries ``x-hivemall-hop`` —
``parse=,queue=,assemble=,predict=,other=,total=`` milliseconds whose
parts sum to the replica's measured wall for that request — which the
router extends with its own relay hop.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
from typing import Optional

import numpy as np

from ..io.weight_arena import host_rss_bytes as _host_rss
from ..obs.http import _Handler as _ObsHandler
from ..obs.slo import SloEngine
from ..obs.trace import get_tracer
from .batcher import MicroBatcher, ServeDeadline, ServeOverload
from .client import RawHTTPClient
from .wire import (CONTENT_TYPE_FRAME, WireError, decode_frame,
                   encode_response_frame)

__all__ = ["PredictServer", "KeepAliveClient", "health_payload"]


class KeepAliveClient(RawHTTPClient):
    """Historical name for the shared raw keep-alive client
    (serve.client.RawHTTPClient) — the bench/smoke drivers and a pile
    of tests construct this. One endpoint, one per thread; see the
    shared module for the wire details (binary frames, UDS)."""


def health_payload(engine, batcher) -> "tuple[bool, dict]":
    """The ``/healthz`` READINESS payload, shared verbatim by both
    serving planes (the fleet manager parses it on every health tick —
    the planes must not drift on a single key). Returns ``(ready,
    payload)``; serve 200 when ready, 503 while warming."""
    ready = engine.ready
    return ready, {
        "status": "ok" if ready else "warming",
        "ready": ready,
        "algo": engine.algo,
        "model_step": engine.model_step,
        "model_age_seconds": engine.model_age_seconds,
        "bundle_age_seconds": engine.bundle_age_seconds,
        "queue_depth": batcher.queue_depth,
        "requests": batcher.requests,
        "shed": batcher.shed,
        "expired": batcher.expired,
        "errors": batcher.errors,
        "reloads": engine.reloads,
        "reload_failures": engine.reload_failures,
        # zero-copy serving gauges: the fleet manager folds these into
        # the `fleet` registry section and the router's aggregated
        # snapshot (host RSS + mapped arena bytes per replica = the
        # memory-headroom evidence)
        "host_rss_bytes": _host_rss(),
        "arena_mapped_bytes": engine.arena_mapped_bytes,
        "precision": engine.precision,
        # cumulative SLO totals (latency histogram + score moments):
        # the fleet manager sums these across replicas into its SLO
        # engine every health tick
        "slo": batcher.slo_totals(),
    }


class _ServeHandler(_ObsHandler):
    """Extends the obs handler (/snapshot, /metrics, timeout, quiet logs)
    with the predict surface. The owning PredictServer is attached on the
    per-server subclass."""

    server_ref: "PredictServer" = None   # type: ignore[assignment]

    # HTTP/1.1 => keep-alive by default: per-request TCP setup (handshake
    # + slow-start + a fresh connection thread) is measurable overhead in
    # bench_serve at high concurrency, and the fleet router holds pooled
    # connections to every replica. Safe here because every response path
    # (_json, the obs handler, send_error) carries Content-Length; the
    # threaded server gives each kept-alive connection its own thread, and
    # the inherited 10s socket timeout reaps idle ones.
    protocol_version = "HTTP/1.1"
    # http.server writes status line / headers / body as SEPARATE small
    # sends; on a kept-alive connection Nagle + delayed ACK turns that
    # into ~40ms stalls per response (measured: fleet p50 went 73ms ->
    # sub-ms with NODELAY). The close-per-request HTTP/1.0 server never
    # saw it because close() flushed.
    disable_nagle_algorithm = True

    # -- helpers -------------------------------------------------------------
    _body_read = False                   # per-request; reset in do_*

    def _wants_frame(self) -> bool:
        """Did the client negotiate an HMR1 binary response?"""
        accept = (self.headers.get("Accept") or "").lower()
        return CONTENT_TYPE_FRAME in accept

    def _frame(self, body: bytes,
               extra_headers: Optional[dict] = None) -> None:
        """A 200 with a binary HMR1 body (success paths only — errors
        stay JSON on every protocol so clients always parse them)."""
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE_FRAME)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if self.close_connection:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj: dict,
              extra_headers: Optional[dict] = None) -> None:
        body = json.dumps(obj, default=str).encode()
        if code >= 400 and not self._body_read:
            # an error sent BEFORE the request body was consumed (e.g.
            # the 64MB cap rejects before reading) leaves bytes on the
            # wire that keep-alive would parse as the next request line —
            # those responses close the connection. Errors after a full
            # read (503 shed, 504 expired, 400 parse) keep it open: at
            # overload, forcing every shed client to re-handshake TCP
            # would amplify load exactly when the server is saturated
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if self.close_connection:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        ln = int(self.headers.get("Content-Length") or 0)
        if ln <= 0:
            self._body_read = True
            return {}
        if ln > (64 << 20):
            raise ValueError(f"request body {ln} bytes > 64MB cap")
        raw = self.rfile.read(ln)
        self._body_read = True           # wire is clean past this point
        obj = json.loads(raw or b"{}")
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # -- routes --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 — http.server API
        self._body_read = True           # GETs carry no body to drain
        path = self.path.split("?", 1)[0]
        s = self.server_ref
        if path == "/healthz":
            # READINESS, not bare liveness: 200 only once warmup completed
            # (503 while warming), so the fleet router — and any external
            # LB probing this port — can gate cold/warming replicas out of
            # rotation instead of routing requests into XLA compiles. The
            # payload is shared with the evloop plane (health_payload).
            # A retrieval-only server reports its retrieval engine here
            # (same keys — the fleet manager must not see plane drift).
            eng = s.engine if s.engine is not None else s.retrieval
            bat = s.batcher if s.batcher is not None else s.rbatcher
            ready, payload = health_payload(eng, bat)
            if s.retrieval is not None and s.engine is not None:
                # both planes up: readiness is the AND (a predict-ready
                # replica with a cold factor table must not take top-k)
                ready = ready and s.retrieval.ready
                payload["ready"] = ready
                if payload["status"] == "ok" and not ready:
                    payload["status"] = "warming"
            self._json(200 if ready else 503, payload)
            return
        if path == "/slo":
            slo = s.slo
            if slo is None:
                self._json(404, {"error": "no SLO engine configured"})
                return
            self._json(200, slo.evaluate())
            return
        if path == "/promotion":
            # promotion status (docs/RELIABILITY.md "Promotion and
            # rollback"): the watched dir's PROMOTED pointer manifest,
            # the engine's follow mode, and — when a controller/manager
            # registered one — the live `promotion` registry section
            from ..obs.registry import registry
            from .promote import promotion_manifest_view
            eng = s.engine if s.engine is not None else s.retrieval
            out = promotion_manifest_view(eng.checkpoint_dir)
            out["follow"] = eng.follow
            out["section"] = registry.snapshot().get("promotion")
            self._json(200, out)
            return
        super().do_GET()               # /snapshot, /metrics, /trace, 404

    def do_POST(self):  # noqa: N802 — http.server API
        self._body_read = False          # fresh request on this connection
        path = self.path.split("?", 1)[0]
        s = self.server_ref
        if path == "/reload":
            try:
                body = self._read_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            # both planes follow the same checkpoint dir: one /reload
            # ticks whichever engines exist so a promoted bundle can
            # never serve predicts at step N and top-k at step N-1
            eng = s.engine if s.engine is not None else s.retrieval
            try:
                swapped = eng.reload(body.get("path"))
                if s.retrieval is not None and eng is not s.retrieval:
                    swapped = s.retrieval.reload(body.get("path")) \
                        or swapped
            except ValueError as e:    # out-of-tree path: the model dir
                self._json(403, {"error": str(e)})   # is the trust boundary
                return
            self._json(200, {"reloaded": swapped,
                             "model_step": eng.model_step,
                             "reload_failures": eng.reload_failures})
            return
        if path == "/retrieve":
            self._do_retrieve()
            return
        if path != "/predict":
            self.send_error(404, "unknown path (try /predict, /retrieve, "
                                 "/healthz, /reload, /slo, /snapshot or "
                                 "/metrics)")
            return
        if s.engine is None:
            # body unread -> _json closes the connection (wire hygiene)
            self._json(404, {"error": "no predict engine on this server "
                                      "(retrieval-only; try /retrieve)"})
            return
        t_req0 = time.monotonic()
        # request-scoped tracing: honor a client/router-supplied id —
        # the spans this request touches get tagged with it and the
        # response echoes it (docs/OBSERVABILITY.md)
        tid = self.headers.get("x-hivemall-trace")
        ctype = (self.headers.get("Content-Type") or "").lower()
        try:
            if ctype.startswith(CONTENT_TYPE_FRAME):
                # binary frame protocol (serve.wire): pre-hashed rows,
                # no libsvm string parse; bit-matches the JSON path
                ln = int(self.headers.get("Content-Length") or 0)
                if ln > (64 << 20):
                    raise ValueError(
                        f"request body {ln} bytes > 64MB cap")
                raw_body = self.rfile.read(ln) if ln > 0 else b""
                self._body_read = True
                frame_rows, deadline_ms = decode_frame(
                    raw_body, s.engine.max_row_features)
                parsed = [s.engine.parse(r) for r in frame_rows]
                rows = None            # no raw strings to tee
            else:
                body = self._read_body()
                rows = body.get("rows")
                if rows is None:
                    feats = body.get("features")
                    if feats is None:
                        raise ValueError(
                            'body needs "rows" or "features"')
                    rows = [feats]
                if not isinstance(rows, list) \
                        or not all(isinstance(r, list) for r in rows):
                    raise ValueError(
                        '"rows" must be a list of feature-string '
                        'lists (a bare string would be read as '
                        'per-character rows)')
                deadline_ms = body.get("deadline_ms")
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)   # malformed -> 400
                # hashing/parsing on THIS connection thread — concurrent
                # requests parse in parallel, only scoring serializes
                parsed = [s.engine.parse(r) for r in rows]
        except WireError as e:
            # a desynced binary stream cannot be resynchronized
            # mid-connection: 400 AND close (JSON 400s keep alive)
            self.close_connection = True
            self._json(400, {"error": str(e)})
            return
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        t_parsed = time.monotonic()
        try:
            with s.tracer.context(tid):   # tags serve.enqueue
                # `rows` rides along as the raw feature strings so a
                # raw-capturing tee (the retrain replay buffer) can
                # mirror what the client actually sent
                fut = s.batcher.submit(parsed, deadline_ms=deadline_ms,
                                       trace_id=tid, raw=rows)
            res = fut.result(timeout=s.request_timeout)
        except ServeOverload as e:
            self._json(503, {"error": str(e), "shed": True})
            return
        except ServeDeadline as e:
            self._json(504, {"error": str(e), "expired": True})
            return
        except Exception as e:         # noqa: BLE001 — predict failure is
            # a 500 on THIS request, never a handler crash
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if isinstance(res, tuple):
            scores, step = res
        else:                          # zero-row request short-circuit
            scores, step = res, s.engine.model_step
        # per-hop latency breakdown: parts sum to the replica's measured
        # wall for THIS request ("other" closes the residual — result
        # pickup + response build). The router stacks its relay hop on
        # top; bench_serve and the fleet smoke consume these.
        hop = getattr(fut, "hop", None) or {}
        total_ms = (time.monotonic() - t_req0) * 1000.0
        parse_ms = (t_parsed - t_req0) * 1000.0
        queue_ms = hop.get("queue_s", 0.0) * 1000.0
        assemble_ms = hop.get("assemble_s", 0.0) * 1000.0
        predict_ms = hop.get("predict_s", 0.0) * 1000.0
        other_ms = max(0.0, total_ms - parse_ms - queue_ms
                       - assemble_ms - predict_ms)
        extra = {"x-hivemall-hop":
                 f"parse={parse_ms:.3f},queue={queue_ms:.3f},"
                 f"assemble={assemble_ms:.3f},predict={predict_ms:.3f},"
                 f"other={other_ms:.3f},total={total_ms:.3f}"}
        if tid:
            extra["x-hivemall-trace"] = tid
        if self._wants_frame():
            # HMR1: all scores as one frame row (scores-only layout) —
            # skips the per-float JSON encode on the response hot path
            self._frame(encode_response_frame([scores],
                                              model_step=int(step)),
                        extra_headers=extra)
            return
        self._json(200, {"scores": [float(v) for v in scores],
                         "model_step": int(step),
                         "n": len(scores)}, extra_headers=extra)

    def _do_retrieve(self) -> None:
        """POST /retrieve — top-k queries through the retrieval plane's
        own MicroBatcher (docs/SERVING.md "Retrieval plane")."""
        s = self.server_ref
        r = s.retrieval
        if r is None:
            self._json(404, {"error": "no retrieval engine on this "
                                      "server (serve --retrieval)"})
            return
        t_req0 = time.monotonic()
        tid = self.headers.get("x-hivemall-trace")
        try:
            body = self._read_body()
            queries = body.get("queries")
            if queries is None:
                # one bare query object rides at the top level
                queries = [body] if ("user" in body or "item" in body) \
                    else None
            if not isinstance(queries, list) or not queries:
                raise ValueError('body needs "queries": [{"user": id} | '
                                 '{"item": id}, ...]')
            deadline_ms = body.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
            parsed = [r.parse_query(q) for q in queries]
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        t_parsed = time.monotonic()
        try:
            with s.tracer.context(tid):
                fut = s.rbatcher.submit(parsed, deadline_ms=deadline_ms,
                                        trace_id=tid)
            res = fut.result(timeout=s.request_timeout)
        except ServeOverload as e:
            self._json(503, {"error": str(e), "shed": True})
            return
        except ServeDeadline as e:
            self._json(504, {"error": str(e), "expired": True})
            return
        except Exception as e:         # noqa: BLE001 — ranking failure is
            # a 500 on THIS request, never a handler crash
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if isinstance(res, tuple):
            packed, step = res
        else:                          # zero-query short-circuit
            packed, step = res, r.model_step
        # unpack [n, max_k, 2] (ids|-1 pad, scores) into ragged lists
        ids_rows, scores_rows = [], []
        for i in range(len(parsed)):
            ids = packed[i, :, 0]
            valid = ids >= 0
            ids_rows.append(ids[valid].astype(np.int32))
            scores_rows.append(
                np.asarray(packed[i, valid, 1], np.float32))
        hop = getattr(fut, "hop", None) or {}
        total_ms = (time.monotonic() - t_req0) * 1000.0
        parse_ms = (t_parsed - t_req0) * 1000.0
        queue_ms = hop.get("queue_s", 0.0) * 1000.0
        assemble_ms = hop.get("assemble_s", 0.0) * 1000.0
        predict_ms = hop.get("predict_s", 0.0) * 1000.0
        other_ms = max(0.0, total_ms - parse_ms - queue_ms
                       - assemble_ms - predict_ms)
        extra = {"x-hivemall-hop":
                 f"parse={parse_ms:.3f},queue={queue_ms:.3f},"
                 f"assemble={assemble_ms:.3f},predict={predict_ms:.3f},"
                 f"other={other_ms:.3f},total={total_ms:.3f}"}
        if tid:
            extra["x-hivemall-trace"] = tid
        if self._wants_frame():
            self._frame(encode_response_frame(scores_rows, ids_rows,
                                              model_step=int(step)),
                        extra_headers=extra)
            return
        results = []
        for ids, sc in zip(ids_rows, scores_rows):
            row = {"ids": [int(v) for v in ids],
                   "scores": [float(v) for v in sc]}
            words = r.labels(ids)
            if words is not None:
                row["words"] = words
            results.append(row)
        self._json(200, {"results": results, "model_step": int(step),
                         "n": len(results)}, extra_headers=extra)


class _ThreadedHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._conns: set = set()         # live accepted sockets
        self._conns_lock = threading.Lock()

    def handle_error(self, request, client_address):
        pass                           # client disconnects are routine

    def get_request(self):
        sock, addr = super().get_request()
        with self._conns_lock:
            self._conns.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_connections(self, timeout: float = 5.0) -> None:
        """Drain surviving keep-alive connections. shutdown() only
        stops the accept loop — a peer that holds its side open (the
        fleet router's conn pool) would park each handler thread in
        readline until the 30s idle reaper, leaving the accepted socket
        open past teardown (the leaktrack census counts that).

        Graceful by construction: EOF the READ side first, so an idle
        handler wakes and exits while one mid-request keeps its intact
        write side and finishes its response (drain=True's promise),
        then loops into the EOF. Each exiting handler closes its own
        socket via shutdown_request; only stragglers past ``timeout``
        get force-closed."""
        import socket as _socket
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(_socket.SHUT_RD)
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._conns_lock:
                if not self._conns:
                    return
            time.sleep(0.01)
        with self._conns_lock:
            leftovers = list(self._conns)
            self._conns.clear()
        for sock in leftovers:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class PredictServer:
    """Engine + batcher + HTTP endpoint, wired into the obs registry.

    ``port=0`` binds an ephemeral port (read ``self.port``). Loopback-only
    by default; bind ``host="0.0.0.0"`` explicitly to serve a fleet.
    Starting the server also starts the engine's checkpoint watcher when a
    watch directory is configured (the train+serve shared-dir recipe).

    ``retrieval=`` mounts a serve.retrieve.RetrievalEngine on
    ``POST /retrieve`` behind its OWN MicroBatcher (top-k ranking must
    not queue behind predict scoring and vice versa — the two planes
    coalesce independently). ``engine=None`` with a retrieval engine is
    a retrieval-only server: /predict 404s, health/SLO ride the
    retrieval plane."""

    def __init__(self, engine=None, *, host: str = "127.0.0.1",
                 port: int = 0,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0,
                 max_queue_rows: Optional[int] = None,
                 deadline_ms: float = 0.0,
                 request_timeout: float = 60.0,
                 watch: bool = True,
                 slo: "bool | SloEngine" = True,
                 slo_p99_ms: float = 100.0,
                 slo_availability: float = 0.999,
                 retrieval=None):
        if engine is None and retrieval is None:
            raise ValueError("PredictServer needs an engine, a retrieval "
                             "engine, or both")
        self.engine = engine
        self.retrieval = retrieval
        self.request_timeout = float(request_timeout)
        self._watch = bool(watch)
        self.tracer = get_tracer()
        # the versioned predict fn: each response carries the step of the
        # model version that actually scored it (correct across hot swaps)
        self.batcher: Optional[MicroBatcher] = None
        if engine is not None:
            self.batcher = MicroBatcher(
                engine.predict_rows_versioned,
                max_batch=int(max_batch or engine.max_batch),
                max_delay_ms=max_delay_ms,
                max_queue_rows=max_queue_rows,
                deadline_ms=deadline_ms)
            engine.attach_batcher(self.batcher)
        self.rbatcher: Optional[MicroBatcher] = None
        if retrieval is not None:
            self.rbatcher = MicroBatcher(
                retrieval.retrieve_rows_versioned,
                max_batch=int(retrieval.max_batch),
                max_delay_ms=max_delay_ms,
                max_queue_rows=max_queue_rows,
                deadline_ms=deadline_ms)
            retrieval.attach_batcher(self.rbatcher)
        # SLO engine over this server's own batcher totals (the fleet
        # topology passes slo=False here and samples fleet-wide at the
        # manager instead — one engine per surface, never two)
        if isinstance(slo, SloEngine):
            self.slo: Optional[SloEngine] = slo
            self._own_slo = False
        elif slo:
            self.slo = SloEngine(p99_ms=slo_p99_ms,
                                 availability=slo_availability)
            self._own_slo = True
        else:
            self.slo = None
            self._own_slo = False
        handler = type("_BoundServeHandler", (_ServeHandler,),
                       {"server_ref": self})
        self._httpd = _ThreadedHTTPServer((host, port), handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PredictServer":
        if self._watch:
            if self.engine is not None:
                self.engine.start_watch()
            if self.retrieval is not None:
                self.retrieval.start_watch()
        if self._own_slo and self.slo is not None:
            bat = self.batcher if self.batcher is not None \
                else self.rbatcher
            self.slo.start(bat.slo_totals)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"serve-http:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = False) -> None:
        """Shut down: stop accepting connections, then close the batcher.
        ``drain=True`` is the graceful path (a fleet replica on SIGTERM):
        requests already accepted score to completion before the batcher
        stops; the default fails queued requests fast."""
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._own_slo and self.slo is not None:
            self.slo.stop()
        if self.batcher is not None:
            self.batcher.close(drain=drain, timeout=30.0 if drain else 5.0)
        if self.rbatcher is not None:
            self.rbatcher.close(drain=drain,
                                timeout=30.0 if drain else 5.0)
        # EOF-drain surviving keep-alive conns: in-flight responses
        # (scores resolved during the batcher drain) still write to
        # completion; nothing outlives the server
        self._httpd.close_connections()
        if self.engine is not None:
            self.engine.close()
        if self.retrieval is not None:
            self.retrieval.close()
