"""HTTP front end for the serve subsystem (docs/SERVING.md).

A threaded ``http.server`` endpoint (one thread per connection — request
parsing/hashing runs concurrently on connection threads; actual scoring
is serialized through the MicroBatcher's single dispatch thread, which is
exactly what makes concurrent requests coalesce):

- ``POST /predict`` — body ``{"rows": [["f1:1", "f2:0.5"], ...]}`` (or
  ``{"features": [...]}`` for one row; FFM rows use
  ``"field:index:value"`` tokens), optional ``"deadline_ms"``. Features
  hash through the trainer's own ftvec/mhash path. Response:
  ``{"scores": [...], "model_step": N, "n": N}``. Shed requests get 503,
  expired deadlines 504, parse errors 400.
- ``GET /healthz`` — liveness + model step/age + queue depth.
- ``POST /reload`` — force a hot-reload check (body optionally
  ``{"path": "...npz"}`` to load an explicit bundle).
- ``GET /snapshot`` / ``GET /metrics`` — the central obs registry (the
  ``serve`` section rides next to pipeline/train/mix/checkpoint/spans),
  inherited from the obs HTTP handler.
"""

from __future__ import annotations

import http.server
import json
import threading
from typing import Optional

from ..obs.http import _Handler as _ObsHandler
from .batcher import MicroBatcher, ServeDeadline, ServeOverload

__all__ = ["PredictServer"]


class _ServeHandler(_ObsHandler):
    """Extends the obs handler (/snapshot, /metrics, timeout, quiet logs)
    with the predict surface. The owning PredictServer is attached on the
    per-server subclass."""

    server_ref: "PredictServer" = None   # type: ignore[assignment]

    # -- helpers -------------------------------------------------------------
    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        ln = int(self.headers.get("Content-Length") or 0)
        if ln <= 0:
            return {}
        if ln > (64 << 20):
            raise ValueError(f"request body {ln} bytes > 64MB cap")
        obj = json.loads(self.rfile.read(ln) or b"{}")
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # -- routes --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            s = self.server_ref
            self._json(200, {
                "status": "ok",
                "algo": s.engine.algo,
                "model_step": s.engine.model_step,
                "model_age_seconds": s.engine.model_age_seconds,
                "queue_depth": s.batcher.queue_depth,
            })
            return
        super().do_GET()               # /snapshot, /metrics, 404

    def do_POST(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        s = self.server_ref
        if path == "/reload":
            try:
                body = self._read_body()
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            try:
                swapped = s.engine.reload(body.get("path"))
            except ValueError as e:    # out-of-tree path: the model dir
                self._json(403, {"error": str(e)})   # is the trust boundary
                return
            self._json(200, {"reloaded": swapped,
                             "model_step": s.engine.model_step,
                             "reload_failures": s.engine.reload_failures})
            return
        if path != "/predict":
            self.send_error(404, "unknown path (try /predict, /healthz, "
                                 "/reload, /snapshot or /metrics)")
            return
        try:
            body = self._read_body()
            rows = body.get("rows")
            if rows is None:
                feats = body.get("features")
                if feats is None:
                    raise ValueError('body needs "rows" or "features"')
                rows = [feats]
            if not isinstance(rows, list) \
                    or not all(isinstance(r, list) for r in rows):
                raise ValueError('"rows" must be a list of feature-string '
                                 'lists (a bare string would be read as '
                                 'per-character rows)')
            deadline_ms = body.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)   # malformed -> 400
            # hashing/parsing on THIS connection thread — concurrent
            # requests parse in parallel, only scoring serializes
            parsed = [s.engine.parse(r) for r in rows]
        except (ValueError, TypeError, KeyError,
                json.JSONDecodeError) as e:
            self._json(400, {"error": str(e)})
            return
        try:
            fut = s.batcher.submit(parsed, deadline_ms=deadline_ms)
            res = fut.result(timeout=s.request_timeout)
        except ServeOverload as e:
            self._json(503, {"error": str(e), "shed": True})
            return
        except ServeDeadline as e:
            self._json(504, {"error": str(e), "expired": True})
            return
        except Exception as e:         # noqa: BLE001 — predict failure is
            # a 500 on THIS request, never a handler crash
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if isinstance(res, tuple):
            scores, step = res
        else:                          # zero-row request short-circuit
            scores, step = res, s.engine.model_step
        self._json(200, {"scores": [float(v) for v in scores],
                         "model_step": int(step),
                         "n": len(scores)})


class _ThreadedHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        pass                           # client disconnects are routine


class PredictServer:
    """Engine + batcher + HTTP endpoint, wired into the obs registry.

    ``port=0`` binds an ephemeral port (read ``self.port``). Loopback-only
    by default; bind ``host="0.0.0.0"`` explicitly to serve a fleet.
    Starting the server also starts the engine's checkpoint watcher when a
    watch directory is configured (the train+serve shared-dir recipe)."""

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 2.0,
                 max_queue_rows: Optional[int] = None,
                 deadline_ms: float = 0.0,
                 request_timeout: float = 60.0,
                 watch: bool = True):
        self.engine = engine
        self.request_timeout = float(request_timeout)
        self._watch = bool(watch)
        # the versioned predict fn: each response carries the step of the
        # model version that actually scored it (correct across hot swaps)
        self.batcher = MicroBatcher(
            engine.predict_rows_versioned,
            max_batch=int(max_batch or engine.max_batch),
            max_delay_ms=max_delay_ms,
            max_queue_rows=max_queue_rows,
            deadline_ms=deadline_ms)
        engine.attach_batcher(self.batcher)
        handler = type("_BoundServeHandler", (_ServeHandler,),
                       {"server_ref": self})
        self._httpd = _ThreadedHTTPServer((host, port), handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PredictServer":
        if self._watch:
            self.engine.start_watch()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"serve-http:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self.batcher.close()
        self.engine.close()
