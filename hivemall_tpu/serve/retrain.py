"""Autopilot retraining — the drift-driven controller that closes the
train→validate→promote→rollback loop (docs/RELIABILITY.md "Autonomous
retraining").

Hivemall's essence is the full UDTF-train→predict loop over live
warehouse data (PAPER.md [B]); until now this repo's loop was open at
one seam: the SLO engine's score-drift changefinder emits
``retrain_wanted`` votes (obs/slo.py) and nothing consumed them. This
module is the consumer:

- :class:`ReplayBuffer` — a spill-to-disk ring of recent LABELED
  traffic rows (raw request feature strings + joined labels), teed off
  the serving path (:class:`~hivemall_tpu.serve.promote.ShadowBuffer`
  raw capture in a single server, :class:`RouterTee` in a fleet).
  Segments are written with the checkpoint idiom (tmp → fsync →
  ``os.replace``) so a crash never leaves a torn segment, and the ring
  evicts oldest-first so the buffer always holds the newest regime.
- :class:`RetrainController` — the daemon. It debounces
  ``retrain_wanted`` votes through the shared
  :class:`~hivemall_tpu.obs.devprof.DriftWatch` flap detector plus
  explicit storm controls (per-model cooldown with rejection backoff, a
  max-retrains-per-window cap, a concurrent-retrain budget of exactly
  one), then launches a retrain in a SUPERVISED CHILD PROCESS:
  warm-started from the ``PROMOTED`` bundle via the trainer's bundle
  resume path, fed from the base corpus (whose epochs go through the
  PR 6 shard caches — warm mmap, zero re-parse) concatenated with the
  replay buffer. The candidate bundle lands in the watched checkpoint
  dir, where the EXISTING gate/canary/rollback machinery
  (serve.promote / serve.fleet) finishes the job; the controller
  watches the pointer manifest + ``.rejected`` markers to learn the
  outcome, and a gate rejection quarantines the attempt and BACKS OFF
  (cooldown × backoff^consecutive-rejections) so a bad data regime can
  never retrain-storm.

State machine (the ``retrain`` obs registry section):
``idle → triggered → training → gating → canary → cooldown → idle``.

Every transition is durable: the controller persists a ``RETRAIN_STATE``
stamp (atomic json) next to the ``PROMOTED`` pointer, so a controller
crashed/SIGKILLed at ANY state recovers purely from on-disk facts — the
pointer manifest says whether a candidate is baking or promoted, the
``.rejected`` marker says it was quarantined, the replay segments are
still there, and the cooldown stamp still holds the storm controls
closed. Humans only read the obs report.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

from ..io.checkpoint import (_atomic_write_json, bundle_step, is_rejected,
                             promoted_bundle, read_promoted)
from ..utils.metrics import get_stream

__all__ = ["ReplayBuffer", "RouterTee", "RetrainController",
           "build_retrain_stream", "retrain_stub"]

#: the on-disk controller stamp, next to the PROMOTED pointer
_STATE_FILE = "RETRAIN_STATE"
#: replay segments live under <checkpoint_dir>/replay by default
_REPLAY_DIRNAME = "replay"

STATES = ("idle", "triggered", "training", "gating", "canary", "cooldown")


def retrain_stub() -> dict:
    """A fresh copy of the ``retrain`` registry stub (key-for-key mirror
    of :meth:`RetrainController.obs_section`, pinned by
    tests/test_obs.py::test_stub_sections_match_live_providers)."""
    from ..obs.registry import RETRAIN_STUB
    return {**RETRAIN_STUB, "replay": dict(RETRAIN_STUB["replay"])}


# ---------------------------------------------------------------------------
# replay buffer: spill-to-disk ring of labeled traffic
# ---------------------------------------------------------------------------

class ReplayBuffer:
    """Disk ring of recent labeled traffic rows for retrain input.

    ``add(raw_rows, labels)`` buffers rows in memory; every
    ``segment_rows`` rows a segment file (``replay-<seq>.jsonl``: one
    header line + one ``{"f": [...], "y": ...}`` line per row) is
    written atomically (tmp → fsync → ``os.replace`` → dir fsync — the
    checkpoint idiom, so a crash can never leave a torn segment) and the
    ring drops oldest segments beyond ``max_segments``. Readers
    (:meth:`rows` / :meth:`dataset`) see only COMMITTED segments — the
    child retrain process trains on exactly what survives a crash.

    Thread-safe; a tee thread feeds ``add`` while the controller's tick
    thread calls ``flush``/``counters``."""

    def __init__(self, dir: str, *, segment_rows: int = 256,
                 max_segments: int = 8):
        self.dir = dir
        self.segment_rows = int(segment_rows)
        self.max_segments = int(max_segments)
        os.makedirs(dir, exist_ok=True)
        self._lock = threading.Lock()
        self._pending: List[Tuple[list, float]] = []
        self.rows_in = 0
        self.rows_dropped = 0
        self.segments_written = 0
        self.segments_dropped = 0
        # recover the sequence counter from whatever segments survived
        self._seq = 1 + max(
            [self._seq_of(p) for p in self._list()] or [-1])

    @staticmethod
    def _seq_of(path: str) -> int:
        name = os.path.basename(path)
        try:
            return int(name[len("replay-"):-len(".jsonl")])
        except ValueError:
            return -1

    def _list(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = [os.path.join(self.dir, n) for n in names
               if n.startswith("replay-") and n.endswith(".jsonl")]
        return sorted(out, key=self._seq_of)

    # -- write side ----------------------------------------------------------
    def add(self, raw_rows: List[list], labels: List[float]) -> int:
        """Append labeled rows; rows whose label is None are skipped
        (an unjoinable row must not train as label 0). Returns rows
        accepted. Full segments are committed inline."""
        accepted = []
        for row, y in zip(raw_rows, labels):
            if y is None or row is None:
                continue
            accepted.append((list(row), float(y)))
        if not accepted:
            return 0
        with self._lock:
            self._pending.extend(accepted)
            self.rows_in += len(accepted)
            while len(self._pending) >= self.segment_rows:
                chunk = self._pending[:self.segment_rows]
                del self._pending[:self.segment_rows]
                self._write_segment(chunk)
        return len(accepted)

    def flush(self) -> None:
        """Commit any buffered partial segment (called before a retrain
        launches so the child sees every mirrored row)."""
        with self._lock:
            if self._pending:
                chunk, self._pending = self._pending, []
                self._write_segment(chunk)

    def _write_segment(self, chunk: List[Tuple[list, float]]) -> None:
        """Atomic segment commit + ring eviction (caller holds _lock)."""
        path = os.path.join(self.dir, f"replay-{self._seq:08d}.jsonl")
        self._seq += 1
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"rows": len(chunk),
                                    "ts": round(time.time(), 3)}) + "\n")
                for row, y in chunk:
                    f.write(json.dumps({"f": row, "y": y}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        try:  # rename durability — the checkpoint idiom's dir fsync
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        self.segments_written += 1
        segs = self._list()
        for old in segs[:max(0, len(segs) - self.max_segments)]:
            dropped = self._segment_rows(old)
            try:
                os.remove(old)
            except OSError:
                continue
            self.segments_dropped += 1
            self.rows_dropped += dropped

    @staticmethod
    def _segment_rows(path: str) -> int:
        try:
            with open(path) as f:
                return int(json.loads(f.readline()).get("rows") or 0)
        except (OSError, ValueError):
            return 0

    # -- read side -----------------------------------------------------------
    def rows(self) -> List[Tuple[list, float]]:
        """Every committed row, oldest segment first. A torn line (only
        possible through external corruption — commits are atomic) is
        skipped, never raised."""
        out: List[Tuple[list, float]] = []
        for path in self._list():
            try:
                with open(path) as f:
                    f.readline()                 # header
                    for line in f:
                        try:
                            rec = json.loads(line)
                            out.append((rec["f"], float(rec["y"])))
                        except (ValueError, KeyError, TypeError):
                            continue
            except OSError:
                continue
        return out

    def dataset(self, trainer):
        """Committed rows parsed through the TRAINER'S OWN row parser
        (the same hashing serving uses) into a SparseDataset — or None
        when the buffer is empty."""
        from ..io.sparse import SparseDataset
        rows = self.rows()
        if not rows:
            return None
        parsed, labels, fields = [], [], []
        has_fields = False
        for feats, y in rows:
            p = trainer._parse_row(feats)
            if len(p) == 3:              # FFM-style (idx, val, field)
                has_fields = True
                parsed.append((p[0], p[1]))
                fields.append(p[2])
            else:
                parsed.append(p)
                fields.append(None)
            labels.append(y)
        return SparseDataset.from_rows(
            parsed, labels, fields=fields if has_fields else None)

    def counters(self) -> dict:
        with self._lock:
            pending = len(self._pending)
        return {"rows": self.rows_in,
                "rows_dropped": self.rows_dropped,
                "segments": len(self._list()),
                "pending_rows": pending}


class RouterTee:
    """Bounded non-blocking intake of raw ``/predict`` bodies on router
    connection threads — the fleet-mode traffic source for the replay
    buffer (the manager process never sees parsed rows; the router sees
    every request body). At capacity the oldest body is evicted
    (counted), so a stalled controller can never backpressure the
    serving path."""

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._q: deque = deque(maxlen=self.capacity)
        self.teed = 0
        self.dropped = 0

    def __call__(self, body: bytes) -> None:
        with self._lock:
            if len(self._q) >= self.capacity:
                self.dropped += 1
            self._q.append(bytes(body))
            self.teed += 1

    def drain(self) -> List[bytes]:
        with self._lock:
            out = list(self._q)
            self._q.clear()
        return out

    @staticmethod
    def rows_of(body: bytes) -> List[list]:
        """Feature-string rows out of one ``/predict`` body (the same
        shapes the HTTP handler accepts); malformed bodies yield []."""
        try:
            obj = json.loads(body or b"{}")
            rows = obj.get("rows")
            if rows is None:
                feats = obj.get("features")
                rows = [feats] if feats is not None else []
            return [r for r in rows if isinstance(r, list)]
        except (ValueError, TypeError, AttributeError):
            return []


# ---------------------------------------------------------------------------
# retrain input stream: shard-cache-backed base corpus ∪ replay buffer
# ---------------------------------------------------------------------------

def _load_base(trainer, base):
    """The base corpus as a dataset/stream: a SparseDataset passes
    through; a directory becomes a ParquetStream wired to the trainer's
    ``-shard_cache_dir`` (warm traversals mmap the PR 6 decode cache
    instead of re-reading Parquet); a file reads as LIBSVM."""
    if base is None:
        return None
    if not isinstance(base, str):
        return base                      # dataset-like: has .batches
    kw = dict(dims=getattr(trainer, "dims", None))
    if getattr(trainer, "F", None) is not None \
            and trainer.NAME == "train_ffm":
        kw.update(ffm=True, num_fields=trainer.F)
    if os.path.isdir(base):
        from ..io.arrow import ParquetStream
        opts = getattr(trainer, "opts", None)
        cache_dir = opts.get("shard_cache_dir") if opts is not None else None
        return ParquetStream(base, cache_dir=cache_dir, **kw)
    from ..io.libsvm import read_libsvm
    return read_libsvm(base, **kw)


def build_retrain_stream(trainer, *, base=None, replay_dir: Optional[str]
                         = None, batch_size: int = 64, epochs: int = 1):
    """The retrain input: base-corpus batches (through the shard caches
    when configured) followed by replay-buffer batches, DETERMINISTIC
    (no shuffle) so a retrain over the same on-disk inputs is bit-
    reproducible — the warm-start fidelity contract tests/test_retrain
    pins at ``-steps_per_dispatch`` 1 and 8. Returns (stream, n_rows);
    n_rows == 0 means there is nothing to train on."""
    import itertools
    parts = []
    n_rows = 0
    ds = _load_base(trainer, base)
    if ds is not None:
        n_rows += len(ds) * max(1, int(epochs))
        parts.append(ds.batches(int(batch_size), epochs=max(1, int(epochs)),
                                shuffle=False))
    if replay_dir:
        rds = ReplayBuffer(replay_dir).dataset(trainer)
        if rds is not None:
            n_rows += len(rds) * max(1, int(epochs))
            parts.append(rds.batches(int(batch_size),
                                     epochs=max(1, int(epochs)),
                                     shuffle=False))
    return itertools.chain(*parts), n_rows


# ---------------------------------------------------------------------------
# supervised child: one retrain attempt in its own process
# ---------------------------------------------------------------------------

def _child(spec_json: str) -> int:
    """One retrain attempt: fresh trainer, warm-started from the
    promoted bundle, fit over base ∪ replay, candidate bundle saved
    atomically into the checkpoint dir (where the gate watches). Prints
    ONE json result line. Isolated in a child process so a diverging
    retrain (OOM, wedged compile, poisoned data) can be killed by the
    supervising controller without taking serving down."""
    spec = json.loads(spec_json)
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and "axon" not in want:
        import jax
        jax.config.update("jax_platforms", want)
    from ..catalog import lookup
    cls = lookup(spec["algo"]).resolve()
    trainer = cls(spec.get("options") or "")
    trainer.load_bundle(spec["warm_bundle"])
    start_step = int(getattr(trainer, "_t", 0))
    t0 = time.monotonic()
    stream, n_rows = build_retrain_stream(
        trainer, base=spec.get("train_input"),
        replay_dir=spec.get("replay_dir"),
        batch_size=int(spec.get("batch_size") or 64),
        epochs=int(spec.get("epochs") or 1))
    if n_rows == 0:
        print(json.dumps({"ok": False, "error": "no training data "
                          "(empty replay buffer and no train_input)"}),
              flush=True)
        return 1
    trainer.fit_stream(stream)
    step = int(getattr(trainer, "_t", 0))
    if step <= start_step:
        print(json.dumps({"ok": False, "error": "no steps advanced"}),
              flush=True)
        return 1
    path = os.path.join(spec["checkpoint_dir"],
                        f"{trainer.NAME}-step{step:010d}.npz")
    trainer.save_bundle(path)            # atomic: the gate never sees a
    print(json.dumps({                   # torn candidate
        "ok": True, "bundle": os.path.basename(path), "step": step,
        "warm_step": start_step, "rows": n_rows,
        "seconds": round(time.monotonic() - t0, 3)}), flush=True)
    return 0


# env vars that must never leak into the retrain child (the TPU-tunnel
# relay is single-client; same scrub the fleet applies to replicas)
_SCRUB_ENV = ("PALLAS_AXON_POOL_IPS",)


class RetrainController:
    """Drift votes in, gated candidates out — with storm controls.

    The controller is DATA-PLANE-FREE: it never touches a live scorer.
    It consumes cumulative ``retrain_wanted`` vote counts (``slo=``
    in-process, or ``votes_fn=`` for a remote ``/slo`` poller), drains
    traffic tees into the :class:`ReplayBuffer`, launches at most ONE
    supervised child retrain at a time, and then watches the on-disk
    promotion protocol (pointer manifest + ``.rejected`` markers) to
    learn the candidate's fate — which is also exactly what makes a
    controller restart free: every decision input is on disk.

    Debounce + storm controls, all enforced before a trigger:

    - ``min_votes`` fresh votes within ``vote_window_s``;
    - the shared DriftWatch flap detector over the per-tick vote rate —
      a vote STORM (changefinder flapping) extends the holdoff instead
      of feeding it;
    - per-model ``cooldown_s`` after every attempt, multiplied by
      ``backoff_factor`` per CONSECUTIVE gate rejection (capped at
      ``max_backoff_s``) — a bad data regime decays to near-silence;
    - at most ``max_retrains_per_window`` triggers per ``window_s``;
    - a concurrent-retrain budget of exactly 1 (the single child).

    ``tick()`` is re-entrant-free and cheap; the fleet manager calls it
    from its watch loop, a standalone controller runs it on its own
    daemon thread (:meth:`start`)."""

    def __init__(self, algo: str, options: str = "", *,
                 checkpoint_dir: str,
                 slo=None,
                 votes_fn: Optional[Callable[[], int]] = None,
                 shadow=None,
                 router_tee: Optional[RouterTee] = None,
                 label_fn: Optional[Callable] = None,
                 replay_dir: Optional[str] = None,
                 replay_segment_rows: int = 256,
                 replay_max_segments: int = 8,
                 train_input: Optional[str] = None,
                 gate=None,
                 batch_size: int = 64,
                 epochs: int = 1,
                 min_votes: int = 1,
                 vote_window_s: float = 300.0,
                 cooldown_s: float = 60.0,
                 window_s: float = 3600.0,
                 max_retrains_per_window: int = 4,
                 backoff_factor: float = 2.0,
                 max_backoff_s: float = 3600.0,
                 train_timeout_s: float = 900.0,
                 gate_timeout_s: float = 600.0,
                 interval: float = 2.0,
                 flap_sigma: float = 6.0,
                 flap_warmup: int = 16,
                 env: Optional[dict] = None):
        from ..catalog import lookup
        self.algo = algo
        self.options = options
        self.checkpoint_dir = checkpoint_dir
        self._name = lookup(algo).resolve().NAME
        self.slo = slo
        self._votes_fn = votes_fn
        self.shadow = shadow             # ShadowBuffer w/ raw capture
        self.router_tee = router_tee
        self.label_fn = label_fn
        self.train_input = train_input
        self.gate = gate                 # own gate (CLI --once); a fleet
        self.batch_size = int(batch_size)   # manager/controller gates
        self.epochs = int(epochs)           # externally when None
        self.min_votes = max(1, int(min_votes))
        self.vote_window_s = float(vote_window_s)
        self.cooldown_s = float(cooldown_s)
        self.window_s = float(window_s)
        self.max_retrains_per_window = int(max_retrains_per_window)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.train_timeout_s = float(train_timeout_s)
        self.gate_timeout_s = float(gate_timeout_s)
        self.interval = float(interval)
        self.env = env
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.replay = ReplayBuffer(
            replay_dir or os.path.join(checkpoint_dir, _REPLAY_DIRNAME),
            segment_rows=replay_segment_rows,
            max_segments=replay_max_segments)
        # vote flap detector: the shared dual-stage changefinder wrapper
        # over the PER-TICK vote arrival rate — a storming changefinder
        # upstream (votes every tick) flags here and HOLDS OFF triggers
        # instead of hammering the trainer
        from ..obs.devprof import DriftWatch
        self.flap_watch = DriftWatch("retrain_votes", "retrain_flap",
                                     sigma=flap_sigma, warmup=flap_warmup)
        self._lock = threading.Lock()
        self.state = "idle"
        self.attempts = 0
        self.successes = 0
        self.rejections = 0
        self.rollbacks = 0
        self.flaps = 0
        self.votes_seen = 0
        self.votes_acked = 0
        self.last_trigger_reason: Optional[str] = None
        self.last_error: Optional[str] = None
        self._consecutive_rejections = 0
        self._candidate: Optional[dict] = None   # {"bundle","step"}
        self._child: Optional[subprocess.Popen] = None
        self._child_reader: Optional[threading.Thread] = None
        self._child_out: List[str] = []
        self._child_since: Optional[float] = None     # monotonic
        self._phase_since = time.monotonic()          # gating watchdog
        self._cooldown_until = 0.0                    # monotonic
        self._flap_until = 0.0                        # monotonic
        self._window: List[float] = []                # monotonic triggers
        self._recent_votes: deque = deque()           # (mono, n)
        self._last_total: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._load_state()
        self._register_obs()

    # -- durable state -------------------------------------------------------
    def _state_path(self) -> str:
        return os.path.join(self.checkpoint_dir, _STATE_FILE)

    def _save_state(self) -> None:
        """Persist the storm-control stamp (atomic json, the checkpoint
        idiom). Timestamps are WALL clock on disk — they must mean the
        same thing to the next process — and are re-anchored onto the
        monotonic clock at load."""
        now_wall = time.time()
        now_mono = time.monotonic()
        rec = {
            "state": self.state,
            "attempts": self.attempts,
            "successes": self.successes,
            "rejections": self.rejections,
            "rollbacks": self.rollbacks,
            "votes_acked": self.votes_acked,
            "consecutive_rejections": self._consecutive_rejections,
            "candidate": self._candidate,
            "last_trigger_reason": self.last_trigger_reason,
            # deliberate wall anchors: on-disk stamps must mean the same
            # thing to the NEXT process (load re-anchors onto monotonic)
            "cooldown_until_ts": round(
                now_wall  # graftcheck: disable=GC02
                + max(0.0, self._cooldown_until - now_mono), 3),
            "window_ts": [round(now_wall - (now_mono - t),  # graftcheck: disable=GC02
                                3) for t in self._window],
            "ts": round(now_wall, 3),
        }
        _atomic_write_json(self._state_path(), rec)

    def _load_state(self) -> None:
        try:
            with open(self._state_path()) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(rec, dict):
            return
        self.attempts = int(rec.get("attempts") or 0)
        self.successes = int(rec.get("successes") or 0)
        self.rejections = int(rec.get("rejections") or 0)
        self.rollbacks = int(rec.get("rollbacks") or 0)
        self.votes_acked = int(rec.get("votes_acked") or 0)
        self._consecutive_rejections = int(
            rec.get("consecutive_rejections") or 0)
        self.last_trigger_reason = rec.get("last_trigger_reason")
        cand = rec.get("candidate")
        self._candidate = cand if isinstance(cand, dict) else None
        # re-anchor wall stamps onto this process's monotonic clock: the
        # on-disk record must survive restarts (wall), runtime compares
        # must survive NTP steps (monotonic)
        now_mono = time.monotonic()
        now_wall = time.time()  # graftcheck: disable=GC02
        until = float(rec.get("cooldown_until_ts") or 0.0)
        self._cooldown_until = \
            now_mono + max(0.0, until - now_wall)  # graftcheck: disable=GC02
        self._window = [now_mono - max(0.0, now_wall - float(t))  # graftcheck: disable=GC02
                        for t in rec.get("window_ts") or []]
        state = rec.get("state")
        # crash recovery: land in whichever state the DISK supports.
        # "training" cannot survive (the child died with us): if its
        # candidate already landed, resume watching the gate; otherwise
        # the attempt is lost — cooldown (stamp already loaded) or idle.
        if state in ("triggered", "training"):
            cand_path = self._candidate_path()
            if cand_path and os.path.exists(cand_path):
                self.state = "gating"
            else:
                self._candidate = None
                self.state = ("cooldown" if self._cooldown_until > now_mono
                              else "idle")
                self.last_error = "recovered: retrain child lost to a " \
                                  "controller crash"
        elif state in ("gating", "canary"):
            self.state = state if self._candidate else "idle"
        elif state == "cooldown":
            self.state = ("cooldown" if self._cooldown_until > now_mono
                          else "idle")
        self._phase_since = now_mono

    def _candidate_path(self) -> Optional[str]:
        if not self._candidate:
            return None
        return os.path.join(self.checkpoint_dir,
                            str(self._candidate["bundle"]))

    def _set_state(self, state: str, **event) -> None:
        with self._lock:
            prev, self.state = self.state, state
            self._phase_since = time.monotonic()
        self._save_state()
        # retrain state edges go to the flight ring too: "the autopilot
        # was mid-<state> when the manager died" is exactly what a
        # post-mortem of a wedged retrain needs
        from ..obs.flight import get_flight
        fl = get_flight()
        if fl.enabled:
            fl.record("retrain.state", state=state, prev=prev)
        if event.pop("emit", True):
            get_stream().emit("retrain", state=state, prev=prev, **event)

    # -- vote intake ---------------------------------------------------------
    def _votes_total(self) -> int:
        if self._votes_fn is not None:
            try:
                return int(self._votes_fn())
            except Exception as e:       # noqa: BLE001 — a dead /slo
                self.last_error = f"votes: {type(e).__name__}: {e}"
                return self.votes_seen   # source must not kill the loop
        return int(getattr(self.slo, "retrain_wanted", 0) or 0)

    def _observe_votes(self, now: float) -> int:
        """Fold the cumulative vote counter into the recency window and
        the flap detector; returns votes pending (fresh, unacked). The
        DURABLE ``votes_acked`` ledger (in the state stamp) is what
        prevents answered votes from re-firing across controller
        restarts — on first sight everything above it is honestly
        pending drift the autopilot has never answered."""
        total = self._votes_total()
        prev = self._last_total
        if prev is not None and total < prev:
            # the serve process restarted (counter reset): re-baseline —
            # votes already counted must not replay
            self._last_total = total
            self.votes_seen = total
            self._recent_votes.clear()
            if total < self.votes_acked:
                self.votes_acked = total
            return 0
        delta = (total - prev if prev is not None
                 else max(0, total - self.votes_acked))
        self._last_total = total
        self.votes_seen = total
        if delta > 0:
            self._recent_votes.append((now, delta))
        ev = self.flap_watch.update(float(delta))
        if ev is not None:
            with self._lock:
                self.flaps += 1
            self._flap_until = now + self.cooldown_s
        while self._recent_votes and \
                now - self._recent_votes[0][0] > self.vote_window_s:
            self._recent_votes.popleft()
        recent = sum(n for _, n in self._recent_votes)
        return min(recent, max(0, total - self.votes_acked))

    def _ack_votes(self) -> int:
        """Consume every pending vote (they're answered by this
        retrain): bump the SLO engine's ``retrain_acked`` so the obs
        surface distinguishes votes from actions."""
        total = self.votes_seen
        n = max(0, total - self.votes_acked)
        self.votes_acked = total
        self._recent_votes.clear()
        if n and self.slo is not None \
                and hasattr(self.slo, "ack_retrain"):
            self.slo.ack_retrain(n)
        elif n:
            get_stream().emit("retrain_acked", count=n, total=total)
        return n

    # -- traffic tees → replay -----------------------------------------------
    def _drain_tees(self) -> None:
        if self.shadow is not None and hasattr(self.shadow,
                                               "drain_labeled"):
            rows, labels = self.shadow.drain_labeled()
            if rows:
                self.replay.add(rows, labels)
        if self.router_tee is not None:
            bodies = self.router_tee.drain()
            if bodies and self.label_fn is not None:
                rows: List[list] = []
                for b in bodies:
                    rows.extend(RouterTee.rows_of(b))
                if rows:
                    labels = [self._label(r) for r in rows]
                    self.replay.add(rows, labels)

    def _label(self, row: list):
        try:
            return self.label_fn(row)
        except Exception:                # noqa: BLE001 — an unjoinable
            return None                  # row is skipped, never poison

    # -- the tick ------------------------------------------------------------
    def tick(self) -> None:
        """One control step; safe to call from any single loop (the
        fleet manager's watch tick, or this controller's own thread)."""
        now = time.monotonic()
        self._drain_tees()
        self._poll_child(now)
        # votes are observed EVERY tick (the flap detector needs the
        # honest per-tick arrival rate, not a lump when idle resumes);
        # only the idle state may act on them
        pending = self._observe_votes(now)
        state = self.state
        if state == "training":
            return                       # child alive; _poll_child watches
        if state in ("gating", "canary"):
            self._watch_candidate(now)
            return
        if state == "cooldown":
            if now < self._cooldown_until:
                return
            self._set_state("idle", emit=False)   # expired: fall through
        # idle: debounce votes through the storm controls
        if pending < self.min_votes:
            return
        if now < self._cooldown_until:
            return                       # per-model cooldown holds
        if now < self._flap_until:
            return                       # flap detector holds
        self._window = [t for t in self._window
                        if now - t <= self.window_s]
        if len(self._window) >= self.max_retrains_per_window:
            self.last_error = (f"retrain budget exhausted "
                               f"({self.max_retrains_per_window} per "
                               f"{self.window_s:.0f}s window)")
            return
        if self._child is not None:
            return                       # concurrent-retrain budget: 1
        self.trigger(f"{pending} drift vote(s) within "
                     f"{self.vote_window_s:.0f}s")

    def trigger(self, reason: str) -> bool:
        """Launch one supervised retrain now (the debounced path calls
        this; ``retrain --once`` calls it directly). Returns False when
        there is no promoted bundle to warm-start from or no data."""
        pb = promoted_bundle(self.checkpoint_dir, self._name)
        if pb is None:
            self.last_error = "no PROMOTED bundle to warm-start from"
            return False
        self.replay.flush()
        if not self.train_input and not self.replay.rows():
            self.last_error = "no training data (empty replay buffer " \
                              "and no train_input)"
            return False
        self._ack_votes()
        with self._lock:
            self.attempts += 1
            self.last_trigger_reason = reason
        self._window.append(time.monotonic())
        self._set_state("triggered", reason=reason, warm_step=pb[0])
        self._launch(pb[1])
        self._set_state("training", warm_step=pb[0], emit=False)
        # an already-exited child (a failed exec, or a test stand-in)
        # resolves on the triggering tick instead of waiting one interval
        self._poll_child(time.monotonic())
        return True

    # -- child supervision ---------------------------------------------------
    def _spec(self, warm_bundle: str) -> dict:
        return {"algo": self.algo, "options": self.options,
                "checkpoint_dir": self.checkpoint_dir,
                "warm_bundle": warm_bundle,
                "train_input": self.train_input,
                "replay_dir": self.replay.dir,
                "batch_size": self.batch_size, "epochs": self.epochs}

    def _launch(self, warm_bundle: str) -> None:
        env = dict(os.environ)
        for k in _SCRUB_ENV:
            env.pop(k, None)
        for k, v in (self.env or {}).items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = str(v)
        proc = subprocess.Popen(
            [sys.executable, "-m", "hivemall_tpu.serve.retrain",
             "--child", json.dumps(self._spec(warm_bundle))],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            env=env)
        out: List[str] = []
        # _child is written by the tick thread here AND by stop() on the
        # owner's thread (a stop racing a slow tick is legal), so every
        # write takes the controller lock
        with self._lock:
            self._child = proc
            self._child_out = out
            self._child_since = time.monotonic()

        def read():
            try:
                for line in proc.stdout:
                    out.append(line)
            except Exception:            # noqa: BLE001 — pipe teardown
                pass

        reader = threading.Thread(target=read, name="retrain-child-out",
                                  daemon=True)
        with self._lock:
            self._child_reader = reader
        reader.start()

    def _poll_child(self, now: float) -> None:
        child = self._child
        if child is None:
            return
        rc = child.poll()
        if rc is None:
            if self._child_since is not None \
                    and now - self._child_since > self.train_timeout_s:
                child.terminate()
                try:
                    child.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    child.kill()
                with self._lock:
                    self._child = None
                self._attempt_failed("retrain child timed out after "
                                     f"{self.train_timeout_s:.0f}s")
            return
        with self._lock:
            self._child = None
            reader = self._child_reader
        if reader is not None:
            # the child can exit the instant after printing its result:
            # let the pipe reader drain to EOF before parsing, or a
            # successful retrain could misread as a no-result failure
            reader.join(timeout=5.0)
        result = None
        for line in reversed(self._child_out):
            try:
                result = json.loads(line)
                break
            except ValueError:
                continue
        if rc != 0 or not isinstance(result, dict) \
                or not result.get("ok"):
            err = (result or {}).get("error") or f"child exit rc={rc}"
            self._attempt_failed(f"retrain failed: {err}")
            return
        with self._lock:
            self._candidate = {"bundle": result["bundle"],
                               "step": int(result["step"])}
        self._set_state("gating", bundle=result["bundle"],
                        step=result["step"], rows=result.get("rows"),
                        seconds=result.get("seconds"))
        if self.gate is not None:
            self._gate_own()

    def _attempt_failed(self, reason: str) -> None:
        self.last_error = reason
        self._enter_cooldown(self.cooldown_s)
        get_stream().emit("retrain", state="cooldown", outcome="failed",
                          reason=reason)

    # -- candidate fate ------------------------------------------------------
    def _gate_own(self) -> None:
        """CLI standalone mode (``retrain --once`` with a holdout): gate
        the candidate ourselves and flip/quarantine like the promotion
        controller would."""
        from ..io.checkpoint import promote_bundle, reject_bundle
        from .promote import _gate_summary
        path = self._candidate_path()
        pb = promoted_bundle(self.checkpoint_dir, self._name)
        report = self.gate.evaluate(path, pb[1] if pb else None)
        if report["verdict"] == "pass":
            promote_bundle(self.checkpoint_dir, path,
                           gate=_gate_summary(report), state="serving")
            get_stream().emit("promotion",
                              bundle=os.path.basename(path),
                              step=report["step"], state="serving")
            self._candidate_promoted()
        else:
            reject_bundle(path, "; ".join(report["reasons"]))
            self._candidate_rejected("; ".join(report["reasons"]))

    def _watch_candidate(self, now: float) -> None:
        """gating/canary: learn the candidate's fate purely from disk —
        the ``.rejected`` marker and the pointer manifest (which is what
        makes SIGKILL-anywhere recovery free)."""
        path = self._candidate_path()
        if path is None:
            self._set_state("idle", emit=False)
            return
        step = int(self._candidate["step"])
        if is_rejected(path):
            if self.state == "canary":
                with self._lock:
                    self.rollbacks += 1
                self._candidate_rejected("canary rolled back",
                                         rolled_back=True)
            else:
                from ..io.checkpoint import rejected_reason
                self._candidate_rejected(rejected_reason(path)
                                         or "gate rejected")
            return
        m = read_promoted(self.checkpoint_dir)
        cur = (m or {}).get("current") or {}
        cur_step = int(cur.get("step") or -1)
        if cur_step == step:
            if (m or {}).get("state") == "canary":
                if self.state != "canary":
                    self._set_state("canary", step=step)
            else:
                self._candidate_promoted()
            return
        if cur_step > step:
            # a newer promotion superseded our candidate while it waited
            self._candidate_done("superseded", outcome="superseded")
            return
        if now - self._phase_since > self.gate_timeout_s:
            self._candidate_done(
                f"no gate verdict within {self.gate_timeout_s:.0f}s",
                outcome="gate_timeout")

    def _candidate_promoted(self) -> None:
        with self._lock:
            self.successes += 1
            self._consecutive_rejections = 0
        # votes that arrived WHILE this retrain ran were votes against
        # the model it just replaced — answered, so acked; a rejection
        # leaves them pending (they retry once the backoff lapses)
        self._ack_votes()
        self._candidate_done("promoted", outcome="promoted",
                             cooldown=self.cooldown_s)

    def _candidate_rejected(self, reason: str,
                            rolled_back: bool = False) -> None:
        with self._lock:
            self.rejections += 1
            self._consecutive_rejections += 1
            k = self._consecutive_rejections
        cool = min(self.max_backoff_s,
                   self.cooldown_s * (self.backoff_factor ** k))
        self._candidate_done(reason,
                             outcome="rolled_back" if rolled_back
                             else "rejected", cooldown=cool)

    def _candidate_done(self, reason: str, *, outcome: str,
                        cooldown: Optional[float] = None) -> None:
        bundle = (self._candidate or {}).get("bundle")
        with self._lock:
            self._candidate = None
        if outcome not in ("promoted",):
            self.last_error = reason
        self._enter_cooldown(cooldown if cooldown is not None
                             else self.cooldown_s)
        get_stream().emit("retrain", state="cooldown", outcome=outcome,
                          reason=reason, bundle=bundle)

    def _enter_cooldown(self, seconds: float) -> None:
        self._cooldown_until = time.monotonic() + max(0.0, seconds)
        self._set_state("cooldown", emit=False)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RetrainController":
        """Self-ticking daemon thread (standalone / single-server mode;
        the fleet manager ticks in its own watch loop instead)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.tick()
                except Exception as e:   # noqa: BLE001 — the autopilot
                    self.last_error = f"{type(e).__name__}: {e}"   # must
                    #                    outlive any one bad tick

        self._thread = threading.Thread(target=run, name="retrain-ctl",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            child, self._child = self._child, None
        if child is not None and child.poll() is None:
            child.terminate()
            try:
                child.wait(timeout=5)
            except subprocess.TimeoutExpired:
                child.kill()

    def wait_idle(self, timeout: float = 60.0) -> bool:
        """Block until the controller leaves the active states (test /
        --once helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.state in ("idle", "cooldown"):
                return True
            self.tick()
            time.sleep(0.1)
        return False

    # -- obs -----------------------------------------------------------------
    def status(self) -> dict:
        """The ``retrain --status`` payload: the live section plus the
        on-disk stamp and pointer context."""
        out = {"section": self.obs_section()}
        try:
            with open(self._state_path()) as f:
                out["stamp"] = json.load(f)
        except (OSError, ValueError):
            out["stamp"] = None
        out["promoted"] = read_promoted(self.checkpoint_dir)
        return out

    def obs_section(self) -> dict:
        with self._lock:
            cand = dict(self._candidate) if self._candidate else None
            state = self.state
        now = time.monotonic()
        d = retrain_stub()
        d.update({
            "configured": True,
            "state": state,
            "attempts": self.attempts,
            "successes": self.successes,
            "rejections": self.rejections,
            "rollbacks": self.rollbacks,
            "flaps": self.flaps,
            "votes_seen": self.votes_seen,
            "votes_acked": self.votes_acked,
            "cooldown_remaining_s": round(
                max(0.0, self._cooldown_until - now), 1),
            "child_alive": self._child is not None,
            "candidate_step": (cand or {}).get("step"),
            "last_trigger_reason": self.last_trigger_reason,
            "last_error": self.last_error,
            "replay": self.replay.counters(),
        })
        return d

    def _register_obs(self) -> None:
        import weakref
        from ..obs.registry import registry
        ref = weakref.ref(self)

        def retrain() -> dict:
            c = ref()
            return c.obs_section() if c is not None else retrain_stub()

        registry.register("retrain", retrain)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="hivemall_tpu.serve.retrain")
    ap.add_argument("--child", metavar="SPEC_JSON",
                    help="run one retrain attempt from a json spec "
                         "(internal: spawned by RetrainController)")
    args = ap.parse_args(argv)
    if args.child:
        return _child(args.child)
    ap.error("only --child mode is runnable directly; use "
             "`hivemall_tpu retrain` for the controller")
    return 2


if __name__ == "__main__":
    sys.exit(main())
