"""Dynamic micro-batcher — Clipper-style request coalescing.

Concurrent ``/predict`` requests land here and are coalesced into one
bucketed predict call: the dispatch thread waits up to ``max_delay_ms``
past the FIRST queued request (or until ``max_batch`` rows are queued)
then scores everything waiting in one batch — at low load a request pays
at most the delay bound, at high load batches fill instantly and
amortize dispatch overhead across the whole batch.

Overload is handled by FAILING FAST, not queue collapse: the queue is
bounded at ``max_queue_rows`` and a submit that would exceed it is shed
immediately with :class:`ServeOverload` (HTTP 503) — a client sees the
rejection in microseconds instead of a timeout, and the queue can never
grow a latency backlog that outlives the burst. Each request may also
carry a deadline; a request whose deadline passed while queued is
completed with :class:`ServeDeadline` (HTTP 504) instead of wasting a
batch slot on an answer nobody is waiting for.

Requests are never split across batches (a request's rows score
together, on one model version); a single request larger than
``max_batch`` rows is admitted alone as an oversized batch.

:class:`BatchPlane` is the stats/SLO/tee surface shared by BOTH serving
planes — this threaded ``MicroBatcher`` and the event-loop inline
assembler (``serve.evloop.InlineAssembler``).  Everything downstream
(the obs ``serve`` section, the SLO engine's totals, the promotion
shadow tee, the retrain replay tee) programs against the base class, so
the planes cannot drift apart on observability or the tee contracts.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..io.sparse import pow2_len
from ..obs.flight import FS, get_flight, pack_ids
from ..obs.histo import BATCH_SIZE_BUCKETS, LATENCY_BUCKETS_S, Histogram
from ..obs.trace import get_tracer
from ..utils.metrics import Meter

__all__ = ["BatchPlane", "MicroBatcher", "ServeOverload", "ServeDeadline"]


class ServeOverload(RuntimeError):
    """Queue full — request shed (fail-fast backpressure, HTTP 503)."""
    status = 503


class ServeDeadline(RuntimeError):
    """Request deadline expired while queued (HTTP 504)."""
    status = 504


@dataclass
class _Req:
    rows: list
    n: int
    fut: Future
    t_enq: float
    t_deadline: Optional[float]
    trace_id: Optional[str] = None
    raw: Optional[list] = None           # original feature strings (the
    #                                      raw-capturing tee's input)
    req_no: int = 0                      # plane-local admission number —
    #                                      the flight recorder's
    #                                      admit/complete correlation key


class BatchPlane:
    """Counters, histograms, score moments, SLO totals and the traffic
    tee — the plane-independent half of request batching.  Subclasses
    own the actual coalescing machinery (queue + dispatch thread here;
    inline assembly on the event loop in serve.evloop) and call the
    ``_note_*`` helpers as batches score."""

    def _init_plane(self, max_batch: int, max_delay_ms: float,
                    max_queue_rows: Optional[int],
                    deadline_ms: float) -> None:
        self.max_batch = int(max_batch)
        self.max_delay = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows
                                  if max_queue_rows is not None
                                  else 8 * self.max_batch)
        self.deadline_ms = float(deadline_ms)
        self._tracer = get_tracer()
        # black-box flight recorder (obs.flight): BOTH planes record
        # admit/complete/shed wide events through this shared base, so
        # the crash-safe story cannot drift between them. Every hot site
        # guards with `if fl.enabled:` — the disabled plane pays one
        # attribute check per seam, nothing more.
        self._flight = get_flight()
        self._queued_rows = 0
        # counters (merged into the obs `serve` section by the engine)
        self.requests = 0
        self.rows_in = 0
        self.batches = 0
        self.batch_rows_sum = 0
        self.coalesced_sum = 0          # requests folded into batches
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.batch_hist: Dict[int, int] = {}   # pow2 rows-bucket -> count
        # real Prometheus histograms (docs/OBSERVABILITY.md "Serving
        # traces and SLOs"): cumulative, so external scrapers can window
        # them and the SLO engine can diff two snapshots
        self.latency_hist = Histogram(LATENCY_BUCKETS_S)   # enqueue->scored
        self.batch_size_hist = Histogram(BATCH_SIZE_BUCKETS)
        # cumulative prediction-score moments (fleet-summable; the SLO
        # engine's score-drift changefinder reads mean/std off these)
        self.score_sum = 0.0
        self.score_sumsq = 0.0
        self.score_n = 0
        # traffic mirror (serve.promote.ShadowBuffer): called with each
        # successfully scored batch's rows AFTER the request completions
        # resolve — a shadow consumer rides the scoring tail, never the
        # request path
        self._tee = None
        self._req_meter = Meter()
        self._row_meter = Meter()

    @property
    def queue_depth(self) -> int:
        return 0

    # -- scoring-side bookkeeping (called by the owning plane) ---------------
    def _note_batch(self, n_rows: int, n_reqs: int, scores) -> None:
        """One successfully scored batch of ``n_rows`` rows coalesced
        from ``n_reqs`` requests."""
        self.batches += 1
        self.batch_rows_sum += n_rows
        self.coalesced_sum += n_reqs
        b = pow2_len(n_rows)
        self.batch_hist[b] = self.batch_hist.get(b, 0) + 1
        self.batch_size_hist.observe(n_rows)
        self._row_meter.add(n_rows)
        self._note_scores(scores, n_rows)

    def _note_scores(self, scores, n: int) -> None:
        sc = np.asarray(scores[:n], np.float64)
        self.score_sum += float(sc.sum())
        self.score_sumsq += float((sc * sc).sum())
        self.score_n += n

    def _flight_batch_done(self, live: list, n_rows: int,
                           assemble_s: float, predict_s: float,
                           meta) -> None:
        """One ``batch.done`` wide event naming every request this batch
        completed (packed id ranges) — per-request completion cost in the
        ring amortizes across the batch. Callers guard on
        ``self._flight.enabled`` so the disabled path never gets here."""
        line = (f"reqs={pack_ids([r.req_no for r in live])}{FS}"
                f"rows={n_rows}{FS}a={assemble_s * 1e3:.2f}{FS}"
                f"p={predict_s * 1e3:.2f}")
        if meta is not None:
            line += f"{FS}step={meta}"
        self._flight.record("batch.done", line)

    def _tee_batch(self, rows: list, reqs: list) -> None:
        """Mirror one scored batch to the installed tee. ``reqs`` need
        ``.n`` and ``.raw`` (both planes' request records carry them)."""
        tee = self._tee
        if tee is None:
            return
        fn, want_raw = tee
        try:                       # mirror AFTER the completions resolved:
            if want_raw:           # zero added request latency
                # raw strings aligned row-for-row with `rows`; requests
                # submitted without raw pad with None so a raw-capturing
                # consumer stays aligned
                fn(rows, [s for r in reqs for s in
                          (r.raw if r.raw is not None
                           and len(r.raw) == r.n
                           else [None] * r.n)])
            else:
                fn(rows)
        except Exception:          # noqa: BLE001 — a shadow consumer
            pass                   # must never touch the scoring path

    def set_tee(self, fn, raw: bool = False) -> None:
        """Install (or clear, with None) a traffic mirror: ``fn(rows)``
        is called with every successfully scored batch's parsed rows off
        the scoring tail — the promotion gate's shadow-scoring input
        (serve.promote.ShadowBuffer.add). ``raw=True`` calls
        ``fn(rows, raws)`` instead, where ``raws`` are the original
        request feature strings (None-padded for requests submitted
        without them) — the replay-buffer tee (serve.retrain)."""
        self._tee = None if fn is None else (fn, bool(raw))

    # -- stats surface -------------------------------------------------------
    def stats(self) -> dict:
        """JSON-ready counters for the obs ``serve`` section."""
        return {
            "qps": round(self._req_meter.rate, 1),
            "rows_per_sec": round(self._row_meter.rate, 1),
            "queue_depth": self.queue_depth,
            "queued_rows": self._queued_rows,
            "requests": self.requests,
            "rows": self.rows_in,
            "batches": self.batches,
            "mean_batch_rows": round(
                self.batch_rows_sum / max(1, self.batches), 2),
            "mean_coalesced": round(
                self.coalesced_sum / max(1, self.batches), 2),
            "batch_hist": {str(k): v
                           for k, v in sorted(self.batch_hist.items())},
            "shed": self.shed,
            "expired": self.expired,
            "errors": self.errors,
            # real Prometheus histogram families on /metrics
            # (hivemall_tpu_serve_request_latency_seconds_bucket, ...)
            "request_latency_seconds": self.latency_hist.snapshot(),
            "batch_size_rows": self.batch_size_hist.snapshot(),
            "score_mean": round(self.score_sum / self.score_n, 6)
            if self.score_n else None,
            "score_std": round(max(
                0.0, self.score_sumsq / self.score_n
                - (self.score_sum / self.score_n) ** 2) ** 0.5, 6)
            if self.score_n else None,
        }

    def slo_totals(self) -> dict:
        """Cumulative totals for the SLO engine (obs.slo): counters, the
        latency histogram snapshot, and raw score moments — all
        monotonic and summable across a fleet's replicas (the manager
        aggregates each replica's copy off ``/healthz``)."""
        return {
            "requests": self.requests,
            "errors": self.errors,
            "shed": self.shed,
            "expired": self.expired,
            "latency": self.latency_hist.snapshot(),
            "score_sum": round(self.score_sum, 6),
            "score_sumsq": round(self.score_sumsq, 6),
            "score_n": self.score_n,
        }


class MicroBatcher(BatchPlane):
    """Coalesce concurrent predict requests into bounded batches."""

    def __init__(self, predict_fn, *, max_batch: int = 256,
                 max_delay_ms: float = 2.0,
                 max_queue_rows: Optional[int] = None,
                 deadline_ms: float = 0.0):
        self._predict = predict_fn
        self._init_plane(max_batch, max_delay_ms, max_queue_rows,
                         deadline_ms)
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._closed = False
        self._thread = threading.Thread(target=self._run,
                                        name="serve-batcher", daemon=True)
        self._thread.start()

    # -- submit side ---------------------------------------------------------
    def submit(self, rows: list, deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               raw: Optional[list] = None) -> Future:
        """Enqueue one request (a list of parsed rows). Returns a Future
        resolving to float32 scores [len(rows)] — or, when the predict
        fn returns ``(scores, meta)``, to ``(scores_slice, meta)``.
        After completion the future carries a ``hop`` attribute with the
        request's queue/assemble/predict second decomposition (the HTTP
        front end turns it into the per-hop breakdown headers).
        ``trace_id`` tags the dispatch-side spans of the batch this
        request lands in (request-scoped tracing). Raises ServeOverload
        synchronously when the bounded queue is full."""
        fut: Future = Future()
        n = len(rows)
        if n == 0:
            fut.set_result(np.zeros(0, np.float32))
            return fut
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        now = time.monotonic()
        t_deadline = now + dl / 1000.0 if dl > 0 else None
        with self._tracer.span("serve.enqueue"):
            with self._cv:
                if self._closed:
                    raise RuntimeError("batcher is closed")
                # fail-fast shed: an over-budget request never queues —
                # except a single oversized request against an EMPTY
                # queue, which is admitted alone (it could never fit)
                if self._queued_rows + n > self.max_queue_rows and self._q:
                    self.shed += 1
                    fl = self._flight
                    if fl.enabled:       # shed is the black box's best
                        # overload evidence — worth the (rare) event
                        fl.record("req.shed",
                                  f"rows={n}{FS}depth={self._queued_rows}")
                    raise ServeOverload(
                        f"queue full ({self._queued_rows} rows queued, "
                        f"max {self.max_queue_rows}); request shed")
                rq = self.requests + 1
                self._q.append(_Req(rows, n, fut, now, t_deadline,
                                    trace_id, raw, rq))
                self._queued_rows += n
                depth = self._queued_rows
                self.requests = rq
                self.rows_in += n
                self._req_meter.add(1)
                self._cv.notify()
        fl = self._flight
        if fl.enabled:                   # admitted: the crash-safe record
            # of in-flight work (post-mortem correlates these against
            # batch.done to list a victim's final uncompleted requests)
            if trace_id:
                fl.record("req.admit", f"req={rq}{FS}rows={n}{FS}"
                                       f"depth={depth}{FS}trace={trace_id}")
            else:
                fl.record("req.admit",
                          f"req={rq}{FS}rows={n}{FS}depth={depth}")
        return fut

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    # -- dispatch side -------------------------------------------------------
    def _take_batch(self) -> Optional[List[_Req]]:
        """Block until a coalescing window closes; pop its requests.
        Returns None only at close time."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return None
                self._cv.wait()        # submit() and close() both notify
            # window: up to max_delay past the FIRST request, closed
            # early once max_batch rows are waiting
            t_close = self._q[0].t_enq + self.max_delay
            while self._queued_rows < self.max_batch:
                tmo = t_close - time.monotonic()
                if tmo <= 0 or self._closed:
                    break
                self._cv.wait(tmo)
            batch: List[_Req] = []
            nrows = 0
            while self._q:
                r = self._q[0]
                if batch and nrows + r.n > self.max_batch:
                    break              # never split a request
                self._q.popleft()
                self._queued_rows -= r.n
                batch.append(r)
                nrows += r.n
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live: List[_Req] = []
            for r in batch:
                if r.t_deadline is not None and now > r.t_deadline:
                    self.expired += 1
                    fl = self._flight
                    if fl.enabled:
                        fl.record("req.expired", f"req={r.req_no}")
                    # the request's time-in-queue at expiry enters the
                    # latency histogram (a lower bound of its would-be
                    # latency) — otherwise the SLO latency window reads
                    # healthy during a timeout collapse, exactly when
                    # the worst latencies are happening
                    self.latency_hist.observe(now - r.t_enq)
                    r.fut.set_exception(ServeDeadline(
                        f"deadline expired after "
                        f"{(now - r.t_enq) * 1000:.1f}ms in queue"))
                else:
                    live.append(r)
            if not live:
                continue
            rows = [row for r in live for row in r.rows]
            # request-scoped tracing: the batch's dispatch-side spans
            # (serve.batch + the engine's serve.predict inside the
            # predict fn) carry every traced request's id — _NULL_SPAN
            # when the tracer is off or nothing in the batch is traced
            tids = [r.trace_id for r in live if r.trace_id]
            ctx = self._tracer.context(",".join(tids) if tids else None)
            # `now` was taken right after the batch was popped — queue
            # time ends THERE; everything from the pop to the predict
            # call (expiry filter, row flatten, trace setup) is batch
            # assembly and must not masquerade as queue wait
            t_deq = now
            with ctx:
                with self._tracer.span("serve.batch"):
                    t_p0 = time.monotonic()
                    try:
                        out = self._predict(rows)
                    except Exception as e:   # noqa: BLE001 — score-time
                        # failure: isolate per request so one bad
                        # client's rows cannot 500 the innocent requests
                        # coalesced into the same batch; the dispatch
                        # loop survives
                        if len(live) == 1:
                            self.errors += 1
                            fl = self._flight
                            if fl.enabled:
                                fl.record("req.err",
                                          f"req={live[0].req_no}{FS}"
                                          f"err={type(e).__name__}")
                            live[0].fut.set_exception(e)
                        else:
                            self._score_individually(live, t_deq)
                        continue
                    t_p1 = time.monotonic()
            # a predict fn may return (scores, meta) — meta (e.g. the
            # model step that scored this batch) rides along to every
            # request future in the batch
            meta = None
            scores = out
            if isinstance(out, tuple):
                scores, meta = out
            self._note_batch(len(rows), len(live), scores)
            # per-hop decomposition, shared by the batch: assembly =
            # expiry filter + row flatten, predict = the scorer call
            assemble_s = t_p0 - t_deq
            predict_s = t_p1 - t_p0
            t_done = time.monotonic()
            off = 0
            for r in live:
                part = np.asarray(scores[off:off + r.n], np.float32)
                self.latency_hist.observe(t_done - r.t_enq)
                r.fut.hop = {"queue_s": t_deq - r.t_enq,
                             "assemble_s": assemble_s,
                             "predict_s": predict_s}
                r.fut.set_result(part if meta is None else (part, meta))
                off += r.n
            fl = self._flight
            if fl.enabled:
                self._flight_batch_done(live, len(rows), assemble_s,
                                        predict_s, meta)
            self._tee_batch(rows, live)

    def _score_individually(self, reqs: List[_Req],
                            t_deq: Optional[float] = None) -> None:
        """Fallback after a coalesced batch raised: re-score each request
        alone, failing only the one(s) whose rows actually raise.
        ``t_deq`` is when the shared batch was dequeued — queue time ends
        there; the failed shared predict and earlier siblings' rescores
        land in the handler's ``other`` residual, not in ``queue``."""
        for r in reqs:
            try:
                t_p0 = time.monotonic()
                with self._tracer.context(r.trace_id):
                    out = self._predict(r.rows)
                t_p1 = time.monotonic()
                scores, meta = (out if isinstance(out, tuple)
                                else (out, None))
                part = np.asarray(scores[:r.n], np.float32)
                self.latency_hist.observe(t_p1 - r.t_enq)
                # the fallback's requests must stay visible to the
                # score-drift detector — a model shift coinciding with
                # batch failures would otherwise be diluted
                self._note_scores(part, r.n)
                r.fut.hop = {"queue_s": (t_deq if t_deq is not None
                                         else t_p0) - r.t_enq,
                             "assemble_s": 0.0,
                             "predict_s": t_p1 - t_p0}
                r.fut.set_result(part if meta is None else (part, meta))
                fl = self._flight
                if fl.enabled:
                    self._flight_batch_done([r], r.n, 0.0, t_p1 - t_p0,
                                            meta)
            except Exception as e:     # noqa: BLE001 — per-request fate
                self.errors += 1
                fl = self._flight
                if fl.enabled:
                    fl.record("req.err", f"req={r.req_no}{FS}"
                                         f"err={type(e).__name__}")
                r.fut.set_exception(e)

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = False, timeout: float = 5.0) -> None:
        """Stop the dispatch thread. New submits fail immediately with a
        closed error in either mode; what happens to requests ALREADY
        queued is the ``drain`` choice:

        - ``drain=False`` (default): queued requests fail with the closed
          error rather than hanging their futures forever — the abrupt
          shutdown path.
        - ``drain=True``: the dispatch thread keeps scoring until the
          queue is empty, so every accepted request completes — the
          graceful shutdown path (a fleet replica answering its last
          in-flight requests before the process exits).

        The in-flight batch (already handed to the predict fn) always
        completes in both modes."""
        with self._cv:
            self._closed = True
            if drain:
                pending: List[_Req] = []
            else:
                pending = list(self._q)
                self._q.clear()
                self._queued_rows = 0
            self._cv.notify_all()
        for r in pending:
            r.fut.set_exception(RuntimeError("batcher closed"))
        self._thread.join(timeout=timeout)
