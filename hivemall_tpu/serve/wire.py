"""Compact pre-tokenized wire format for ``/predict`` bodies.

The JSON protocol ships feature STRINGS (``"123:0.5"``) and pays a
libsvm string parse + feature hash per row on the serving hot path.
Clients that already hold hashed ids (anything that ran the trainer's
parser once — offline featurizers, the router's replay tee, bench
drivers) can skip that entirely by POSTing a fixed binary frame
instead, negotiated per-request via ``Content-Type:
application/x-hivemall-frame``.  JSON string bodies remain fully
supported on the same listener and bit-match frame scores (same hashed
ids -> same kernels -> same bits); see docs/SERVING.md "Serving
planes".

Frame layout (all little-endian, no alignment padding)::

    magic    4s   b"HMF1"
    flags    u8   bit0: per-request deadline_ms present; rest reserved 0
    n_rows   u16
    deadline f32  milliseconds (present iff flags bit0)
    per row:
        n_feat u16
        idx    i32 * n_feat   hashed feature ids (trainer hash space)
        val    f32 * n_feat

Decoded rows are exactly the trainer's pre-parsed shape —
``(int32[n], float32[n])`` tuples — which ``Trainer._parse_row``
passes through untouched, so a frame predict shares every byte of the
scoring path after parse.  Malformed or truncated frames raise
:class:`WireError`; servers answer 400 and close the connection
(a desynced binary stream cannot be resynchronized mid-connection).

Response frame (``HMR1``): a client that sends ``Accept:
application/x-hivemall-frame`` gets its scores (and, on ``/retrieve``,
its ranked id lists) back as a binary frame instead of JSON — top-k
retrieval responses are dominated by JSON float encode at high k, and
the predict fast path saves the ``json.dumps`` on every hop.  Layout::

    magic    4s   b"HMR1"
    flags    u8   bit0: model_step present; bit1: per-row ids present
    n_rows   u16
    step     i64  model step (present iff flags bit0)
    per row:
        n      u16
        ids    i32 * n   ranked ids (present iff flags bit1)
        scores f32 * n

A scores-only response (``/predict``) sets n to the row's score count
with no ids; a retrieval response carries ids+scores pairs already
trimmed of padding.  Decode errors raise :class:`WireError` exactly
like the request side.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

MAGIC = b"HMF1"
RESPONSE_MAGIC = b"HMR1"
#: Content-Type negotiating the binary frame protocol on /predict
#: (request body) and, via the Accept header, binary responses.
CONTENT_TYPE_FRAME = "application/x-hivemall-frame"

_FLAG_DEADLINE = 0x01
_RFLAG_STEP = 0x01
_RFLAG_IDS = 0x02
_HEAD = struct.Struct("<4sBH")          # magic, flags, n_rows
_DEADLINE = struct.Struct("<f")
_NFEAT = struct.Struct("<H")
_STEP = struct.Struct("<q")

#: Hard cap on rows per frame (u16 field; also bounds a hostile frame).
MAX_ROWS = 0xFFFF


class WireError(ValueError):
    """Malformed or truncated binary frame."""


def encode_frame(rows, deadline_ms: Optional[float] = None) -> bytes:
    """Encode pre-parsed ``(idx, val)`` rows into one binary frame.

    ``rows`` is a sequence of ``(int32-array-like, float32-array-like)``
    tuples in the trainer's hashed id space (e.g. straight from
    ``Trainer._parse_row`` or a decoded frame).
    """
    if len(rows) > MAX_ROWS:
        raise WireError(f"frame rows {len(rows)} > {MAX_ROWS}")
    flags = _FLAG_DEADLINE if deadline_ms is not None else 0
    out = [_HEAD.pack(MAGIC, flags, len(rows))]
    if deadline_ms is not None:
        out.append(_DEADLINE.pack(float(deadline_ms)))
    for idx, val in rows:
        i = np.ascontiguousarray(np.asarray(idx, np.dtype("<i4")))
        v = np.ascontiguousarray(np.asarray(val, np.dtype("<f4")))
        if i.ndim != 1 or i.shape != v.shape:
            raise WireError(f"row shape mismatch: idx {i.shape} "
                            f"val {v.shape}")
        if len(i) > 0xFFFF:
            raise WireError(f"row features {len(i)} > 65535")
        out.append(_NFEAT.pack(len(i)))
        out.append(i.tobytes())
        out.append(v.tobytes())
    return b"".join(out)


def decode_frame(body: bytes, max_row_features: int = 0,
                 ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]],
                            Optional[float]]:
    """Decode one binary frame into ``(rows, deadline_ms)``.

    Rows come back as ``(int32[n], float32[n])`` tuples.  A positive
    ``max_row_features`` bounds each row (the engine's per-row cap,
    enforced here so a hostile frame fails before allocation).
    Raises :class:`WireError` on any structural problem, including
    trailing garbage after the last row.
    """
    if len(body) < _HEAD.size:
        raise WireError(f"frame truncated: {len(body)} bytes < header")
    magic, flags, n_rows = _HEAD.unpack_from(body, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if flags & ~_FLAG_DEADLINE:
        raise WireError(f"unknown flags 0x{flags:02x}")
    off = _HEAD.size
    deadline_ms: Optional[float] = None
    if flags & _FLAG_DEADLINE:
        if len(body) < off + _DEADLINE.size:
            raise WireError("frame truncated in deadline")
        deadline_ms = float(_DEADLINE.unpack_from(body, off)[0])
        off += _DEADLINE.size
    rows: List[Tuple[np.ndarray, np.ndarray]] = []
    for r in range(n_rows):
        if len(body) < off + _NFEAT.size:
            raise WireError(f"frame truncated at row {r} length")
        (n_feat,) = _NFEAT.unpack_from(body, off)
        off += _NFEAT.size
        if max_row_features and n_feat > max_row_features:
            raise WireError(f"row {r}: {n_feat} features > cap "
                            f"{max_row_features}")
        need = n_feat * 8                # i32 + f32 per feature
        if len(body) < off + need:
            raise WireError(f"frame truncated in row {r} payload")
        idx = np.frombuffer(body, np.dtype("<i4"), n_feat, off)
        off += n_feat * 4
        val = np.frombuffer(body, np.dtype("<f4"), n_feat, off)
        off += n_feat * 4
        # frombuffer views are read-only and may be unaligned; copy to
        # native-order owned arrays (the padding kernels slice these)
        rows.append((idx.astype(np.int32), val.astype(np.float32)))
    if off != len(body):
        raise WireError(f"{len(body) - off} trailing bytes after frame")
    return rows, deadline_ms


def encode_response_frame(scores_rows,
                          ids_rows=None,
                          model_step: Optional[int] = None) -> bytes:
    """Encode per-row scores (and optional ranked ids) into one HMR1
    response frame.

    ``scores_rows`` is a sequence of float sequences; ``ids_rows``
    (when given) pairs each with an int sequence of equal length —
    ranked ids for the retrieval plane, already trimmed of -1 padding.
    """
    if len(scores_rows) > MAX_ROWS:
        raise WireError(f"frame rows {len(scores_rows)} > {MAX_ROWS}")
    flags = 0
    if model_step is not None:
        flags |= _RFLAG_STEP
    if ids_rows is not None:
        flags |= _RFLAG_IDS
        if len(ids_rows) != len(scores_rows):
            raise WireError(f"ids rows {len(ids_rows)} != scores rows "
                            f"{len(scores_rows)}")
    out = [_HEAD.pack(RESPONSE_MAGIC, flags, len(scores_rows))]
    if model_step is not None:
        out.append(_STEP.pack(int(model_step)))
    for r, srow in enumerate(scores_rows):
        s = np.ascontiguousarray(np.asarray(srow, np.dtype("<f4")))
        if s.ndim != 1:
            raise WireError(f"row {r}: scores must be 1-d")
        if len(s) > 0xFFFF:
            raise WireError(f"row {r}: {len(s)} scores > 65535")
        out.append(_NFEAT.pack(len(s)))
        if ids_rows is not None:
            i = np.ascontiguousarray(
                np.asarray(ids_rows[r], np.dtype("<i4")))
            if i.shape != s.shape:
                raise WireError(f"row {r}: ids {i.shape} != scores "
                                f"{s.shape}")
            out.append(i.tobytes())
        out.append(s.tobytes())
    return b"".join(out)


def decode_response_frame(body: bytes
                          ) -> Tuple[List[np.ndarray],
                                     Optional[List[np.ndarray]],
                                     Optional[int]]:
    """Decode one HMR1 frame into ``(scores_rows, ids_rows, step)``.
    ``ids_rows`` is None for a scores-only (predict) response; ``step``
    is None when the server did not stamp a model version."""
    if len(body) < _HEAD.size:
        raise WireError(f"response truncated: {len(body)} bytes < header")
    magic, flags, n_rows = _HEAD.unpack_from(body, 0)
    if magic != RESPONSE_MAGIC:
        raise WireError(f"bad response magic {magic!r}")
    if flags & ~(_RFLAG_STEP | _RFLAG_IDS):
        raise WireError(f"unknown response flags 0x{flags:02x}")
    off = _HEAD.size
    step: Optional[int] = None
    if flags & _RFLAG_STEP:
        if len(body) < off + _STEP.size:
            raise WireError("response truncated in step")
        step = int(_STEP.unpack_from(body, off)[0])
        off += _STEP.size
    has_ids = bool(flags & _RFLAG_IDS)
    scores_rows: List[np.ndarray] = []
    ids_rows: Optional[List[np.ndarray]] = [] if has_ids else None
    for r in range(n_rows):
        if len(body) < off + _NFEAT.size:
            raise WireError(f"response truncated at row {r} length")
        (n,) = _NFEAT.unpack_from(body, off)
        off += _NFEAT.size
        need = n * (8 if has_ids else 4)
        if len(body) < off + need:
            raise WireError(f"response truncated in row {r} payload")
        if has_ids:
            ids = np.frombuffer(body, np.dtype("<i4"), n, off)
            off += n * 4
            ids_rows.append(ids.astype(np.int32))
        s = np.frombuffer(body, np.dtype("<f4"), n, off)
        off += n * 4
        scores_rows.append(s.astype(np.float32))
    if off != len(body):
        raise WireError(f"{len(body) - off} trailing bytes after "
                        "response frame")
    return scores_rows, ids_rows, step
