"""Serve smoke — run by run_tests.sh (docs/SERVING.md).

The acceptance surface of the online-serving subsystem, seconds-scale:

1. a checkpoint trained in-process is served over HTTP and concurrent
   single-row predicts COALESCE (observed mean batch rows > 1 — the
   dynamic micro-batcher actually batching, not a degenerate 1-row loop);
2. served probabilities BIT-MATCH the offline ``predict_proba`` on the
   same feature strings (same hashing path, same kernels, same sigmoid);
3. request p99 latency stays under a budget (post-warmup — the engine
   pre-compiles its batch buckets at startup, so no request pays XLA);
4. a NEWER checkpoint written mid-traffic is hot-reloaded without a
   single in-flight request failing, and /healthz reflects the new step;
5. the obs registry surfaces the ``serve`` section through the server's
   own /snapshot and /metrics.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

from ..utils.net import http_get as _get


def _post(url: str, obj: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, json.dumps(obj).encode(), {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _train_bundle(ckdir: str, opts: str, ds, epochs: int = 1):
    """Train (or continue training) and drop a step-named bundle into the
    shared checkpoint dir — the shape a live trainer's autosave produces."""
    from ..models.linear import GeneralClassifier
    t = GeneralClassifier(opts)
    from ..io.checkpoint import newest_bundle
    nb = newest_bundle(ckdir, t.NAME)
    if nb is not None:
        t.load_bundle(nb[1])
    for _ in range(epochs):
        t.fit(ds)
    path = os.path.join(ckdir, f"{t.NAME}-step{t._t:010d}.npz")
    t.save_bundle(path)
    return t, path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="hivemall_tpu.serve.smoke")
    ap.add_argument("--rows", type=int, default=400)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--p99-budget-ms", type=float, default=1500.0,
                    help="per-request p99 wall budget (generous: CPU CI)")
    ap.add_argument("--plane", default="threaded",
                    choices=("threaded", "evloop"),
                    help="serving plane under test (docs/SERVING.md "
                         "'Serving planes')")
    args = ap.parse_args(argv)
    # lockset race sanitizer (HIVEMALL_TPU_TSAN=1): enable BEFORE any
    # serve object exists so every lock in the system is born wrapped;
    # a sanitizer build is never a perf build, so the latency budget
    # relaxes (correctness checks — bit-match, zero drops — stay hard)
    from ..testing import tsan
    if tsan.maybe_enable():
        args.p99_budget_ms *= 3
        print(f"serve smoke: tsan sanitizer ON (p99 budget relaxed to "
              f"{args.p99_budget_ms}ms)", file=sys.stderr)
    # leak census sanitizer (HIVEMALL_TPU_LEAKTRACK=1): snapshot BEFORE
    # any serve object exists; the census re-runs after the full
    # traffic + reload + drain + shutdown cycle and any tracked
    # fd/socket/thread still alive fails the smoke
    from ..testing import leaktrack
    if leaktrack.maybe_enable():
        print("serve smoke: leaktrack sanitizer ON", file=sys.stderr)
        leaktrack.snapshot()
    tmp = tempfile.mkdtemp(prefix="hivemall_tpu_serve_smoke_")
    try:
        rc = _run(args, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if leaktrack.enabled():
        n = leaktrack.check_and_report("serve smoke leaktrack")
        print(f"serve smoke leak_census: {'OK' if n == 0 else 'FAILED'} "
              f"({n} leaked resource(s) after shutdown)",
              file=sys.stderr)
        rc += 1 if n else 0      # counts wrap mod 256 in exit codes —
        #                          a 256-leak run must not read as 0
    return rc


def _run(args, tmp: str) -> int:
    from ..io.libsvm import synthetic_classification
    from ..io.sparse import SparseDataset
    from ..serve.engine import PredictEngine
    from ..serve.http import PredictServer

    opts = "-dims 4096 -loss logloss -opt adagrad -mini_batch 64"
    ds, _ = synthetic_classification(args.rows, 256, seed=7)
    trainer, _ = _train_bundle(tmp, opts, ds)

    # the request corpus: feature STRINGS (the wire format), fed
    # identically to the offline reference and the server
    rows = []
    for i in range(args.requests):
        idx, val = ds.row(i % args.rows)
        rows.append([f"{int(a)}:{float(v)!r}" for a, v in zip(idx, val)])
    parsed = [trainer._parse_row(r) for r in rows]
    ref = trainer.predict_proba(
        SparseDataset.from_rows(parsed, [1.0] * len(parsed)))

    # warmup_len matches the corpus row width so the pre-compiled
    # buckets are the ones traffic hits (p99 measures serving, not XLA)
    engine = PredictEngine("train_classifier", opts, checkpoint_dir=tmp,
                           watch_interval=0.2,
                           warmup_len=max(len(r) for r in rows))
    if args.plane == "evloop":
        from ..serve.evloop import EvloopPredictServer as _ServerCls
    else:
        _ServerCls = PredictServer
    srv = _ServerCls(engine, port=0, max_delay_ms=10.0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        return _drive(args, tmp, ds, rows, ref, engine, srv, base)
    finally:
        srv.stop()


def _drive(args, tmp, ds, rows, ref, engine, srv, base) -> int:
    failures = []

    def check(name, ok, detail=""):
        print(f"serve smoke {name}: {'OK' if ok else 'FAILED'} {detail}",
              file=sys.stderr)
        if not ok:
            failures.append(name)

    # -- concurrent predicts: coalescing + bit-match + latency ------------
    # each worker holds ONE keep-alive connection (HTTP/1.1 — the
    # PredictServer reuse path runs in CI, not just in bench_serve)
    from .http import KeepAliveClient
    scores = [None] * len(rows)
    lat = [0.0] * len(rows)
    errs = []
    pos = iter(range(len(rows)))
    lock = threading.Lock()

    def worker():
        cli = KeepAliveClient("127.0.0.1", srv.port)
        while True:
            with lock:
                i = next(pos, None)
            if i is None:
                cli.close()
                return
            t0 = time.perf_counter()
            try:
                code, r = cli.post_json("/predict", {"rows": [rows[i]]})
                assert code == 200, (code, r)
                scores[i] = r["scores"][0]
            except Exception as e:     # noqa: BLE001 — collected
                errs.append(f"req {i}: {e}")
            lat[i] = time.perf_counter() - t0

    ts = [threading.Thread(target=worker) for _ in range(args.threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    check("requests", not errs, f"({len(rows)} requests, "
                                f"{len(errs)} errors) {errs[:2]}")
    # failed requests leave None behind — score them NaN so the remaining
    # checks still report instead of crashing the smoke mid-drive
    got = np.asarray([np.nan if s is None else s for s in scores],
                     np.float32)
    check("bit_match", np.array_equal(got, ref),
          f"(max abs diff {np.abs(got - ref).max():.2e})")
    st = srv.batcher.stats()
    check("coalescing", st["mean_batch_rows"] > 1.0,
          f"(mean batch {st['mean_batch_rows']}, "
          f"{st['batches']} batches / {st['requests']} requests)")
    p99 = float(np.percentile(np.asarray(lat) * 1000, 99))
    check("p99_latency", p99 <= args.p99_budget_ms,
          f"({p99:.1f}ms vs budget {args.p99_budget_ms}ms)")

    # -- hot reload mid-traffic ------------------------------------------
    stop = threading.Event()
    traffic_errs = []

    def traffic():
        i = 0
        while not stop.is_set():
            try:
                _post(base + "/predict", {"rows": [rows[i % len(rows)]]})
            except Exception as e:     # noqa: BLE001 — collected
                traffic_errs.append(str(e))
            i += 1

    tt = [threading.Thread(target=traffic) for _ in range(4)]
    for t in tt:
        t.start()
    old_step = engine.model_step
    t2, _ = _train_bundle(tmp, "-dims 4096 -loss logloss -opt adagrad "
                               "-mini_batch 64", ds)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and engine.model_step < t2._t:
        time.sleep(0.1)
    stop.set()
    for t in tt:
        t.join()
    check("hot_reload", engine.model_step == t2._t,
          f"(step {old_step} -> {engine.model_step}, "
          f"expected {t2._t}, reloads {engine.reloads})")
    check("reload_no_drops", not traffic_errs,
          f"({len(traffic_errs)} failed during reload) {traffic_errs[:2]}")
    hz = json.loads(_get(base + "/healthz"))
    check("healthz", hz.get("status") == "ok"
          and hz.get("model_step") == engine.model_step, f"({hz})")

    # -- obs surface ------------------------------------------------------
    snap = json.loads(_get(base + "/snapshot"))
    sv = snap.get("serve", {})
    need = ("qps", "queue_depth", "batch_hist", "shed", "model_step",
            "model_age_seconds")
    missing = [k for k in need if k not in sv]
    check("obs_snapshot", not missing, f"(missing {missing})")
    prom = _get(base + "/metrics").decode()
    check("obs_metrics", "hivemall_tpu_serve_model_step" in prom
          and "hivemall_tpu_serve_qps" in prom)

    # -- lockset sanitizer verdict (only when HIVEMALL_TPU_TSAN=1) --------
    from ..testing import tsan
    if tsan.enabled():
        check("tsan_races",
              tsan.check_and_report("serve smoke tsan") == 0)

    print(f"serve smoke: {len(failures)} failures", file=sys.stderr)
    return len(failures)


if __name__ == "__main__":
    sys.exit(main())
