"""hivemall_tpu — a TPU-native (JAX/XLA/Pallas/pjit) machine-learning framework
with the capability surface of Hivemall (reference: maropu/hivemall, whose tree is
the deprecation stub of the Apache Hivemall lineage; see SURVEY.md for the full
component inventory this package implements).

Design thesis (SURVEY.md §1): Hivemall expresses ML as a catalog of SQL functions —
trainers are streaming UDTFs, prediction is a join, parallelism is the engine's.
The TPU rebuild keeps the *catalog* (names, option grammars, semantics) as the
public surface, and replaces the execution substrate:

- per-row JVM math            -> batched, jitted JAX kernels on TPU
- open-addressing hash models -> dense hashed parameter tables in HBM (bf16/f32)
- MixServer async averaging   -> lax.pmean over ICI at -mix_threshold cadence,
                                 plus an async host mix service for DCN
- Hive/Spark engine           -> a thin Arrow/numpy columnar frame + input pipeline

Package map (SURVEY.md §8):
  utils/     hashing (bit-exact murmur3), option-string parser, primitives
  io/        LIBSVM/CSV readers, padded sparse batches, amplify/replay cache
  ftvec/     feature engineering catalog (hashing, scaling, crossing, trans, ...)
  ops/       jitted kernels: losses, optimizers, schedules, sparse dots, pallas
  models/    trainer "UDTFs" (linear, FM/FFM, MF/BPR, word2vec, trees, LDA, ...)
  parallel/  device mesh, mix (psum cadence, argmin-KLD), host mix service
  frame/     evaluation UDAFs, tools/* long tail, each_top_k
  catalog/   define-all manifest: SQL name -> callable + option grammar
  cli/       train/predict runners
"""

__version__ = "0.1.0"

VERSION = __version__

# opt-in numeric sanitizer (SURVEY.md §6): HIVEMALL_TPU_DEBUG_NANS=1
import os as _os

if _os.environ.get("HIVEMALL_TPU_DEBUG_NANS"):
    from .utils.debug import maybe_enable_from_env as _men

    _men()


def hivemall_version() -> str:
    """SQL: hivemall_version() — version UDF (reference: hivemall.VersionUDF)."""
    return __version__
