"""Span tracing — where did this step's wall time go.

A :class:`Tracer` records named monotonic-clock spans into a thread-safe
ring buffer plus per-stage aggregates, mirroring ``MetricsStream``'s
contract: when disabled (the default) a span costs ONE attribute check and
returns a shared no-op context manager — the hot path pays nothing.

Span sites (the training pipeline's real seams — docs/OBSERVABILITY.md):

========================  ===================================================
``ingest.prep``           host batch prep (IngestPipeline worker fn, both
                          the pool workers and the sequential fallback)
``stager.stack``          K-step megabatch stacking (MegabatchStager)
``h2d.stage``             host->device transfer (prefetch.stage_batch)
``dispatch.step``         one jitted step dispatch (host-side boundary)
``dispatch.megastep``     one fused K-step lax.scan dispatch
``mix.exchange``          one MIX exchange incl. retries + fold-back
``checkpoint.save``       one atomic bundle save
========================  ===================================================

Host-side semantics: a dispatch span measures the host's time in the
dispatch call (on CPU that is the synchronous step; on accelerators it is
dispatch latency — the async compute tail lands in the NEXT blocking
boundary, exactly like the bench's stage decomposition). Rollups emit as
``span_rollup`` jsonl events at the trainer's loss-fold cadence; the raw
ring exports as Chrome-trace JSON (``chrome://tracing`` / Perfetto) for
deep dives alongside ``jax.profiler``.

Activation: ``HIVEMALL_TPU_TRACE=1`` enables the process tracer;
``HIVEMALL_TPU_TRACE=/path/trace.json`` additionally writes the Chrome
export there at ``train_done``. Or drive it explicitly via
``get_tracer().enable()``.

Request-scoped tracing (docs/OBSERVABILITY.md "Serving traces and
SLOs"): a serving request sampled by the fleet router (or carrying an
explicit ``x-hivemall-trace`` header) flows its trace id through
:meth:`Tracer.context` — a thread-local tag that every span completed
inside the ``with`` block records into its Chrome-export ``args``. The
export timestamps are WALL-CLOCK anchored (epoch microseconds), so the
router's and each replica's independently-recorded spans line up on one
Perfetto timeline when merged (each process keeps its own ``pid``); the
router's ``/trace`` endpoint does exactly that merge. Disabled-tracer
cost is unchanged: ``span()``/``context()`` stay one attribute check
returning a shared no-op.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["Tracer", "get_tracer", "mint_trace_id"]

_RING = 8192          # completed spans kept for the Chrome export
_RESERVOIR = 512      # per-stage duration reservoir for p50/p99


class _NullSpan:
    """Shared no-op context manager — the disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()

# per-process salt keeps minted ids unique across replica restarts on one
# host (pid alone recycles); 2 bytes is plenty for a serving fleet
_TRACE_SALT = int.from_bytes(os.urandom(2), "big")
_trace_seq = itertools.count(1)


def mint_trace_id() -> str:
    """A new request trace id: ``<pid>-<salt>-<seq>`` hex — unique across
    the processes of one fleet without any coordination."""
    return f"{os.getpid():x}-{_TRACE_SALT:04x}-{next(_trace_seq):x}"


class _TraceCtx:
    """Thread-local trace tag: spans completed inside the block record
    ``tag`` into their Chrome-export args. Nestable (restores the outer
    tag on exit); created only when the tracer is enabled AND a request
    is actually traced, so the untraced hot path never sees it."""

    __slots__ = ("_tls", "tag", "_prev")

    def __init__(self, tls, tag: str):
        self._tls = tls
        self.tag = tag

    def __enter__(self):
        self._prev = getattr(self._tls, "trace", None)
        self._tls.trace = self.tag
        return self

    def __exit__(self, *exc):
        self._tls.trace = self._prev
        return False


class _Span:
    __slots__ = ("_tracer", "name", "t0")

    def __init__(self, tracer: "Tracer", name: str):
        self._tracer = tracer
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer._record(self.name, self.t0,
                             time.perf_counter() - self.t0)
        return False


class _Stage:
    __slots__ = ("count", "total_s", "durs")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.durs: deque = deque(maxlen=_RESERVOIR)


def _pctl(sorted_durs, q: float) -> float:
    return sorted_durs[min(len(sorted_durs) - 1,
                           int(q * (len(sorted_durs) - 1) + 0.5))]


class Tracer:
    """Thread-safe span recorder with per-stage rollups.

    Spans may complete concurrently on ingest workers, the prefetcher
    thread, and the train loop; one lock guards the (cheap) aggregate
    update. ``span()`` when disabled allocates nothing and takes no lock.
    """

    def __init__(self, enabled: bool = False, ring: int = _RING):
        self.enabled = bool(enabled)
        self.export_path: Optional[str] = None
        # shows as the Chrome-export process name next to the pid, so a
        # merged fleet trace reads router/replica instead of bare pids
        self.process_label = f"pid{os.getpid()}"
        self._lock = threading.Lock()
        self._stages: Dict[str, _Stage] = {}
        self._events: deque = deque(maxlen=max(1, ring))
        #: spans evicted from the full ring before export — a silent wrap
        #: used to make a Chrome export look complete when it wasn't;
        #: surfaced as ``spans.dropped`` in the registry and /metrics
        self.dropped = 0
        self._tls = threading.local()
        # paired clocks: spans time with the monotonic perf counter, the
        # export anchors them to the wall clock so independently-recorded
        # processes share one timeline when their exports merge
        self._origin = time.perf_counter()
        self._origin_wall = time.time()

    # -- control -------------------------------------------------------------
    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def reset(self) -> None:
        """Drop all recorded spans and aggregates (tests, run boundaries)."""
        with self._lock:
            self._stages.clear()
            self._events.clear()
            self.dropped = 0

    # -- recording -----------------------------------------------------------
    def span(self, name: str):
        """Context manager timing one span. ~Free when disabled: one
        attribute check, shared no-op object, no allocation."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name)

    def context(self, trace_id: Optional[str]):
        """Tag every span completed in this ``with`` block (on THIS
        thread) with ``trace_id`` — the request-scoped tracing seam.
        One attribute check + shared no-op when disabled or untagged."""
        if not self.enabled or not trace_id:
            return _NULL_SPAN
        return _TraceCtx(self._tls, trace_id)

    def add_span(self, name: str, dur_s: float,
                 trace: Optional[str] = None) -> None:
        """Record an already-measured span ending ~now (the router's
        forward loop measures across retries and can't wrap a single
        ``with``). No-op when disabled."""
        if not self.enabled:
            return
        self._record(name, time.perf_counter() - dur_s, dur_s, trace=trace)

    def _record(self, name: str, t0: float, dur: float,
                trace: Optional[str] = "\0tls") -> None:
        tid = threading.get_ident()
        if trace == "\0tls":             # default: the thread's context tag
            trace = getattr(self._tls, "trace", None)
        with self._lock:
            st = self._stages.get(name)
            if st is None:
                st = self._stages[name] = _Stage()
            st.count += 1
            st.total_s += dur
            st.durs.append(dur)
            if len(self._events) == self._events.maxlen:
                self.dropped += 1        # ring full: the append below
            self._events.append((name, t0, dur, tid, trace))

    # -- reading -------------------------------------------------------------
    def rollup(self) -> Dict[str, dict]:
        """Per-stage ``{count, total_s, p50, p99}`` (percentiles over the
        last ``_RESERVOIR`` spans of each stage). JSON-ready; safe to call
        from any thread while spans are being recorded."""
        with self._lock:
            items = [(name, st.count, st.total_s, list(st.durs))
                     for name, st in self._stages.items()]
        out: Dict[str, dict] = {}
        for name, count, total, durs in sorted(items):
            durs.sort()
            out[name] = {
                "count": count,
                "total_s": round(total, 6),
                "p50": round(_pctl(durs, 0.50), 6) if durs else 0.0,
                "p99": round(_pctl(durs, 0.99), 6) if durs else 0.0,
            }
        return out

    def chrome_dict(self) -> dict:
        """The span ring as a Chrome-trace dict (``ph: "X"`` complete
        events). Timestamps are wall-clock epoch MICROSECONDS (the
        monotonic span clock re-anchored through the paired origins), so
        exports from different processes merge onto one timeline — the
        fleet router concatenates replicas' ``traceEvents`` under their
        own pids to render one request as one cross-process flame."""
        with self._lock:
            events = list(self._events)
        pid = os.getpid()
        wall0 = self._origin_wall - self._origin
        out = []
        for name, t0, dur, tid, trace in events:
            ev = {"name": name, "ph": "X", "cat": "hivemall_tpu",
                  "ts": round((wall0 + t0) * 1e6, 3),
                  "dur": round(dur * 1e6, 3), "pid": pid, "tid": tid}
            if trace is not None:
                ev["args"] = {"trace": trace}
            out.append(ev)
        # metadata last: consumers indexing traceEvents[0] still see the
        # first real span; viewers read ph:"M" anywhere in the list
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": self.process_label}})
        return {"displayTimeUnit": "ms", "traceEvents": out}

    def export_chrome(self, path: str) -> str:
        """Write :meth:`chrome_dict` as JSON — open in chrome://tracing
        or Perfetto. Returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.chrome_dict(), f)
        return path

    def maybe_export(self) -> Optional[str]:
        """Chrome export to ``export_path`` when configured (the
        ``HIVEMALL_TPU_TRACE=<path>.json`` contract); never raises —
        export is observability, not training."""
        if not (self.enabled and self.export_path):
            return None
        try:
            return self.export_chrome(self.export_path)
        except OSError:
            return None


_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The process-wide tracer, bound to ``$HIVEMALL_TPU_TRACE`` on first
    use (unset/"0" = disabled; a ``*.json`` value doubles as the Chrome
    export path) and registered as the obs registry's ``spans`` section."""
    global _tracer
    if _tracer is None:
        env = os.environ.get("HIVEMALL_TPU_TRACE", "")
        t = Tracer(enabled=bool(env) and env != "0")
        if env.endswith(".json"):
            t.export_path = env
        _tracer = t
        from .registry import registry
        # per-stage rollup dicts plus the ring-overflow counter — readers
        # of the section must tolerate the one int among dict values
        # (obs.report / obs.smoke skip non-dict entries)
        registry.register("spans",
                          lambda: {**t.rollup(), "dropped": t.dropped})
    return _tracer
