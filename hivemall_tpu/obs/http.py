"""Opt-in HTTP obs surface — the MixServer's JMX peer, for the runtime.

A deliberately tiny, SINGLE-THREADED ``http.server`` endpoint serving the
central registry (``-obs_port`` trainer option, or :func:`ensure_server`):

- ``GET /snapshot`` — ``registry.snapshot()`` as JSON (one merged dict of
  every subsystem's counters; see obs.registry).
- ``GET /metrics``  — the same counters flattened to Prometheus text
  exposition (version 0.0.4): ``hivemall_tpu_<section>_<key> <value>``
  gauges, booleans as 0/1, non-numeric leaves skipped; dict leaves
  shaped by :meth:`obs.histo.Histogram.snapshot` become real histogram
  families (``_bucket{le=...}``/``_sum``/``_count``).
- ``GET /trace``    — the process tracer's span ring as Chrome-trace
  JSON (wall-clock-anchored; the fleet router merges these per-replica
  exports into one cross-process timeline).

Single-threaded on purpose: one handler at a time means a scrape can never
pile threads onto a training host; a slow scraper only delays the next
scrape, never the fit loop (providers are non-blocking by contract). The
server runs on a daemon thread and dies with the process.
"""

from __future__ import annotations

import http.server
import json
import re
import threading
from typing import Optional

from .registry import Registry, registry

__all__ = ["ObsServer", "ensure_server", "to_prometheus"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(parts) -> str:
    """Join a snapshot path into a valid Prometheus metric name: every
    illegal character becomes ``_`` (dots, dashes — snapshot keys are
    arbitrary provider strings) and a leading digit gets an underscore
    prefix (the grammar requires ``[a-zA-Z_:]`` first)."""
    name = _NAME_RE.sub("_", "_".join(parts))
    if name[:1].isdigit():
        name = "_" + name
    return name


def _fmt_value(val) -> str:
    # ints verbatim, floats via repr — NOT %g, which truncates to 6
    # significant digits and corrupts large counters
    # (examples=44776121 -> 4.47761e+07) and epoch timestamps
    return str(val) if isinstance(val, int) else repr(float(val))


def to_prometheus(snapshot: dict, prefix: str = "hivemall_tpu") -> str:
    """Flatten a registry snapshot into Prometheus text exposition.

    Numeric and boolean leaves become label-less gauges named by their
    dict path (``pipeline.batches_prepared`` ->
    ``hivemall_tpu_pipeline_batches_prepared``); strings/lists/None are
    presentation-only and are skipped (the JSON ``/snapshot`` carries
    them). Dict leaves carrying ``"_type": "histogram"``
    (:meth:`obs.histo.Histogram.snapshot`) export as real histogram
    families — cumulative ``_bucket{le="..."}`` series plus ``_sum`` and
    ``_count`` — so scrapers can ``histogram_quantile()`` over arbitrary
    windows instead of reading snapshot-time p99 gauges. Every family
    carries ``# HELP`` (its snapshot dot-path) and ``# TYPE``. The
    top-level ``ts`` is exported as ``<prefix>_snapshot_ts``.
    """
    lines = []
    # sanitization is lossy ("a.b" and "a_b" both become "a_b"), and two
    # families under one name is invalid exposition — scrapers merge or
    # reject them silently. Disambiguate the LATER arrival with a _dup<N>
    # suffix (its # HELP still carries the true dot-path) and count the
    # events in a <prefix>_name_collisions gauge so the hazard is
    # visible on the scrape itself instead of corrupting dashboards.
    seen: dict = {}                      # emitted name -> snapshot dot-path
    collisions = 0

    def uniq(parts):
        nonlocal collisions
        name = _metric_name(parts)
        path = ".".join(parts[1:])
        if name not in seen:
            seen[name] = path
            return name
        collisions += 1
        n = 2
        while f"{name}_dup{n}" in seen:
            n += 1
        name = f"{name}_dup{n}"
        seen[name] = path
        return name

    def walk(parts, val):
        if isinstance(val, bool):
            emit(parts, 1 if val else 0)
        elif isinstance(val, (int, float)):
            emit(parts, val)
        elif isinstance(val, dict):
            if val.get("_type") == "histogram":
                emit_histogram(parts, val)
                return
            for k in sorted(val):
                walk(parts + [str(k)], val[k])
        # str / list / None: no numeric reading — skipped

    def head(name, parts, mtype):
        lines.append(f"# HELP {name} {'.'.join(parts[1:])}")
        lines.append(f"# TYPE {name} {mtype}")

    def emit(parts, val):
        name = uniq(parts)
        head(name, parts, "gauge")
        lines.append(f"{name} {_fmt_value(val)}")

    def emit_histogram(parts, hist):
        name = uniq(parts)
        head(name, parts, "histogram")
        for bound, cum in hist.get("buckets") or []:
            le = "+Inf" if bound == "+Inf" else _fmt_value(bound)
            lines.append(f'{name}_bucket{{le="{le}"}} {int(cum)}')
        lines.append(f"{name}_sum {_fmt_value(float(hist.get('sum', 0.0)))}")
        lines.append(f"{name}_count {int(hist.get('count', 0))}")

    for section in sorted(snapshot):
        if section == "ts":
            walk([prefix, "snapshot", "ts"], snapshot[section])
        else:
            walk([prefix, section], snapshot[section])
    if collisions:
        name = f"{prefix}_name_collisions"
        lines.append(f"# HELP {name} sanitized metric names that collided "
                     f"(later arrivals renamed with a _dup suffix)")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {collisions}")
    return "\n".join(lines) + "\n"


class _Handler(http.server.BaseHTTPRequestHandler):
    # the registry to serve is attached per-server-class (see ObsServer)
    obs_registry: Registry = registry
    # per-connection socket timeout: the server handles ONE connection at
    # a time, so a client that connects and never sends a request line
    # (half-open TCP, port scanner) must not wedge /metrics for the run —
    # BaseHTTPRequestHandler turns the timeout into a clean close
    timeout = 10

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?", 1)[0]
        if path == "/snapshot":
            # default=str: a stray non-JSON leaf from a provider degrades
            # to its string form instead of killing the scrape
            body = json.dumps(self.obs_registry.snapshot(),
                              default=str).encode()
            ctype = "application/json"
        elif path == "/metrics":
            body = to_prometheus(self.obs_registry.snapshot()).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/trace":
            # the process tracer's span ring as Chrome-trace JSON; the
            # fleet router fetches this per replica and merges the events
            # (distinct pids) into one cross-process request flame
            from .trace import get_tracer
            body = json.dumps(get_tracer().chrome_dict()).encode()
            ctype = "application/json"
        else:
            self.send_error(404, "unknown path (try /snapshot, /metrics "
                                 "or /trace)")
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):     # scrapes must not spam the trainer's
        pass                          # stderr


class _QuietHTTPServer(http.server.HTTPServer):
    def handle_error(self, request, client_address):
        # a scraper disconnecting mid-response (BrokenPipeError etc.) is
        # routine, not a traceback on the trainer's stderr
        pass


class ObsServer:
    """Single-threaded HTTP server over an obs registry.

    ``port=0`` binds an ephemeral port (resolved in ``self.port`` after
    construction). ``start()`` serves on a daemon thread; ``stop()`` shuts
    it down. Loopback-only by default — this is an operator surface, not a
    public API; bind ``host="0.0.0.0"`` explicitly for cluster scrapes.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 obs_registry: Optional[Registry] = None):
        handler = type("_BoundHandler", (_Handler,),
                       {"obs_registry": obs_registry or registry})
        self._httpd = _QuietHTTPServer((host, port), handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"obs-http:{self.port}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_server: Optional[ObsServer] = None
_server_lock = threading.Lock()


def ensure_server(port: int, host: str = "127.0.0.1") -> Optional[ObsServer]:
    """Idempotent process-wide server for the ``-obs_port`` option: the
    first caller binds, later callers (a second trainer in the same
    process) reuse it. A bind failure warns and returns None — the obs
    surface must never take training down."""
    global _server
    with _server_lock:
        if _server is not None:
            if port and port != _server.port:
                import warnings
                warnings.warn(
                    f"obs HTTP server already bound to port "
                    f"{_server.port}; -obs_port {port} is ignored "
                    f"(one server per process)",
                    RuntimeWarning, stacklevel=2)
            return _server
        try:
            _server = ObsServer(port, host).start()
        except OSError as e:
            import warnings
            warnings.warn(f"obs HTTP server failed to bind port {port}: {e};"
                          " /snapshot and /metrics are unavailable",
                          RuntimeWarning, stacklevel=2)
            return None
        return _server
