"""Black-box flight recorder — crash-safe wide events (docs/OBSERVABILITY.md).

The rest of the obs stack (spans, /metrics, SLO burn rates) is opt-in
and in-memory: when a replica is SIGKILLed nothing survives to explain
the death. This module is the aircraft-style black box: every process
appends compact "wide events" (request admitted/completed with trace id
and hop decomposition, engine reloads, promotion/rollback transitions,
retrain state edges, bulk-shard lifecycle, fault-injection hits) into a
fixed-size ring of slots inside an **mmap'd file**. Durability is by
construction — a store into a shared file mapping lands in the kernel
page cache immediately, so a process killed with ``kill -9`` leaves its
last events already on disk; no flush, no signal handler, no atexit.

Writer contract (mirrors ``obs.trace``):

- lock-free on the hot path: slot reservation is one ``next()`` on an
  ``itertools.count`` (atomic under the GIL — the same trick the trace
  id mint uses), then plain stores into the mapping; no lock, ever;
- when disabled, :meth:`FlightRecorder.record` is ONE attribute check
  and a return — and hot call sites additionally guard with
  ``if fl.enabled:`` so even the kwargs dict is never built (pinned by
  ``tests/test_flight.py::test_disabled_record_is_one_attribute_check``);
- a record() can never raise into the request path: a closed/failed
  ring degrades to dropping the event.

Torn-write detection: each slot carries its sequence number at the head
AND the tail; the writer stores head, payload, then tail, so a reader
(running post-mortem against a dead process's ring) accepts a slot only
when both match — a write interrupted mid-slot by SIGKILL fails the
check and is skipped instead of decoding garbage.

On-disk layout (little-endian, version 1)::

    header (256 bytes): magic "HMTPUFR1", version u32, slot_size u32,
        nslots u32, pid u32, anchor_wall f64, anchor_mono f64,
        label 64 bytes (utf-8, NUL padded)
    slot i (slot_size bytes, at 256 + i*slot_size):
        seq u64 | ts_wall f64 | payload_len u32 | payload bytes ...
        ... | seq & 0xFFFFFFFF as u32 in the slot's last 4 bytes

Payload = ``kind\\x1fkey=value\\x1f...`` utf-8, truncated to the slot.
Events carry wall-clock timestamps directly (one ``time.time()`` per
event), so independently-recorded rings — router, every replica, bulk
workers — merge onto ONE timeline by sort, the same wall-clock anchoring
the Chrome trace export uses for its cross-process merge.

Activation: ``HIVEMALL_TPU_FLIGHT=<dir>`` opens a per-process ring
``<dir>/<label>-<pid>.ring`` on first :func:`get_flight` use (label from
``HIVEMALL_TPU_FLIGHT_LABEL``, default ``pid<pid>``); the fleet manager
sets both for every replica it spawns and records each ring's path with
its respawn decisions. ``hivemall_tpu obs postmortem <dir>`` (backed by
:func:`merge_dir`) merges every ring under a run directory into one
ordered timeline, flags the recording gap around each death, and lists
each ring's admitted-but-never-completed request ids — the victim's
final seconds. The registry's ``flight`` section (events written,
overwrites, utilization) lets the recorder observe itself.
"""

from __future__ import annotations

import itertools
import json
import mmap as _mmap_mod
import os
import re
import struct
import threading
import time
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "get_flight", "configure_flight",
           "read_ring", "merge_dir", "render_postmortem",
           "emit_postmortem", "flight_stub", "FS", "pack_ids",
           "unpack_ids"]

MAGIC = b"HMTPUFR1"
VERSION = 1
HEADER_SIZE = 256
DEFAULT_SLOT = 192          # bytes per event slot (head 20 + tail 4 + payload)
DEFAULT_NSLOTS = 4096       # ~last 4k events per process survive a crash

_HDR = struct.Struct("<8sIIIIdd64s")
_HEAD = struct.Struct("<QdI")            # seq, ts_wall, payload_len
_TAIL = struct.Struct("<I")              # seq & 0xFFFFFFFF
#: field separator inside a payload — callers building a pre-formatted
#: ``line`` join their ``k=v`` pairs with this
FS = _FIELD_SEP = "\x1f"
_LABEL_RE = re.compile(r"[^A-Za-z0-9_.-]")

ENV_DIR = "HIVEMALL_TPU_FLIGHT"
ENV_LABEL = "HIVEMALL_TPU_FLIGHT_LABEL"
ENV_SLOTS = "HIVEMALL_TPU_FLIGHT_SLOTS"


class FlightRecorder:
    """One process's ring. Disabled (a dark no-op) until :meth:`open`."""

    def __init__(self):
        self.enabled = False
        self.path: Optional[str] = None
        self.label: Optional[str] = None
        self.truncated = 0               # payloads clipped to the slot
        self._mm = None
        self._f = None
        self._slot = DEFAULT_SLOT
        self._nslots = DEFAULT_NSLOTS
        self._cap = DEFAULT_SLOT - _HEAD.size - _TAIL.size
        self._seq = itertools.count(1)
        self._last_seq = 0               # last reserved seq (~= events)

    # -- lifecycle -----------------------------------------------------------
    def open(self, path: str, *, label: str = "",
             slot_size: int = DEFAULT_SLOT,
             nslots: int = DEFAULT_NSLOTS) -> "FlightRecorder":
        """Create (truncating) the ring file and map it. The file is
        fully sized up front so every later write is a pure store into
        the mapping — nothing on the hot path can block on allocation."""
        self.close()
        slot_size = max(64, int(slot_size))
        nslots = max(8, int(nslots))
        total = HEADER_SIZE + slot_size * nslots
        f = open(path, "w+b")
        try:
            f.truncate(total)
            mm = _mmap_mod.mmap(f.fileno(), total)
        except (OSError, ValueError):
            f.close()
            raise
        mm[:_HDR.size] = _HDR.pack(
            MAGIC, VERSION, slot_size, nslots, os.getpid(),
            time.time(), time.perf_counter(),
            (label or "").encode("utf-8", "replace")[:64])
        self._f, self._mm = f, mm
        self._slot, self._nslots = slot_size, nslots
        self._cap = slot_size - _HEAD.size - _TAIL.size
        self._seq = itertools.count(1)
        self._last_seq = 0
        self.truncated = 0
        self.path = path
        self.label = label or None
        self.enabled = True
        return self

    def open_dir(self, directory: str, *, label: str = "",
                 slot_size: int = DEFAULT_SLOT,
                 nslots: int = DEFAULT_NSLOTS) -> "FlightRecorder":
        """Open the ring as ``<dir>/<label>-<pid>.ring`` — pid in the
        name so a respawned replica writes a FRESH file and its dead
        predecessor's ring survives for the post-mortem."""
        os.makedirs(directory, exist_ok=True)
        safe = _LABEL_RE.sub("_", label) or f"pid{os.getpid()}"
        path = os.path.join(directory, f"{safe}-{os.getpid()}.ring")
        return self.open(path, label=label or safe,
                         slot_size=slot_size, nslots=nslots)

    def close(self) -> None:
        """Unmap and close (leaktrack hygiene — a drained replica must
        census clean). The file itself stays on disk: it IS the record."""
        self.enabled = False
        mm, f = self._mm, self._f
        self._mm = self._f = None
        if mm is not None:
            try:
                mm.close()
            except (OSError, ValueError):
                pass
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    # -- the hot path --------------------------------------------------------
    def record(self, kind: str, line: Optional[str] = None,
               **fields) -> None:
        """Append one wide event. Lock-free; safe from any thread; never
        raises. Disabled cost at THIS level is one attribute check —
        hot call sites guard with ``if fl.enabled:`` so the arguments
        are not even built when the recorder is dark.

        ``fields`` spell the event as keywords; the serving hot path
        passes ``line`` instead — a single pre-built
        ``"k=v\\x1fk=v"`` f-string, which skips the kwargs dict and the
        per-field format calls (~2x cheaper per event)."""
        if not self.enabled:
            return
        if line is not None:
            payload = (kind + _FIELD_SEP + line).encode("utf-8", "replace")
        elif fields:
            payload = (kind + _FIELD_SEP + _FIELD_SEP.join(
                f"{k}={v}" for k, v in fields.items())).encode(
                    "utf-8", "replace")
        else:
            payload = kind.encode("utf-8", "replace")
        n = len(payload)
        if n > self._cap:
            payload = payload[:self._cap]
            n = self._cap
            self.truncated += 1
        try:
            i = next(self._seq)          # GIL-atomic slot reservation
            off = HEADER_SIZE + ((i - 1) % self._nslots) * self._slot
            mm = self._mm
            _HEAD.pack_into(mm, off, i, time.time(), n)
            mm[off + _HEAD.size:off + _HEAD.size + n] = payload
            _TAIL.pack_into(mm, off + self._slot - _TAIL.size,
                            i & 0xFFFFFFFF)
            self._last_seq = i
        except (OSError, ValueError, TypeError, AttributeError):
            pass                         # closed/raced ring: drop, never raise

    # -- self-observation ----------------------------------------------------
    @property
    def events(self) -> int:
        return self._last_seq

    @property
    def dropped(self) -> int:
        """Events overwritten by the ring wrapping (the honest name for
        what a fixed ring does to history)."""
        return max(0, self._last_seq - self._nslots)

    def obs_section(self) -> dict:
        n = self._last_seq
        return {
            "enabled": self.enabled,
            "path": self.path,
            "label": self.label,
            "events": n,
            "dropped": self.dropped,
            "truncated": self.truncated,
            "ring_slots": self._nslots if self.enabled else 0,
            "slot_bytes": self._slot if self.enabled else 0,
            "utilization": round(min(1.0, n / self._nslots), 4)
            if self.enabled else 0.0,
        }


def flight_stub() -> dict:
    """The registry's ``flight`` section before any recorder opened —
    key-for-key the live :meth:`FlightRecorder.obs_section` shape."""
    return {"enabled": False, "path": None, "label": None, "events": 0,
            "dropped": 0, "truncated": 0, "ring_slots": 0,
            "slot_bytes": 0, "utilization": 0.0}


_flight: Optional[FlightRecorder] = None
_flight_lock = threading.Lock()


def get_flight() -> FlightRecorder:
    """The process-wide recorder, bound to ``$HIVEMALL_TPU_FLIGHT`` on
    first use and registered as the obs registry's ``flight`` section.
    An open failure leaves the recorder dark — the black box must never
    take the process down."""
    global _flight
    if _flight is None:
        with _flight_lock:
            if _flight is None:
                fr = FlightRecorder()
                d = os.environ.get(ENV_DIR, "")
                if d and d != "0":
                    label = os.environ.get(ENV_LABEL, "") \
                        or f"pid{os.getpid()}"
                    try:
                        nslots = int(os.environ.get(ENV_SLOTS, "")
                                     or DEFAULT_NSLOTS)
                        fr.open_dir(d, label=label, nslots=nslots)
                    except (OSError, ValueError):
                        pass
                from .registry import registry
                registry.register("flight", fr.obs_section)
                _flight = fr
    return _flight


def configure_flight(directory: Optional[str], *, label: str = "",
                     slot_size: int = DEFAULT_SLOT,
                     nslots: int = DEFAULT_NSLOTS) -> FlightRecorder:
    """Explicitly (re)bind the process recorder: open a fresh ring under
    ``directory`` (``None`` closes and leaves it dark). The fleet uses
    this to label the router's ring before traffic starts."""
    fr = get_flight()
    fr.close()
    if directory:
        try:
            fr.open_dir(directory, label=label, slot_size=slot_size,
                        nslots=nslots)
        except OSError:
            pass
    return fr


# -- reading / post-mortem ----------------------------------------------------

def _coerce(v: str):
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


def read_ring(path: str) -> dict:
    """Parse one ring file — tolerant by design (the interesting rings
    belong to dead processes): torn slots (head/tail seq mismatch) are
    counted and skipped, payloads decode with replacement. Returns
    ``{path, pid, label, ..., events: [...], torn}`` with events sorted
    in write order (by seq)."""
    with open(path, "rb") as f:
        buf = f.read()
    if len(buf) < HEADER_SIZE:
        raise ValueError(f"{path}: truncated flight ring "
                         f"({len(buf)} bytes)")
    magic, version, slot_size, nslots, pid, wall0, mono0, label_b = \
        _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"{path}: not a flight ring (bad magic)")
    label = label_b.rstrip(b"\x00").decode("utf-8", "replace")
    events: List[dict] = []
    torn = 0
    cap = slot_size - _HEAD.size - _TAIL.size
    for s in range(nslots):
        off = HEADER_SIZE + s * slot_size
        if off + slot_size > len(buf):
            break
        seq, ts, n = _HEAD.unpack_from(buf, off)
        if seq == 0:
            continue                     # never written
        (tail,) = _TAIL.unpack_from(buf, off + slot_size - _TAIL.size)
        if tail != (seq & 0xFFFFFFFF) or n > cap:
            torn += 1                    # SIGKILL mid-write: skip
            continue
        raw = buf[off + _HEAD.size:off + _HEAD.size + n]
        parts = raw.decode("utf-8", "replace").split(_FIELD_SEP)
        ev = {"seq": seq, "ts": ts, "kind": parts[0], "fields": {}}
        for p in parts[1:]:
            k, _, v = p.partition("=")
            ev["fields"][k] = _coerce(v)
        events.append(ev)
    events.sort(key=lambda e: e["seq"])
    return {"path": path, "pid": pid, "label": label or f"pid{pid}",
            "version": version, "slot_bytes": slot_size,
            "ring_slots": nslots, "anchor_wall": wall0,
            "events": events, "torn": torn}


def pack_ids(ids) -> str:
    """Compact ``"5-36,40"`` run-length encoding of (mostly ascending)
    int ids — how ``batch.done`` names every request it completed in ONE
    event, so per-request completion cost amortizes across the batch and
    a 256-request batch still fits a slot."""
    out: List[str] = []
    start = prev = None
    for i in ids:
        if start is None:
            start = prev = i
            continue
        if i == prev + 1:
            prev = i
            continue
        out.append(f"{start}-{prev}" if prev > start else f"{start}")
        start = prev = i
    if start is not None:
        out.append(f"{start}-{prev}" if prev > start else f"{start}")
    return ",".join(out)


def unpack_ids(s) -> List[int]:
    """Inverse of :func:`pack_ids`; tolerant of garbage tokens (the
    payload may have been truncated to its slot mid-token)."""
    out: List[int] = []
    for tok in str(s).split(","):
        a, _, b = tok.partition("-")
        try:
            if b:
                out.extend(range(int(a), int(b) + 1))
            else:
                out.append(int(a))
        except ValueError:
            continue
    return out


#: request-lifecycle kinds the uncompleted-scan correlates on
_ADMIT_KIND = "req.admit"
_DONE_KINDS = ("req.done", "req.err", "req.expired")
_BATCH_DONE_KIND = "batch.done"


def _uncompleted(events: List[dict], keep: int = 64) -> List[dict]:
    """Admitted-but-never-completed requests in one ring — the dead
    process's in-flight work. An admit whose matching done was simply
    overwritten by the wrap can only be older than every surviving done,
    so only the TAIL of the open set is meaningful; keep the last
    ``keep``."""
    open_reqs: Dict[int, dict] = {}
    for ev in events:
        if ev["kind"] == _BATCH_DONE_KIND:
            for rq in unpack_ids(ev["fields"].get("reqs", "")):
                open_reqs.pop(rq, None)
            continue
        rq = ev["fields"].get("req")
        if rq is None:
            continue
        if ev["kind"] == _ADMIT_KIND:
            open_reqs[rq] = ev
        elif ev["kind"] in _DONE_KINDS:
            open_reqs.pop(rq, None)
    tail = sorted(open_reqs.values(), key=lambda e: e["seq"])[-keep:]
    return [{"req": e["fields"].get("req"), "ts": e["ts"],
             "trace": e["fields"].get("trace"),
             "rows": e["fields"].get("rows")} for e in tail]


def merge_dir(directory: str, *, since: Optional[float] = None,
              gap_s: float = 1.0) -> dict:
    """The fleet-wide post-mortem: read every ``*.ring`` under
    ``directory`` (recursively — a run dir may nest per-replica dirs),
    merge all events onto one wall-clock timeline, flag each ring whose
    recording stops more than ``gap_s`` before the fleet's last event
    (the death gap), and list each ring's admitted-but-uncompleted
    request ids. ``since`` (epoch seconds) filters the merged timeline;
    gap/uncompleted analysis always runs on the full rings."""
    paths: List[str] = []
    for root, _dirs, files in os.walk(directory):
        paths.extend(os.path.join(root, fn) for fn in files
                     if fn.endswith(".ring"))
    rings: List[dict] = []
    unreadable: List[dict] = []
    for p in sorted(paths):
        try:
            rings.append(read_ring(p))
        except (OSError, ValueError) as e:
            unreadable.append({"path": p, "error": str(e)})
    merged: List[dict] = []
    end_ts = 0.0
    for r in rings:
        name = f"{r['label']}-{r['pid']}"
        r["name"] = name
        r["last_ts"] = r["events"][-1]["ts"] if r["events"] else None
        r["uncompleted"] = _uncompleted(r["events"])
        if r["last_ts"]:
            end_ts = max(end_ts, r["last_ts"])
        for ev in r["events"]:
            if since is not None and ev["ts"] < since:
                continue
            merged.append({"ring": name, **ev})
    merged.sort(key=lambda e: (e["ts"], e["seq"]))
    gaps = []
    for r in rings:
        if r["last_ts"] is None:
            continue
        gap = end_ts - r["last_ts"]
        if gap > gap_s:
            # this ring went silent while the rest of the fleet kept
            # recording — the signature of a death (or a wedged process)
            gaps.append({"ring": r["name"], "last_ts": r["last_ts"],
                         "gap_s": round(gap, 3),
                         "uncompleted": len(r["uncompleted"])})
    return {
        "dir": directory,
        "rings": [{k: r[k] for k in ("name", "path", "pid", "label",
                                     "last_ts", "torn", "uncompleted")}
                  | {"events": len(r["events"])} for r in rings],
        "unreadable": unreadable,
        "events": merged,
        "gaps": gaps,
        "since": since,
        "end_ts": end_ts or None,
    }


def _fmt_ts(ts: float) -> str:
    frac = f"{ts % 1.0:.3f}"[1:]
    return time.strftime("%H:%M:%S", time.localtime(ts)) + frac


def render_postmortem(merged: dict, tail: int = 200) -> str:
    """Human-readable timeline of :func:`merge_dir` output: the ring
    roster with death gaps, each dead ring's final uncompleted request
    ids, then the last ``tail`` merged events."""
    lines: List[str] = []
    events = merged["events"]
    n_rings = len(merged["rings"])
    span = ""
    if events:
        span = f", {_fmt_ts(events[0]['ts'])} .. {_fmt_ts(events[-1]['ts'])}"
    lines.append(f"flight postmortem: {n_rings} ring(s), "
                 f"{len(events)} event(s){span}")
    if merged.get("since"):
        lines.append(f"  (since {_fmt_ts(merged['since'])})")
    gap_by_ring = {g["ring"]: g for g in merged["gaps"]}
    for r in merged["rings"]:
        mark = ""
        g = gap_by_ring.get(r["name"])
        if g:
            mark = (f"  ** DEATH GAP: silent for {g['gap_s']}s before "
                    f"the fleet's last event **")
        torn = f", {r['torn']} torn slot(s)" if r["torn"] else ""
        lines.append(f"  {r['name']}: {r['events']} event(s){torn}{mark}")
        if g and r["uncompleted"]:
            ids = ", ".join(
                str(u["req"]) + (f" trace={u['trace']}"
                                 if u.get("trace") else "")
                for u in r["uncompleted"][-8:])
            lines.append(f"    admitted but never completed "
                         f"({len(r['uncompleted'])}): {ids}")
    for u in merged["unreadable"]:
        lines.append(f"  UNREADABLE {u['path']}: {u['error']}")
    show = events[-tail:] if tail and len(events) > tail else events
    if len(show) < len(events):
        lines.append(f"  ... {len(events) - len(show)} earlier event(s) "
                     f"elided (--tail {tail})")
    for ev in show:
        fields = " ".join(f"{k}={v}" for k, v in ev["fields"].items())
        lines.append(f"{_fmt_ts(ev['ts'])} [{ev['ring']}] {ev['kind']}"
                     + (f" {fields}" if fields else ""))
    return "\n".join(lines) + "\n"


def emit_postmortem(directory: str, out_path: Optional[str] = None,
                    tail: int = 200) -> Optional[str]:
    """Write the merged timeline next to the rings (JSON + the rendered
    text) — the fleet manager calls this when it detects an unexpected
    replica exit, so the post-mortem exists even if nobody runs the CLI.
    Never raises; returns the text path or None."""
    try:
        merged = merge_dir(directory)
        out = out_path or os.path.join(directory, "postmortem.txt")
        with open(out + ".json.tmp", "w") as f:
            json.dump(merged, f, default=str)
        os.replace(out + ".json.tmp", out + ".json")
        with open(out + ".tmp", "w") as f:
            f.write(render_postmortem(merged, tail=tail))
        os.replace(out + ".tmp", out)
        return out
    except (OSError, ValueError):
        return None
