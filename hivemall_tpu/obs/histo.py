"""Cumulative histograms — the Prometheus-native latency primitive.

The PR-4 obs surface exported only gauges snapshotted from rollups
(p50/p99 over the tracer's last-512 reservoir), which an external scraper
cannot window, rate, or aggregate across replicas. :class:`Histogram` is
the fix: fixed upper bounds, CUMULATIVE bucket counts (`le` semantics),
plus ``sum``/``count`` — exactly the Prometheus histogram type, so
``histogram_quantile()`` works over arbitrary scrape windows and the SLO
engine can diff two snapshots of the same histogram to get the true
latency distribution of any time window (obs.slo).

Lock-cheap by design: ``observe()`` does the bucket search (bisect over a
tuple, no allocation) OUTSIDE the lock and holds it only for three scalar
updates — the serve hot path calls this once per request and once per
batch. Readers (``snapshot()``) take the same lock briefly to copy the
counters, so a scrape can never tear a bucket array mid-increment.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Sequence

__all__ = ["Histogram", "quantile_from_buckets",
           "LATENCY_BUCKETS_S", "BATCH_SIZE_BUCKETS"]

#: request-latency bounds in SECONDS: sub-ms to 10 s, roughly
#: logarithmic — the serving SLO range (docs/OBSERVABILITY.md)
LATENCY_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: batch-size bounds in ROWS: the batcher's pow2 coalescing buckets
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class Histogram:
    """Thread-safe fixed-bucket cumulative histogram.

    ``bounds`` are inclusive upper bounds (Prometheus ``le``); an implicit
    ``+Inf`` bucket catches the tail. Counters only ever increase, so two
    snapshots taken at different times can be subtracted bucket-wise to
    recover the exact distribution of the interval between them.
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, bounds: Sequence[float]):
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = tuple(sorted(float(b) for b in bounds))
        self._counts = [0] * (len(self.bounds) + 1)   # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        """Record one observation. Bucket search happens outside the lock;
        the critical section is three scalar updates."""
        v = float(value)
        i = bisect_left(self.bounds, v)   # first bound >= v (le semantics)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> dict:
        """JSON-ready registry form, recognized by ``obs.http``'s
        Prometheus encoder (``_type: histogram`` → ``_bucket``/``_sum``/
        ``_count`` series) and consumed cumulatively by ``obs.slo``:

        ``{"_type": "histogram", "buckets": [[le, cumulative], ...,
        ["+Inf", total]], "sum": ..., "count": ...}``
        """
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._count
        cum = 0
        buckets = []
        for bound, c in zip(self.bounds, counts):
            cum += c
            buckets.append([bound, cum])
        buckets.append(["+Inf", cum + counts[-1]])
        return {"_type": "histogram", "buckets": buckets,
                "sum": round(s, 6), "count": n}


def quantile_from_buckets(buckets, q: float) -> float:
    """Estimate the ``q``-quantile from cumulative ``[le, count]`` pairs
    (a :meth:`Histogram.snapshot` ``buckets`` list, or a bucket-wise DIFF
    of two snapshots — the SLO engine's windowed-p99 path). Linear
    interpolation inside the winning bucket, Prometheus
    ``histogram_quantile`` style; the +Inf bucket clamps to the largest
    finite bound. Returns 0.0 for an empty distribution."""
    if not buckets:
        return 0.0
    total = buckets[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in buckets:
        if cum >= target:
            if bound == "+Inf":
                return float(prev_bound)
            if cum == prev_cum:          # degenerate: empty bucket hit
                return float(bound)
            frac = (target - prev_cum) / (cum - prev_cum)
            return float(prev_bound) + frac * (float(bound) - prev_bound)
        if bound != "+Inf":
            prev_bound, prev_cum = float(bound), cum
    return float(prev_bound)
