"""Unified telemetry for the training runtime (SURVEY.md §6).

The reference's observability was Hadoop progress counters, log4j, and the
MixServer's JMX beans. After the ingest-pipeline, fused-dispatch, and
fault-tolerance rounds the rebuild had four disjoint counter surfaces
(PipelineStats, the stager's stack/megabatch counters, MixClient/MixServer
counters, CheckpointManager) and a loss-cadence jsonl stream — but no way
to answer "where did this step's time go" or "is this live run healthy"
without reading bench output. This package is the layer that unifies them:

- :mod:`trace` — low-overhead span tracing (monotonic clock, thread-safe
  ring buffer, one attribute check when disabled) wired into the hot path
  at its real seams: ingest prep, megabatch stacking, h2d staging, the
  jitted (mega)step dispatch, MIX exchanges, checkpoint saves. Per-stage
  ``{count, total_s, p50, p99}`` rollups land in the jsonl metrics stream
  at the loss-fold cadence; the raw spans export as Chrome-trace JSON
  (chrome://tracing / Perfetto) alongside ``jax.profiler``.
- :mod:`registry` — the central counter registry every subsystem registers
  with; ``registry.snapshot()`` is ONE merged, JSON-ready dict
  (pipeline/stager, train progress, mix client+server, checkpoints, span
  rollups, metrics-stream health).
- :mod:`http` — opt-in single-threaded HTTP surface (``-obs_port``):
  ``/snapshot`` (JSON) and ``/metrics`` (Prometheus text exposition) off
  the registry — the MixServer's JMX peer, back.
- :mod:`report` — the ``hivemall_tpu obs <metrics.jsonl>`` terminal
  summary (rates, stage breakdown, breaker state, checkpoint age).
- :mod:`histo` — cumulative fixed-bucket histograms (the Prometheus
  ``_bucket/_sum/_count`` primitive) feeding serve request-latency and
  batch-size families on ``/metrics``, and window diffs in :mod:`slo`.
- :mod:`slo` — the fleet SLO engine: ring time series over serving
  totals, 5 m / 1 h error-budget burn rates (``/slo``), and in-tree
  changefinder drift detection over the latency and prediction-score
  streams (``slo_drift`` events in the metrics jsonl).

See docs/OBSERVABILITY.md for the event schema, span names, and the
"Serving traces and SLOs" tier (request-scoped trace propagation across
the serving fleet, per-hop latency breakdowns, burn-rate math).
"""

from .histo import Histogram
from .registry import Registry, registry
from .slo import SloEngine
from .trace import Tracer, get_tracer, mint_trace_id

__all__ = ["Registry", "registry", "Tracer", "get_tracer",
           "mint_trace_id", "Histogram", "SloEngine"]
