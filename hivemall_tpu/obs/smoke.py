"""Observability smoke — run by run_tests.sh (docs/OBSERVABILITY.md).

A seconds-scale fit with the full telemetry stack on, asserting the
acceptance surface of the obs subsystem:

1. the jsonl stream contains ``train_step``, ``train_done`` and
   ``span_rollup`` events and every line parses (no torn/interleaved
   writes from the multi-worker pipeline);
2. the ``train_done`` registry snapshot carries the pipeline, train, mix,
   checkpoint and spans sections, with hot-path spans actually recorded;
3. ``hivemall_tpu obs <file>`` renders the stream without error;
4. per-step tracing overhead stays within the budget (default 5%) vs. the
   same fit with tracing disabled — the "~no-op when disabled, cheap when
   enabled" contract, enforced where a regression would first show.

Timing method: the traced and untraced fits run as PAIRS with alternating
order (any machine drift hits both arms), and the overhead estimate is the
MINIMUM per-pair ratio over ``--repeats`` pairs — a real tracing
regression shows up in every pair, while one-sided load noise only
inflates individual pairs (measured span cost is ~2µs enabled / ~0.4µs
disabled, ≈0.5% of a smoke step; the budget guards against an order-of-
magnitude regression, not the noise floor). The metrics stream is ON in
both arms so the comparison isolates tracing itself.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def _make_batches(n_batches: int, bs: int, dims: int, seed: int = 7):
    from ..io.sparse import SparseBatch
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        idx = rng.integers(1, dims, (bs, 8)).astype(np.int32)
        val = rng.normal(size=(bs, 8)).astype(np.float32)
        lab = (rng.integers(0, 2, bs) * 2 - 1).astype(np.float32)
        out.append(SparseBatch(idx, val, lab))
    return out


def _fit_once(batches, metrics_path, dims: int, bs: int) -> float:
    """One fit_stream over prebuilt batches; returns wall seconds. A fresh
    trainer per run (the jitted step is config-cached process-wide, so no
    recompiles after the warmup run)."""
    import hivemall_tpu.utils.metrics as M
    from ..models.linear import GeneralClassifier
    old = M._stream
    M._stream = M.MetricsStream(metrics_path)
    try:
        tr = GeneralClassifier(
            f"-dims {dims} -mini_batch {bs} -eta fixed -eta0 0.1 -reg no "
            f"-ingest_workers 2")
        t0 = time.perf_counter()
        tr.fit_stream(iter(batches))
        return time.perf_counter() - t0
    finally:
        M._stream.close()
        M._stream = old


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="hivemall_tpu.obs.smoke")
    ap.add_argument("--batches", type=int, default=768,
                    help="steps per fit (>=257 so a fold-cadence rollup "
                         "lands)")
    ap.add_argument("--bs", type=int, default=64)
    ap.add_argument("--dims", type=int, default=1024)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--overhead-budget", type=float, default=0.05,
                    help="max (traced - untraced) / untraced")
    args = ap.parse_args(argv)

    from ..obs.trace import get_tracer
    tracer = get_tracer()
    tmp = tempfile.mkdtemp(prefix="hivemall_tpu_obs_smoke_")
    try:
        return _run(args, tracer, tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)   # run_tests.sh runs this
                                                 # every time — no litter


def _run(args, tracer, tmp: str) -> int:
    from ..obs.report import load_events, render_file
    batches = _make_batches(args.batches, args.bs, args.dims)

    # warmup: compile the jitted step outside every timed arm
    tracer.disable()
    _fit_once(batches, os.path.join(tmp, "warmup.jsonl"), args.dims, args.bs)

    t_off = t_on = overhead = float("inf")
    traced_path = os.path.join(tmp, "traced.jsonl")

    def run(traced: bool, rep: int) -> float:
        if traced:
            tracer.enable()
            tracer.reset()              # spans, like the stream below,
            if os.path.exists(traced_path):  # describe ONE run — the
                os.remove(traced_path)       # assertions depend on it
            path = traced_path
        else:
            tracer.disable()
            path = os.path.join(tmp, f"off{rep}.jsonl")
        return _fit_once(batches, path, args.dims, args.bs)

    for rep in range(max(1, args.repeats)):
        first_traced = bool(rep % 2)    # alternate order within the pair
        a = run(first_traced, rep)
        b = run(not first_traced, rep)
        on, off = (a, b) if first_traced else (b, a)
        t_on, t_off = min(t_on, on), min(t_off, off)
        overhead = min(overhead, (on - off) / max(off, 1e-9))
    tracer.disable()

    failures = []

    # 1. stream integrity + required events
    events, bad = load_events(traced_path)
    if bad:
        failures.append(f"{bad} unparsable jsonl lines in {traced_path}")
    names = {e["event"] for e in events}
    for need in ("train_step", "train_done", "span_rollup"):
        if need not in names:
            failures.append(f"stream missing required event {need!r} "
                            f"(got {sorted(names)})")

    # 2. the train_done snapshot carries every acceptance section and the
    #    hot-path spans really recorded
    done = [e for e in events if e["event"] == "train_done"]
    snap = done[-1].get("telemetry", {}) if done else {}
    for section in ("pipeline", "train", "mix", "checkpoint", "spans"):
        if section not in snap:
            failures.append(f"train_done telemetry missing {section!r}")
    spans = snap.get("spans", {})
    for stage in ("dispatch.step", "ingest.prep"):
        if spans.get(stage, {}).get("count", 0) <= 0:
            failures.append(f"no {stage!r} spans recorded")
    # stage attribution sanity: the traced stages should account for most
    # of the measured wall (CPU backend: dispatch is synchronous compute)
    total_span_s = sum(s.get("total_s", 0.0) for s in spans.values()
                       if isinstance(s, dict))   # skip spans.dropped
    if total_span_s > 3.0 * t_on:
        failures.append(f"span total {total_span_s:.3f}s implausibly "
                        f"exceeds wall {t_on:.3f}s")

    # 3. the obs CLI renders it
    try:
        rc = render_file(traced_path)
        if rc != 0:
            failures.append(f"obs render exited {rc}")
    except Exception as e:              # noqa: BLE001 — smoke must report
        failures.append(f"obs render raised {type(e).__name__}: {e}")

    # 4. tracing overhead budget (min-over-pairs; see module docstring)
    if overhead > args.overhead_budget:
        failures.append(
            f"tracing overhead {overhead * 100:.1f}% exceeds "
            f"{args.overhead_budget * 100:.0f}% budget "
            f"(traced {t_on:.3f}s vs untraced {t_off:.3f}s)")

    steps_s = args.batches / t_on
    print(f"obs smoke: {args.batches} steps, traced {t_on:.3f}s "
          f"({steps_s:.0f} steps/s), untraced {t_off:.3f}s, "
          f"overhead {overhead * 100:+.1f}%, "
          f"{len(events)} events, {len(failures)} failures",
          file=sys.stderr)
    for f in failures:
        print(f"obs smoke FAILURE: {f}", file=sys.stderr)
    if not failures:
        print(json.dumps({"metric": "obs_smoke_traced_steps_per_sec",
                          "value": round(steps_s, 1),
                          "overhead_fraction": round(overhead, 4)}))
    return min(len(failures), 125)


if __name__ == "__main__":
    sys.exit(main())
