"""``hivemall_tpu obs <metrics.jsonl>`` — live-run summary off the stream.

Tails/aggregates a jsonl metrics file (the ``HIVEMALL_TPU_METRICS`` sink)
into a terminal summary: event counts, current training rate, the
per-stage span breakdown (from the latest ``span_rollup``), MIX breaker
state and checkpoint age (from the latest registry snapshot carried by
``telemetry`` / ``train_done`` events), and metrics-stream health
(dropped events, rotations). ``--follow`` re-renders as the file grows —
the poor ops engineer's ``watch`` for a soak run.

Robustness contract: a metrics file from a live (or crashed) run may end
in a torn line and may interleave events from several trainers;
unparsable lines are counted, never fatal. Follow mode is built for
soaks: each tick reads only the appended bytes and folds them into
BOUNDED incremental aggregates (counts + newest record per event type) —
memory and per-tick work stay O(1) in the file's history.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["load_events", "summarize", "render_file", "render_slo",
           "render_slo_source", "parse_since"]


def parse_since(v) -> Optional[float]:
    """The shared ``--since`` grammar (``obs`` and ``obs postmortem``):
    values under 1e9 are "seconds ago" (``--since 300`` = the last five
    minutes), larger values are an absolute epoch timestamp."""
    if v is None:
        return None
    s = float(v)
    # event `ts` fields are wall-clock epoch by schema; a relative
    # --since can only anchor against wall "now"
    return time.time() - s if s < 1e9 else s  # graftcheck: disable=GC02


class _TailState:
    """Bounded aggregates over a stream of events: per-event counts,
    the newest record per event type, the newest registry snapshot, the
    ts range, and the unparsable-line count. Everything the renderer
    needs, in O(1) memory."""

    def __init__(self, since: Optional[float] = None):
        self.counts: Dict[str, int] = {}
        self.last: Dict[str, dict] = {}
        self.snapshot: Optional[dict] = None
        self.t_lo: Optional[float] = None
        self.t_hi: Optional[float] = None
        self.bad = 0
        self.total = 0
        self.since = since

    def add(self, rec: dict) -> None:
        name = rec["event"]
        ts0 = rec.get("ts")
        if self.since is not None and isinstance(ts0, (int, float)) \
                and ts0 < self.since:
            return                       # --since: before the window
        self.total += 1
        self.counts[name] = self.counts.get(name, 0) + 1
        self.last[name] = rec
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            self.t_lo = ts if self.t_lo is None else min(self.t_lo, ts)
            self.t_hi = ts if self.t_hi is None else max(self.t_hi, ts)
        if name == "telemetry" and isinstance(rec.get("snapshot"), dict):
            self.snapshot = rec["snapshot"]
        elif name == "train_done" and isinstance(rec.get("telemetry"),
                                                 dict):
            self.snapshot = rec["telemetry"]

    def feed_lines(self, raw: bytes) -> None:
        """Fold the complete jsonl lines in ``raw`` into the aggregates;
        unparsable lines are counted in ``bad``."""
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                self.bad += 1
                continue
            if isinstance(rec, dict) and "event" in rec:
                self.add(rec)
            else:
                self.bad += 1


def load_events(path: str) -> Tuple[List[dict], int]:
    """All parsable events in ``path`` plus the count of unparsable lines
    (torn tail of a live run, partial writes after a crash)."""
    events: List[dict] = []
    bad = 0
    with open(path, "rb") as f:
        for line in f.read().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict) and "event" in rec:
                events.append(rec)
            else:
                bad += 1
    return events, bad


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def _render(state: _TailState, path: str = "",
            now: Optional[float] = None) -> str:
    now = time.time() if now is None else now
    if not state.total:
        return (f"obs: {path or 'stream'}: no parsable events"
                + (f" ({state.bad} unparsable lines)" if state.bad else ""))
    out: List[str] = []
    span_s = 0.0
    if state.t_lo is not None and state.t_hi is not None:
        span_s = max(0.0, state.t_hi - state.t_lo)
    head = (f"obs: {path or 'stream'} — {state.total} events over "
            f"{span_s:.1f}s")
    if state.bad:
        head += f" ({state.bad} unparsable lines)"
    out.append(head)
    out.append("events: " + ", ".join(
        f"{k} x{v}" for k, v in sorted(state.counts.items())))

    # newest progress record wins: a finished run's train_done carries the
    # final step/examples, a live run only has train_step so far
    candidates = [r for r in (state.last.get("train_step"),
                              state.last.get("train_done")) if r]
    step = max(candidates, key=lambda r: r.get("step", 0), default=None)
    if step is not None:
        line = (f"train:  {step.get('trainer', '?')} step {step.get('step')}"
                f"  examples {step.get('examples')}")
        if "examples_per_sec" in step:
            line += f"  rate {step['examples_per_sec']}/s"
        if "avg_loss" in step:
            line += f"  avg_loss {step['avg_loss']}"
        if state.counts.get("train_done"):
            line += "  [done]"
        out.append(line)

    roll = state.last.get("span_rollup")
    snap = state.snapshot
    stages = (roll or {}).get("stages") \
        or ((snap or {}).get("spans") if snap else None)
    if stages:
        # the spans section carries one scalar beside the stage dicts
        # (`dropped`, the ring-overflow counter) — filter to real stages
        stages = {n: s for n, s in stages.items() if isinstance(s, dict)}
    if stages:
        total = sum(s.get("total_s", 0.0) for s in stages.values()) or 1.0
        out.append("stages (latest rollup):")
        width = max(len(n) for n in stages)
        for name in sorted(stages,
                           key=lambda n: -stages[n].get("total_s", 0.0)):
            s = stages[name]
            out.append(
                f"  {name:<{width}}  count {s.get('count', 0):>7}  "
                f"total {_fmt_s(s.get('total_s', 0.0)):>9}  "
                f"p50 {_fmt_s(s.get('p50', 0.0)):>9}  "
                f"p99 {_fmt_s(s.get('p99', 0.0)):>9}  "
                f"({100.0 * s.get('total_s', 0.0) / total:4.1f}%)")
        dropped = ((snap or {}).get("spans") or {}).get("dropped")
        if isinstance(dropped, int) and dropped > 0:
            out.append(f"  (span ring overflowed: {dropped} spans dropped)")

    dp = (snap or {}).get("devprof") or {}
    if dp.get("compiles") or dp.get("active"):
        line = (f"profile: compiles {dp.get('compiles', 0)} "
                f"({_fmt_s(dp.get('compile_seconds', 0.0))})"
                f"  retraces {dp.get('retraces', 0)}")
        builds = dp.get("builds") or {}
        if builds:
            n_builds = sum(b.get("count", 0) for b in builds.values()
                           if isinstance(b, dict))
            line += f"  builds {n_builds} ({len(builds)} factories)"
        if dp.get("shape_buckets"):
            line += f"  buckets {dp['shape_buckets']}"
        out.append(line)
        mem = dp.get("memory") or {}
        if mem.get("live_bytes") or mem.get("bytes_in_use"):
            line = (f"memory: live {mem.get('live_bytes', 0) / 1e6:.1f}MB "
                    f"in {mem.get('live_arrays', 0)} arrays")
            if mem.get("bytes_in_use"):
                line += f"  in_use {mem['bytes_in_use'] / 1e6:.1f}MB"
            if dp.get("peak_dispatch_bytes"):
                line += (f"  dispatch_peak "
                         f"{dp['peak_dispatch_bytes'] / 1e6:.1f}MB")
            out.append(line)
        drift = dp.get("drift") or {}
        if drift.get("train_events") or drift.get("mem_events"):
            out.append(f"drift:  train x{drift.get('train_events', 0)}  "
                       f"mem x{drift.get('mem_events', 0)}")

    if snap:
        mix = snap.get("mix") or {}
        if mix.get("active"):
            out.append(
                f"mix:    breaker {mix.get('breaker_state', '?')}"
                f"  exchanges {mix.get('exchanges', 0)}"
                f"  dropped {mix.get('dropped_exchanges', 0)}"
                f"  transport_errors {mix.get('transport_errors', 0)}"
                f"  alive {mix.get('alive')}")
        ms = snap.get("metrics_stream") or {}
        if ms:
            out.append(f"stream: dropped_events {ms.get('dropped_events', 0)}"
                       f"  rotations {ms.get('rotations', 0)}")

    ck = state.last.get("checkpoint")
    if ck is not None:
        # event `ts` fields are wall-clock by schema (cross-process jsonl
        # merge); diffing against wall "now" is the only coherent read
        age = now - ck.get("ts", now)  # graftcheck: disable=GC02
        where = ck.get("path", "?")
        at = (f"step {ck['step']}" if "step" in ck
              else f"epoch {ck.get('epoch', '?')}")
        out.append(f"ckpt:   last at {at}, {age:.1f}s ago -> {where}")

    # promotion control plane (docs/RELIABILITY.md "Promotion and
    # rollback"): the registry section when a snapshot carries one, plus
    # the newest gate/rollback events from the stream itself
    promo = (snap or {}).get("promotion") or {}
    has_events = any(state.counts.get(e) for e in
                     ("promotion", "promotion_gate", "promotion_rollback",
                      "retrain_wanted"))
    if promo.get("configured") or has_events:
        line = (f"promo:  step {promo.get('promoted_step', '?')} "
                f"[{promo.get('state', '?')}]"
                f"  gate {promo.get('gate_passes', 0)} pass"
                f"/{promo.get('gate_failures', 0)} fail"
                f"  promotions {promo.get('promotions', 0)}"
                f"  rollbacks {promo.get('rollbacks', 0)}"
                f"  retrain_wanted {promo.get('retrain_wanted', 0)}"
                f"/acked {promo.get('retrain_acked', 0)}")
        canary = promo.get("canary") or {}
        if canary.get("active"):
            line += (f"  [canary step {canary.get('step')} x"
                     f"{canary.get('cohort')} baking "
                     f"{canary.get('age_seconds')}s]")
        out.append(line)
        g = state.last.get("promotion_gate")
        if g is not None:
            line = (f"  gate:  {g.get('verdict', '?')} "
                    f"{g.get('bundle', '?')} (step {g.get('step')})")
            if g.get("reasons"):
                line += f" — {g['reasons'][0]}"
            out.append(line)
        rb = state.last.get("promotion_rollback")
        if rb is not None:
            out.append(f"  rollback: {rb.get('bundle', '?')} — "
                       f"{rb.get('reason', '?')}")

    # retrain autopilot (docs/RELIABILITY.md "Autonomous retraining"):
    # the registry section when a snapshot carries one, plus the newest
    # state-transition event from the stream
    rt = (snap or {}).get("retrain") or {}
    if rt.get("configured") or state.counts.get("retrain"):
        line = (f"retrain: [{rt.get('state', '?')}]"
                f"  attempts {rt.get('attempts', 0)}"
                f"  ok {rt.get('successes', 0)}"
                f"  rejected {rt.get('rejections', 0)}"
                f"  rollbacks {rt.get('rollbacks', 0)}"
                f"  flaps {rt.get('flaps', 0)}"
                f"  votes {rt.get('votes_seen', 0)}"
                f"/acked {rt.get('votes_acked', 0)}")
        rp = rt.get("replay") or {}
        if rp.get("rows"):
            line += (f"  replay {rp.get('rows', 0)} rows/"
                     f"{rp.get('segments', 0)} seg")
        out.append(line)
        ev = state.last.get("retrain")
        if ev is not None and (ev.get("reason") or ev.get("outcome")):
            out.append(f"  last: {ev.get('outcome') or ev.get('state')}"
                       f" — {ev.get('reason', '?')}")

    # bulk offline scoring (docs/PERFORMANCE.md "Bulk scoring"): a live
    # job's registry section when a snapshot carries one; otherwise the
    # newest `bulk` stream event (the job emits its section per shard)
    bk = (snap or {}).get("bulk") or {}
    if not (bk.get("active") or bk.get("rows_scored")):
        bk = state.last.get("bulk") or bk
    if bk.get("active") or bk.get("rows_scored"):
        out.append(
            f"bulk:   [{'scoring' if bk.get('active') else 'done'}]"
            f"  shards {bk.get('shards_done', 0)}"
            f"/{bk.get('shards_total', 0)}"
            f"  rows {bk.get('rows_scored', 0)}"
            f"  rate {bk.get('rows_per_sec', 0)}/s"
            f"  backend {bk.get('backend') or '?'}"
            f"/{bk.get('precision') or '?'}"
            f"  workers {bk.get('workers', 0)}"
            f" util {bk.get('worker_utilization', 0)}")

    # black-box flight recorder (docs/OBSERVABILITY.md "Flight
    # recorder"): the ring's self-census when a snapshot carries one
    fli = (snap or {}).get("flight") or {}
    if fli.get("enabled") or fli.get("events"):
        out.append(
            f"flight: [{'recording' if fli.get('enabled') else 'closed'}]"
            f"  events {fli.get('events', 0)}"
            f"  dropped {fli.get('dropped', 0)}"
            f"  truncated {fli.get('truncated', 0)}"
            f"  util {fli.get('utilization', 0.0)}"
            f"  ring {fli.get('path') or '?'}")
    return "\n".join(out)


def summarize(events: List[dict], bad: int = 0, path: str = "",
              now: Optional[float] = None,
              since: Optional[float] = None) -> str:
    """Render the summary text for one loaded event list."""
    state = _TailState(since=since)
    for rec in events:
        state.add(rec)
    state.bad = bad
    return _render(state, path=path, now=now)


class _FollowTail:
    """One incremental follow of a metrics jsonl path: each :meth:`tick`
    folds only the bytes appended since the last tick into the bounded
    aggregates and returns the re-rendered summary (or None when nothing
    new landed). Factored out of :func:`render_file` so the
    rotation-under-follow contract is testable without driving a thread
    through the sleep loop.

    Rotation contract (``HIVEMALL_TPU_METRICS_MAX_MB``): when
    ``MetricsStream._rotate`` replaces ``<path>`` with a FRESH file (the
    old generation moves to ``<path>.1``), the tail detects the inode
    change and REOPENS ``<path>`` from offset 0 — it never opens
    ``<path>.1``, so rotated-away history is not replayed into the
    aggregates (events already folded stay folded; a generation rotated
    fully away between ticks is lost, by design). A bare truncation
    (same inode, smaller size) likewise restarts from the head. A stat
    or open that lands in the replace window (file briefly absent)
    retries next tick."""

    def __init__(self, path: str, since: Optional[float] = None):
        self.path = path
        self.state = _TailState(since=since)
        self._offset = 0
        self._ino: Optional[int] = None

    def tick(self) -> Optional[str]:
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            # rotation window: MetricsStream._rotate has os.replace'd
            # the file and not yet re-opened it — retry next tick
            return None
        size = st.st_size
        # rotation = a FRESH file replaced the tailed one (inode change —
        # size alone can't tell when the new file already grew past the
        # old offset) or in-place truncation: restart from the head.
        if self._ino is None:
            self._ino = st.st_ino
        elif st.st_ino != self._ino:
            self._ino, self._offset = st.st_ino, 0
        if size < self._offset:
            self._offset = 0
        if size <= self._offset:
            return None
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except FileNotFoundError:        # rotated between stat and open
            return None
        nl = data.rfind(b"\n")
        if nl < 0:                       # complete lines only; the torn
            return None                  # tail waits for its newline
        self._offset += nl + 1
        self.state.feed_lines(data[:nl + 1])
        return _render(self.state, path=self.path)


def render_file(path: str, follow: bool = False,
                interval: float = 2.0,
                since: Optional[float] = None) -> int:
    """Print the summary for ``path``; with ``follow`` re-render whenever
    the file grows (Ctrl-C exits). Returns a process exit code.

    Follow mode tails INCREMENTALLY via :class:`_FollowTail`: each tick
    reads only the appended bytes, folds them into the bounded
    aggregates, and defers a partial trailing line — a record mid-write
    is read whole on the next tick, never counted as torn. A file
    replaced by ``HIVEMALL_TPU_METRICS_MAX_MB`` rotation is reopened
    from its head without replaying ``<path>.1``."""
    if not os.path.exists(path):
        print(f"obs: {path}: no such file", file=sys.stderr)
        return 1
    if not follow:
        events, bad = load_events(path)
        print(summarize(events, bad, path=path, since=since))
        return 0
    tail = _FollowTail(path, since=since)
    try:
        while True:
            out = tail.tick()
            if out is not None:
                print(out)
                print("-" * 60)
            time.sleep(max(0.1, interval))
    except KeyboardInterrupt:
        return 0


# --- serving SLO report (docs/OBSERVABILITY.md "Serving traces and SLOs")


def render_slo(slo: dict, source: str = "") -> str:
    """Human rendering of a serve/router ``/slo`` payload: targets, the
    per-window burn-rate table, and recent drift events."""
    t = slo.get("targets") or {}
    out = [f"slo: {source or 'serving'} — targets: "
           f"p99 <= {t.get('p99_ms', '?')}ms, "
           f"availability >= {t.get('availability', '?')}"
           f"  ({slo.get('samples', 0)} samples)"]
    wins = slo.get("windows") or {}
    if not wins:
        out.append("  no samples yet")
    for name in sorted(wins, key=lambda k: wins[k].get("seconds", 0)):
        w = wins[name]
        p99 = w.get("p99_ms")
        out.append(
            f"  {name:>3}: qps {w.get('qps', 0):>8}  "
            f"avail {w.get('availability', 1.0):.6f} "
            f"(burn {w.get('availability_burn_rate', 0.0):g}x)  "
            f"p99 {('%.1fms' % p99) if p99 is not None else '—':>9}  "
            f"over-slo {100.0 * w.get('frac_over_slo', 0.0):.2f}% "
            f"(burn {w.get('latency_burn_rate', 0.0):g}x)")
    sc = slo.get("score")
    if sc:
        out.append(f"  score: mean {sc.get('mean')}  std {sc.get('std')}")
    dr = slo.get("drift") or {}
    out.append(f"  drift: latency x{dr.get('latency_events', 0)}  "
               f"score x{dr.get('score_events', 0)}  "
               f"retrain_wanted x{dr.get('retrain_wanted', 0)} "
               f"(acked x{dr.get('retrain_acked', 0)})")
    for ev in (dr.get("recent") or [])[-4:]:
        out.append(f"    [{ev.get('series')}] change "
                   f"{ev.get('change_score')} at value {ev.get('value')} "
                   f"(ts {ev.get('ts')})")
    return "\n".join(out)


def _fetch_slo(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        import urllib.request
        url = source.rstrip("/")
        if not url.endswith("/slo"):
            url += "/slo"
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.loads(resp.read())
    with open(source, "rb") as f:
        return json.loads(f.read())


def render_slo_source(source: str, follow: bool = False,
                      interval: float = 2.0) -> int:
    """``hivemall_tpu obs --slo <url-or-file>``: fetch and render the SLO
    report; ``--follow`` re-renders on the poll interval."""
    try:
        print(render_slo(_fetch_slo(source), source=source))
    except (OSError, ValueError) as e:
        print(f"obs --slo: {source}: {e}", file=sys.stderr)
        return 1
    if not follow:
        return 0
    try:
        while True:
            time.sleep(max(0.1, interval))
            try:
                print("-" * 60)
                print(render_slo(_fetch_slo(source), source=source))
            except (OSError, ValueError) as e:
                print(f"obs --slo: {source}: {e}", file=sys.stderr)
    except KeyboardInterrupt:
        return 0
