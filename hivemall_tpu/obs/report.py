"""``hivemall_tpu obs <metrics.jsonl>`` — live-run summary off the stream.

Tails/aggregates a jsonl metrics file (the ``HIVEMALL_TPU_METRICS`` sink)
into a terminal summary: event counts, current training rate, the
per-stage span breakdown (from the latest ``span_rollup``), MIX breaker
state and checkpoint age (from the latest registry snapshot carried by
``telemetry`` / ``train_done`` events), and metrics-stream health
(dropped events, rotations). ``--follow`` re-renders as the file grows —
the poor ops engineer's ``watch`` for a soak run.

Robustness contract: a metrics file from a live (or crashed) run may end
in a torn line and may interleave events from several trainers;
unparsable lines are counted, never fatal. Follow mode is built for
soaks: each tick reads only the appended bytes and folds them into
BOUNDED incremental aggregates (counts + newest record per event type) —
memory and per-tick work stay O(1) in the file's history.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["load_events", "summarize", "render_file"]


class _TailState:
    """Bounded aggregates over a stream of events: per-event counts,
    the newest record per event type, the newest registry snapshot, the
    ts range, and the unparsable-line count. Everything the renderer
    needs, in O(1) memory."""

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self.last: Dict[str, dict] = {}
        self.snapshot: Optional[dict] = None
        self.t_lo: Optional[float] = None
        self.t_hi: Optional[float] = None
        self.bad = 0
        self.total = 0

    def add(self, rec: dict) -> None:
        name = rec["event"]
        self.total += 1
        self.counts[name] = self.counts.get(name, 0) + 1
        self.last[name] = rec
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            self.t_lo = ts if self.t_lo is None else min(self.t_lo, ts)
            self.t_hi = ts if self.t_hi is None else max(self.t_hi, ts)
        if name == "telemetry" and isinstance(rec.get("snapshot"), dict):
            self.snapshot = rec["snapshot"]
        elif name == "train_done" and isinstance(rec.get("telemetry"),
                                                 dict):
            self.snapshot = rec["telemetry"]

    def feed_lines(self, raw: bytes) -> None:
        """Fold the complete jsonl lines in ``raw`` into the aggregates;
        unparsable lines are counted in ``bad``."""
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                self.bad += 1
                continue
            if isinstance(rec, dict) and "event" in rec:
                self.add(rec)
            else:
                self.bad += 1


def load_events(path: str) -> Tuple[List[dict], int]:
    """All parsable events in ``path`` plus the count of unparsable lines
    (torn tail of a live run, partial writes after a crash)."""
    events: List[dict] = []
    bad = 0
    with open(path, "rb") as f:
        for line in f.read().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict) and "event" in rec:
                events.append(rec)
            else:
                bad += 1
    return events, bad


def _fmt_s(v: float) -> str:
    return f"{v * 1e3:.2f}ms" if v < 1.0 else f"{v:.3f}s"


def _render(state: _TailState, path: str = "",
            now: Optional[float] = None) -> str:
    now = time.time() if now is None else now
    if not state.total:
        return (f"obs: {path or 'stream'}: no parsable events"
                + (f" ({state.bad} unparsable lines)" if state.bad else ""))
    out: List[str] = []
    span_s = 0.0
    if state.t_lo is not None and state.t_hi is not None:
        span_s = max(0.0, state.t_hi - state.t_lo)
    head = (f"obs: {path or 'stream'} — {state.total} events over "
            f"{span_s:.1f}s")
    if state.bad:
        head += f" ({state.bad} unparsable lines)"
    out.append(head)
    out.append("events: " + ", ".join(
        f"{k} x{v}" for k, v in sorted(state.counts.items())))

    # newest progress record wins: a finished run's train_done carries the
    # final step/examples, a live run only has train_step so far
    candidates = [r for r in (state.last.get("train_step"),
                              state.last.get("train_done")) if r]
    step = max(candidates, key=lambda r: r.get("step", 0), default=None)
    if step is not None:
        line = (f"train:  {step.get('trainer', '?')} step {step.get('step')}"
                f"  examples {step.get('examples')}")
        if "examples_per_sec" in step:
            line += f"  rate {step['examples_per_sec']}/s"
        if "avg_loss" in step:
            line += f"  avg_loss {step['avg_loss']}"
        if state.counts.get("train_done"):
            line += "  [done]"
        out.append(line)

    roll = state.last.get("span_rollup")
    snap = state.snapshot
    stages = (roll or {}).get("stages") \
        or ((snap or {}).get("spans") if snap else None)
    if stages:
        total = sum(s.get("total_s", 0.0) for s in stages.values()) or 1.0
        out.append("stages (latest rollup):")
        width = max(len(n) for n in stages)
        for name in sorted(stages,
                           key=lambda n: -stages[n].get("total_s", 0.0)):
            s = stages[name]
            out.append(
                f"  {name:<{width}}  count {s.get('count', 0):>7}  "
                f"total {_fmt_s(s.get('total_s', 0.0)):>9}  "
                f"p50 {_fmt_s(s.get('p50', 0.0)):>9}  "
                f"p99 {_fmt_s(s.get('p99', 0.0)):>9}  "
                f"({100.0 * s.get('total_s', 0.0) / total:4.1f}%)")

    if snap:
        mix = snap.get("mix") or {}
        if mix.get("active"):
            out.append(
                f"mix:    breaker {mix.get('breaker_state', '?')}"
                f"  exchanges {mix.get('exchanges', 0)}"
                f"  dropped {mix.get('dropped_exchanges', 0)}"
                f"  transport_errors {mix.get('transport_errors', 0)}"
                f"  alive {mix.get('alive')}")
        ms = snap.get("metrics_stream") or {}
        if ms:
            out.append(f"stream: dropped_events {ms.get('dropped_events', 0)}"
                       f"  rotations {ms.get('rotations', 0)}")

    ck = state.last.get("checkpoint")
    if ck is not None:
        age = now - ck.get("ts", now)
        where = ck.get("path", "?")
        at = (f"step {ck['step']}" if "step" in ck
              else f"epoch {ck.get('epoch', '?')}")
        out.append(f"ckpt:   last at {at}, {age:.1f}s ago -> {where}")
    return "\n".join(out)


def summarize(events: List[dict], bad: int = 0, path: str = "",
              now: Optional[float] = None) -> str:
    """Render the summary text for one loaded event list."""
    state = _TailState()
    for rec in events:
        state.add(rec)
    state.bad = bad
    return _render(state, path=path, now=now)


def render_file(path: str, follow: bool = False,
                interval: float = 2.0) -> int:
    """Print the summary for ``path``; with ``follow`` re-render whenever
    the file grows (Ctrl-C exits). Returns a process exit code.

    Follow mode tails INCREMENTALLY: each tick reads only the appended
    bytes, folds them into the bounded aggregates, and defers a partial
    trailing line — a record mid-write is read whole on the next tick,
    never counted as torn. A shrinking file (rotation by
    ``HIVEMALL_TPU_METRICS_MAX_MB``) restarts the tail from zero."""
    if not os.path.exists(path):
        print(f"obs: {path}: no such file", file=sys.stderr)
        return 1
    if not follow:
        events, bad = load_events(path)
        print(summarize(events, bad, path=path))
        return 0
    state = _TailState()
    offset = 0
    ino = None
    try:
        while True:
            try:
                st = os.stat(path)
            except FileNotFoundError:
                # rotation window: MetricsStream._rotate has os.replace'd
                # the file and not yet re-opened it — retry next tick
                time.sleep(max(0.1, interval))
                continue
            size = st.st_size
            # rotation = a FRESH file replaced the tailed one (inode
            # change — size alone can't tell when the new file already
            # grew past the old offset) or in-place truncation: restart
            # from the head. Aggregates keep running across generations;
            # a generation rotated fully away between polls is lost.
            if ino is None:
                ino = st.st_ino
            elif st.st_ino != ino:
                ino, offset = st.st_ino, 0
            if size < offset:
                offset = 0
            if size > offset:
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        data = f.read()
                except FileNotFoundError:  # rotated between stat and open
                    time.sleep(max(0.1, interval))
                    continue
                nl = data.rfind(b"\n")
                if nl >= 0:              # complete lines only; the torn
                    offset += nl + 1     # tail waits for its newline
                    state.feed_lines(data[:nl + 1])
                    print(_render(state, path=path))
                    print("-" * 60)
            time.sleep(max(0.1, interval))
    except KeyboardInterrupt:
        return 0
