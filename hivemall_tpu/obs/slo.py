"""Fleet SLO engine — error-budget burn rates + drift detection.

Answers the two questions the span/gauge surface could not (ROADMAP
items 1/2): *are we inside our latency/availability budget over the last
5 minutes / hour*, and *did the latency or prediction-score distribution
just shift*.

Inputs are CUMULATIVE serving totals — request/error/shed counters plus
a cumulative latency :class:`~hivemall_tpu.obs.histo.Histogram` snapshot
— sampled on a fixed cadence into a bounded in-memory ring (the single
``PredictServer`` samples its own micro-batcher; the fleet's
``ReplicaManager`` sums every replica's ``/healthz`` ``slo`` section each
health tick). ``evaluate()`` then diffs the newest sample against the
sample at each window's far edge, which recovers the EXACT distribution
of that window from monotonic counters — no decaying averages, and a
replica respawn (counters reset) degrades to a clamped-at-zero diff
instead of a negative rate.

Per window (5 m / 1 h by default):

- **availability**: ``1 - (errors + shed) / requests`` vs the
  ``--slo-availability`` target; burn rate = bad-fraction / error-budget
  (>1 = burning budget faster than allowed; 1.0 = exactly on budget).
- **latency**: the fraction of requests over ``--slo-p99-ms`` vs the 1 %
  allowance a p99 objective implies; burn rate = over-fraction / 0.01.
  The window's true p99 is interpolated from the bucket diff.

Drift detection (ROADMAP item 2's "point the changefinder at the
latency and score streams"): every sample tick feeds the interval's mean
latency and the fleet's prediction-score mean into two
:class:`~hivemall_tpu.obs.devprof.DriftWatch` instances — the shared
dual-stage in-tree changefinder wrapper the training profiler also uses
for step-time and memory drift. A score beyond ``drift_sigma`` standard
deviations of the detector's own running score distribution flags a
drift event: counted, kept in a bounded recent-events list, and emitted
as an ``slo_drift`` record into the metrics jsonl stream — the same
stream ``hivemall_tpu obs`` tails, so a latency regression or
model-score shift shows up next to train/serve telemetry without any
external alerting stack.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from .histo import quantile_from_buckets

__all__ = ["SloEngine"]

#: evaluation windows: SRE-standard fast/slow burn pair
WINDOWS = (("5m", 300.0), ("1h", 3600.0))


class _Sample:
    __slots__ = ("ts", "mono", "offered", "bad", "buckets", "lat_sum",
                 "lat_count", "score_sum", "score_sumsq", "score_n")

    def __init__(self, ts, mono, offered, bad, buckets, lat_sum,
                 lat_count, score_sum, score_sumsq, score_n):
        self.ts = ts                    # wall: event export / human corr
        self.mono = mono                # monotonic: ALL window math
        self.offered = offered          # accepted + shed: what clients
        self.bad = bad                  # actually attempted
        self.buckets = buckets          # cumulative [le, count] pairs
        self.lat_sum = lat_sum
        self.lat_count = lat_count
        self.score_sum = score_sum      # cumulative score moments
        self.score_sumsq = score_sumsq
        self.score_n = score_n


def _diff_buckets(new, old) -> List[list]:
    """Bucket-wise clamped difference of two cumulative bucket lists —
    the distribution of the interval between the two snapshots. Bounds
    are positional (both sides come from the same Histogram config).
    A counter reset (replica respawn) clamps at zero, and a PARTIAL
    fleet reset (one replica's history vanishes while survivors grow)
    can leave the per-bucket clamps non-monotone — a running max
    restores a valid cumulative series so downstream quantiles and
    over-SLO fractions stay in range."""
    if not new:
        return []
    if not old or len(old) != len(new):
        return [[b, int(c)] for b, c in new]
    out = []
    run = 0
    for (b, c), (_, oc) in zip(new, old):
        run = max(run, int(c) - int(oc))
        out.append([b, run])
    return out


class SloEngine:
    """Windowed SLO evaluation + changefinder drift over serving totals.

    Thread-safe: ``sample()`` runs on a sampler/health thread while
    ``evaluate()`` serves ``/slo`` scrapes. Registers itself as the obs
    registry's ``slo`` section (last engine wins, weakly held).
    """

    #: ring capacity; paired with _RING_GAP thinning below so the ring
    #: always covers the FULL 1 h window no matter how fast the sampler
    #: ticks (the fleet manager samples every health_interval, 0.2-0.5 s)
    _CAPACITY = 4096
    #: minimum spacing between RING entries: capacity x gap > 1 h, so a
    #: sub-second cadence thins into the ring instead of evicting the
    #: window edge; drift detection still sees every raw tick
    _RING_GAP = 3600.0 / (_CAPACITY - 256)

    def __init__(self, *, p99_ms: float = 100.0,
                 availability: float = 0.999,
                 drift_sigma: float = 6.0,
                 drift_warmup: int = 32,
                 interval: float = 1.0):
        if not 0.0 < availability < 1.0:
            raise ValueError(f"availability target must be in (0, 1), "
                             f"got {availability}")
        self.p99_ms = float(p99_ms)
        self.availability = float(availability)
        self.drift_sigma = float(drift_sigma)
        self.drift_warmup = int(drift_warmup)
        self.interval = float(interval)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._CAPACITY)
        self._last: Optional[_Sample] = None   # newest RAW sample (the
        # ring is gap-thinned; evaluation freshness must not be)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # drift detectors over the per-tick series: the shared
        # obs.devprof.DriftWatch (dual-stage in-tree changefinder,
        # PAPER.md [B] — stage-1 outlier catches step regressions,
        # stage-2 change catches gradual drifts, Welford-self-calibrated
        # mu + sigma*std thresholds per score stream). One implementation
        # for serving latency/score AND training step/memory drift, so a
        # threshold or clamping fix can never reach one and not the other.
        from .devprof import DriftWatch
        self._watch = {k: DriftWatch(k, "slo_drift", sigma=self.drift_sigma,
                                     warmup=self.drift_warmup)
                       for k in ("latency_ms", "score")}
        self.drift_events: deque = deque(maxlen=64)
        self.drift_counts = {k: 0 for k in self._watch}
        # drift-driven retrain hook (ROADMAP item 2): every score-stream
        # drift event is also a `retrain_wanted` vote — the changefinder
        # watching live prediction scores telling training the serving
        # model no longer matches the traffic. Counted here, surfaced in
        # the `slo` AND `promotion` registry sections, emitted into the
        # metrics jsonl for `hivemall_tpu obs`.
        self.retrain_wanted = 0
        # votes vs ACTIONS: the retrain controller (serve.retrain) bumps
        # this as it consumes votes — the obs surface can always show
        # whether anything is answering the changefinder
        self.retrain_acked = 0
        self.samples = 0
        self._register_obs()

    def ack_retrain(self, n: int = 1) -> int:
        """The retrain controller consumed ``n`` votes (a retrain was
        triggered for them, or they were answered by one completing).
        Emits a ``retrain_acked`` event so votes-vs-actions read off the
        same jsonl the votes landed in."""
        with self._lock:
            self.retrain_acked += int(n)
            total = self.retrain_acked
        from ..utils.metrics import get_stream
        get_stream().emit("retrain_acked", count=int(n), total=total)
        return total

    # -- sampling ------------------------------------------------------------
    def sample(self, totals: dict, ts: Optional[float] = None) -> None:
        """Fold one snapshot of cumulative serving totals into the ring.

        ``totals`` keys (all optional, cumulative unless noted):
        ``requests``, ``errors``, ``shed``, ``expired``, ``latency`` (a
        ``Histogram.snapshot()`` dict), ``score_sum`` / ``score_sumsq`` /
        ``score_n`` (cumulative score moments, fleet-summable), plus
        ``reset`` (bool, NOT cumulative): the sampler observed a
        counter reset inside this interval (a replica respawned), so
        the tick's deltas are unreliable — fold the sample into the
        windows (diffs clamp) but skip the drift feed.
        """
        # split clocks: window durations diff `mono` (an NTP step must
        # not stretch or fold a burn-rate window), while drift events and
        # the /slo payload export wall `ts`. An explicit ts drives both —
        # tests run on one synthetic clock.
        mono = time.monotonic() if ts is None else float(ts)
        ts = time.time() if ts is None else float(ts)
        lat = totals.get("latency") or {}
        shed = int(totals.get("shed") or 0)
        cur = _Sample(
            ts, mono,
            # the batcher's `requests` counts ACCEPTED requests (a shed
            # submit raises before the counter) — the availability
            # denominator must be what clients OFFERED, or overload
            # reads as >100% failure
            int(totals.get("requests") or 0) + shed,
            # every client-visible failure burns the availability
            # budget: errors (500s), shed (503s) AND expired (504s)
            int(totals.get("errors") or 0) + shed
            + int(totals.get("expired") or 0),
            [[b, int(c)] for b, c in (lat.get("buckets") or [])],
            float(lat.get("sum") or 0.0),
            int(lat.get("count") or 0),
            float(totals.get("score_sum") or 0.0),
            float(totals.get("score_sumsq") or 0.0),
            int(totals.get("score_n") or 0))
        with self._lock:
            prev = self._last
            self._last = cur
            # gap-thinned ring: sub-second cadences keep full 1h window
            # coverage instead of evicting the far edge (evaluate() uses
            # self._last for freshness, the ring for window edges)
            if not self._ring or cur.mono - self._ring[-1].mono \
                    >= self._RING_GAP:
                self._ring.append(cur)
            self.samples += 1
        if not totals.get("reset"):
            self._detect_drift(prev, cur)

    def start(self, provider: Callable[[], dict]) -> "SloEngine":
        """Sample ``provider()`` every ``interval`` seconds on a daemon
        thread — the single-server recipe (the fleet manager calls
        :meth:`sample` from its own health loop instead)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.sample(provider())
                except Exception:        # noqa: BLE001 — obs never takes
                    pass                 # serving down

        self._thread = threading.Thread(target=run, name="slo-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- drift ---------------------------------------------------------------
    def _detect_drift(self, prev: Optional[_Sample], cur: _Sample) -> None:
        if prev is None:
            return
        feeds = []
        # negative sum deltas happen on PARTIAL fleet counter resets
        # (one replica respawned, the others kept counting): the tick's
        # interval mean is unknowable, so skip the feed — a garbage
        # negative value would flag a spurious drift event exactly
        # during the crash-recovery the fleet is built to absorb
        if cur.lat_count > prev.lat_count \
                and cur.lat_sum >= prev.lat_sum:
            d = cur.lat_count - prev.lat_count
            feeds.append(("latency_ms",
                          (cur.lat_sum - prev.lat_sum) / d * 1000.0))
        if cur.score_n > prev.score_n:
            # the INTERVAL's mean score (moment diff), not the cumulative
            # mean — a model-score shift must hit the detector at full
            # magnitude, not diluted by the whole run's history; scores
            # may legitimately be negative, so the reset guard here is
            # the sumsq moment (monotonic for real data)
            if cur.score_sumsq >= prev.score_sumsq:
                dn = cur.score_n - prev.score_n
                feeds.append(("score",
                              (cur.score_sum - prev.score_sum) / dn))
        for series, x in feeds:
            # DriftWatch flags at most one event per update (either
            # stage) and emits the `slo_drift` record into the jsonl
            # stream itself; the engine keeps its own bounded recent
            # list + per-series counters for /slo
            ev = self._watch[series].update(x, ts=round(cur.ts, 3))
            if ev:
                with self._lock:          # evaluate() copies the deque
                    self.drift_counts[series] += 1   # from HTTP threads
                    self.drift_events.append(ev)
                if series == "score":
                    with self._lock:
                        self.retrain_wanted += 1
                    from ..utils.metrics import get_stream
                    get_stream().emit("retrain_wanted", series=series,
                                      value=ev.get("value"),
                                      stage=ev.get("stage"),
                                      ts=ev.get("ts"))

    # -- evaluation ----------------------------------------------------------
    def _window_edge(self, samples: List[_Sample], now: float,
                     seconds: float) -> Optional[_Sample]:
        """The newest sample at or beyond the window's far edge (falling
        back to the oldest sample when history is shorter than the
        window — the diff then covers everything we have)."""
        lo = now - seconds
        edge = None
        for s in samples:               # oldest -> newest
            if s.mono <= lo:
                edge = s
            else:
                break
        return edge or (samples[0] if samples else None)

    def evaluate(self, now: Optional[float] = None) -> dict:
        """The ``/slo`` payload: per-window traffic, availability and
        latency vs target with error-budget burn rates, plus drift
        state. JSON-ready and cheap enough per scrape (one pass over the
        bounded ring per window)."""
        mono_now = time.monotonic() if now is None else float(now)
        now = time.time() if now is None else float(now)
        with self._lock:
            samples = list(self._ring)
            cur = self._last
            drift_recent = list(self.drift_events)[-8:]
            drift_counts = dict(self.drift_counts)
            retrain_wanted = self.retrain_wanted
            retrain_acked = self.retrain_acked
        if cur is not None and (not samples or samples[-1] is not cur):
            samples.append(cur)          # freshest raw sample wins
        # clock-mismatch guard: samples fed with an EXPLICIT ts (a test's
        # synthetic clock, or a wall timestamp from an older caller) live
        # on a different epoch than this process's monotonic clock — the
        # gap is years, never honest elapsed time. Anchor the window "now"
        # to the freshest sample instead of silently degrading every
        # window to lifetime totals (the far edge would never match).
        if cur is not None and abs(mono_now - cur.mono) > 1e7:
            mono_now = cur.mono
        out: dict = {
            "ts": round(now, 3),
            "configured": True,
            "samples": len(samples),
            "targets": {"p99_ms": self.p99_ms,
                        "availability": self.availability},
            "windows": {},
            "drift": {
                "latency_events": drift_counts["latency_ms"],
                "score_events": drift_counts["score"],
                "retrain_wanted": retrain_wanted,
                "retrain_acked": retrain_acked,
                "recent": drift_recent,
            },
        }
        if not samples:
            return out
        for name, seconds in WINDOWS:
            base = self._window_edge(samples, mono_now, seconds)
            span = max(1e-9, cur.mono - base.mono) \
                if base is not cur else 0.0
            d_req = max(0, cur.offered - base.offered) \
                if base is not cur else cur.offered
            d_bad = max(0, cur.bad - base.bad) \
                if base is not cur else cur.bad
            # a PARTIAL fleet reset can clamp the offered delta harder
            # than the bad delta (the dead replica held good history);
            # bad ⊆ offered by definition, so bound it — availability
            # must never go negative
            d_bad = min(d_bad, d_req)
            diff = _diff_buckets(cur.buckets,
                                 base.buckets if base is not cur else None)
            d_cnt = diff[-1][1] if diff else 0
            w: dict = {
                "seconds": seconds,
                "covered_seconds": round(span, 1),
                "requests": d_req,
                "bad": d_bad,
                "qps": round(d_req / span, 2) if span else 0.0,
            }
            avail = 1.0 - (d_bad / d_req) if d_req else 1.0
            w["availability"] = round(avail, 6)
            w["availability_burn_rate"] = round(
                (1.0 - avail) / (1.0 - self.availability), 3)
            if d_cnt:
                p99_s = quantile_from_buckets(diff, 0.99)
                w["p99_ms"] = round(p99_s * 1000.0, 3)
                over = max(0, d_cnt
                           - self._count_le(diff, self.p99_ms / 1000.0))
                frac_over = over / d_cnt
                w["frac_over_slo"] = round(frac_over, 6)
                # a p99 objective allows 1% of requests over the bound
                w["latency_burn_rate"] = round(frac_over / 0.01, 3)
            else:
                w["p99_ms"] = None
                w["frac_over_slo"] = 0.0
                w["latency_burn_rate"] = 0.0
            d_sn = max(0, cur.score_n - base.score_n) \
                if base is not cur else cur.score_n
            if d_sn:
                ds = cur.score_sum - (base.score_sum
                                      if base is not cur else 0.0)
                dss = cur.score_sumsq - (base.score_sumsq
                                         if base is not cur else 0.0)
                m = ds / d_sn
                var = dss / d_sn - m * m
                # moment-consistency guard (the partial-reset hardening
                # the availability/latency paths above get): sumsq is
                # monotone for real data and mean² <= E[s²] by
                # Cauchy–Schwarz — a window diff violating either mixes
                # pre- and post-reset history, so suppress rather than
                # report a garbage score_mean
                if dss >= 0.0 and var >= -1e-9:
                    w["score_mean"] = round(m, 6)
                    w["score_std"] = round(max(0.0, var) ** 0.5, 6)
            out["windows"][name] = w
        if cur.score_n > 0:
            m = cur.score_sum / cur.score_n
            out["score"] = {"mean": round(m, 6),
                            "std": round(max(
                                0.0, cur.score_sumsq / cur.score_n
                                - m * m) ** 0.5, 6)}
        return out

    @staticmethod
    def _count_le(diff, bound_s: float) -> int:
        """Requests at or under ``bound_s`` in a bucket diff: the
        cumulative count of the LARGEST bucket bound <= the target —
        conservative for an SLO (a target between two bounds counts the
        straddling bucket as violations, never as compliance)."""
        best = 0
        for b, c in diff:
            if b == "+Inf" or float(b) > bound_s:
                break
            best = int(c)
        return best

    # -- obs -----------------------------------------------------------------
    def obs_section(self) -> dict:
        """The registry ``slo`` section: the numeric core of
        :meth:`evaluate` (burn rates + drift counters flatten into
        ``/metrics`` gauges; the full payload lives at ``/slo``)."""
        ev = self.evaluate()
        d: dict = {"configured": True, "samples": ev["samples"],
                   "target_p99_ms": self.p99_ms,
                   "target_availability": self.availability,
                   "drift_latency_events": ev["drift"]["latency_events"],
                   "drift_score_events": ev["drift"]["score_events"],
                   "retrain_wanted": self.retrain_wanted,
                   "retrain_acked": self.retrain_acked}
        for name, w in ev["windows"].items():
            d[name] = {"qps": w["qps"], "availability": w["availability"],
                       "availability_burn_rate":
                           w["availability_burn_rate"],
                       "p99_ms": w["p99_ms"],
                       "latency_burn_rate": w["latency_burn_rate"]}
        return d

    def _register_obs(self) -> None:
        import weakref
        from .registry import SLO_STUB, registry
        ref = weakref.ref(self)

        def slo() -> dict:
            e = ref()
            return e.obs_section() if e is not None \
                else dict(SLO_STUB)

        registry.register("slo", slo)

