"""Central counter registry — every subsystem's metrics in ONE snapshot.

The reference scattered its counters across Hadoop MapredContext, log4j
and the MixServer's JMX beans; the rebuild likewise grew four disjoint
surfaces (PipelineStats, MixClient/MixServer counters(), CheckpointManager,
Meter). This registry is the merge point: subsystems register a named
zero-argument provider returning a JSON-ready dict, and ``snapshot()``
calls them all into one record — the payload of the ``train_done`` /
``telemetry`` jsonl events, the ``/snapshot`` HTTP endpoint, and (flattened)
the ``/metrics`` Prometheus exposition.

Contract for providers:

- cheap and non-blocking: snapshot() may be called from another thread
  WHILE a fit is running (the live-surface case), so a provider must never
  sync the device, take a long lock, or mutate trainer state;
- JSON-ready: dicts/lists/str/numbers/bools/None only;
- failure-isolated: a provider that raises yields an ``{"error": ...}``
  section, never a broken snapshot.

Registration is last-wins by section name (a new trainer's ``pipeline``
provider replaces the previous trainer's) and providers should hold their
subject weakly — the registry is process-global and must not keep dead
trainers alive.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

__all__ = ["Registry", "registry"]

Provider = Callable[[], dict]


class Registry:
    """Named sections of JSON-ready counters, merged on demand."""

    def __init__(self):
        self._lock = threading.Lock()
        self._providers: Dict[str, Provider] = {}

    def register(self, name: str, provider: Provider) -> str:
        """Bind ``name`` to ``provider`` (last registration wins). Returns
        the name so callers can later :meth:`unregister` it."""
        if not callable(provider):
            raise TypeError(f"provider for {name!r} must be callable")
        with self._lock:
            self._providers[name] = provider
        return name

    def unregister(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    def sections(self) -> List[str]:
        with self._lock:
            return sorted(self._providers)

    def snapshot(self) -> dict:
        """One merged, JSON-ready dict: ``{"ts": ..., section: {...}}``.
        Provider failures are isolated into their own section — a broken
        subsystem must never take the whole surface down."""
        with self._lock:
            providers = list(self._providers.items())
        out: dict = {"ts": round(time.time(), 3)}
        for name, fn in providers:
            try:
                out[name] = fn()
            except Exception as e:          # noqa: BLE001 — isolation is the point
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out


#: The process-wide registry. Subsystems register themselves on
#: construction (LearnerBase: pipeline/train/mix; CheckpointManager:
#: checkpoint; MixServer: mix_server; MetricsStream: metrics_stream;
#: Tracer: spans). The defaults below guarantee the acceptance sections
#: exist in every snapshot even before a subsystem comes up.
#:
#: Stub contract (hardened after the PR 7/8 key-drift recurrences, pinned
#: by tests/test_obs.py::test_stub_sections_match_live_providers): every
#: stub's KEY SET mirrors its live provider's snapshot exactly — gauges a
#: dashboard keys on never appear/vanish across subsystem lifecycle. The
#: inactive forms trainers/managers return when their subsystem is down
#: reuse these same dicts, so the two can never drift apart.

#: MixClient.counters() + the "active" discriminator
MIX_STUB = {"active": False, "exchanges": 0, "reconnects": 0,
            "dropped_exchanges": 0, "transport_errors": 0,
            "breaker_trips": 0, "breaker_state": "closed",
            "touched_overflow": 0, "alive": False}
#: CheckpointManager.obs_section()
CHECKPOINT_STUB = {"configured": False, "dir": None, "every": 0,
                   "keep": 0, "last_saved_step": None,
                   "age_seconds": None, "bundles": 0}
#: SloEngine.obs_section() in its fresh (no samples) state
SLO_STUB = {"configured": False, "samples": 0, "target_p99_ms": None,
            "target_availability": None, "drift_latency_events": 0,
            "drift_score_events": 0, "retrain_wanted": 0,
            "retrain_acked": 0}
#: serve.fleet.ReplicaManager.obs_section()
FLEET_STUB = {"replicas": 0, "ready": 0, "respawns": 0, "rolls": 0,
              "roll_failures": 0, "rejected_bundles": 0,
              "fleet_step": None, "model_steps": {},
              "replica_rss_bytes": {}, "arena_mapped_bytes": {}}
#: serve.promote.PromotionController.obs_section() /
#: serve.fleet.ReplicaManager.promotion_section() in their inactive form
#: (copy via serve.promote.promotion_stub — the nested canary dict must
#: not be shared mutable state)
PROMOTION_STUB = {"configured": False, "promoted_step": None,
                  "state": None, "candidates": 0, "gate_passes": 0,
                  "gate_failures": 0, "arena_published": 0,
                  "promotions": 0, "rollbacks": 0,
                  "quarantined": 0,
                  "canary": {"active": False, "step": None, "cohort": 0,
                             "age_seconds": None},
                  "shadow": {"mirrored": 0, "dropped": 0, "rows": 0},
                  "last_verdict": None, "retrain_wanted": 0,
                  "retrain_acked": 0}
#: serve.retrain.RetrainController.obs_section() in its inactive form
#: (copy via serve.retrain.retrain_stub — the nested replay dict must
#: not be shared mutable state)
RETRAIN_STUB = {"configured": False, "state": "idle", "attempts": 0,
                "successes": 0, "rejections": 0, "rollbacks": 0,
                "flaps": 0, "votes_seen": 0, "votes_acked": 0,
                "cooldown_remaining_s": 0.0, "child_alive": False,
                "candidate_step": None, "last_trigger_reason": None,
                "last_error": None,
                "replay": {"rows": 0, "rows_dropped": 0, "segments": 0,
                           "pending_rows": 0}}
#: serve.retrieve.RetrievalEngine.obs_section() in its inactive form
#: (copy via serve.retrieve.retrieval_stub — the nested index/arena
#: dicts must not be shared mutable state)
RETRIEVAL_STUB = {"configured": False, "algo": None, "follow": None,
                  "ready": False, "model_step": None,
                  "model_age_seconds": None, "bundle_age_seconds": None,
                  "model_path": None, "reloads": 0, "reload_failures": 0,
                  "watching": False, "precision": None, "tier": None,
                  "max_k": 0, "rescore_backend": None,
                  "queries_user": 0, "queries_item": 0,
                  "queries_lsh": 0, "queries_exact": 0,
                  "empty_candidates": 0, "last_reload_error": None,
                  "index": {"tables": 0, "bits": 0, "rows": 0,
                            "buckets": 0, "max_bucket": 0,
                            "mean_bucket": 0.0, "build_seconds": 0.0,
                            "recall_at_k": 0.0},
                  "arena": {"active": False, "mapped_bytes": 0,
                            "loads": 0, "publishes": 0},
                  "plane": None}
#: io.bulk.BulkProgress.obs_section() before any bulk job ran — the
#: offline scoring plane's section, key-for-key the live provider's shape
BULK_STUB = {"active": False, "input": None, "output": None,
             "backend": None, "precision": None, "workers": 0,
             "shards_total": 0, "shards_done": 0, "rows_scored": 0,
             "rows_per_sec": 0.0, "worker_utilization": 0.0,
             "elapsed_seconds": 0.0, "model_step": None, "bundle": None}

registry = Registry()
registry.register("mix", lambda: dict(MIX_STUB))
registry.register("checkpoint", lambda: dict(CHECKPOINT_STUB))
# io.shard_cache overrides this with its live counters on import (the
# first cache-aware fit); until then the section reports unconfigured
# zeros so the acceptance surface is shape-stable in every snapshot
registry.register("ingest_cache", lambda: {
    "configured": False, "hits": 0, "misses": 0, "invalid": 0,
    "rebuilds": 0, "build_failed": 0, "bytes_mmapped": 0,
    "bytes_written": 0, "canonicalizer": "unresolved"})
# serve.fleet.ReplicaManager overrides this with its live replica/roll
# counters when a fleet is running in this process
registry.register("fleet", lambda: dict(FLEET_STUB))
# obs.slo.SloEngine overrides this with live burn rates when a serve
# surface configures an SLO
registry.register("slo", lambda: dict(SLO_STUB))
# serve.promote.PromotionController / serve.fleet.ReplicaManager override
# this with live gate/canary/rollback state when promotion is gated
registry.register("promotion", lambda: {**PROMOTION_STUB,
                                        "canary":
                                        dict(PROMOTION_STUB["canary"]),
                                        "shadow":
                                        dict(PROMOTION_STUB["shadow"])})
# serve.retrain.RetrainController overrides this with the live retrain
# state machine when the autopilot is running
registry.register("retrain", lambda: {**RETRAIN_STUB,
                                      "replay":
                                      dict(RETRAIN_STUB["replay"])})
# serve.retrieve.RetrievalEngine overrides this with the live factor
# index/query counters when a retrieval plane is serving in this process
registry.register("retrieval", lambda: {
    **RETRIEVAL_STUB, "index": dict(RETRIEVAL_STUB["index"]),
    "arena": dict(RETRIEVAL_STUB["arena"])})
# io.bulk.bulk_predict overrides this with live shard/rows-per-sec
# progress while a bulk scoring job runs in this process
registry.register("bulk", lambda: dict(BULK_STUB))
# obs.devprof.DevProf overrides this with live compile/retrace/memory
# telemetry on first use (any trainer construction)
from .devprof import devprof_stub  # noqa: E402 — stub needs the dict shape
registry.register("devprof", devprof_stub)
# obs.flight.get_flight overrides this with the live ring's self-census
# (events written, overwrites, utilization) on first use
from .flight import flight_stub  # noqa: E402 — stub needs the dict shape
registry.register("flight", flight_stub)
