"""Training-side deep profiling — compile/retrace telemetry, device-memory
accounting, and step-time drift (docs/OBSERVABILITY.md "Training
profiling").

The serving tier got request tracing, latency histograms and an SLO
engine (PR 8); this module is the TRAINING half's equivalent depth. The
repo's single biggest self-inflicted perf hazard is silent recompilation:
every trainer family enforces one-compile-per-config by hand (the
module-level ``lru_cache`` factories in models/linear|fm|word2vec|
topicmodel|anomaly, ``models.base.shared_step``, the megastep cache in
ops/scan, the bucketed scorers in io.sparse) — word2vec measured ~5 s of
wasted XLA compile per duplicate instance, LDA 1.5 s of a 2.3 s bench —
and until now NOTHING watched that discipline at runtime. Three watches
live here:

**Compile telemetry.** Two attribution layers feed one ledger:

- the factory layer: every compile factory is wrapped by
  :func:`instrument_factory`, so a cache MISS (a fresh closure actually
  built) records a per-``(model, fn)`` build count + wall time, a
  ``compile.<model>.<fn>`` span, and — for shape-driven factories — the
  shape bucket. Shape-bucketed scoring (io.sparse.score_batches, the
  serve engine's warmup peer) reports first-use of each (B, L) bucket
  through :meth:`DevProf.note_bucket`.
- the XLA layer: a ``jax.monitoring`` listener counts every backend
  compile (``/jax/core/compile/backend_compile_duration``) and trace
  (``jaxpr_trace_duration``) with wall time — the ground truth the
  factory layer attributes. A fresh closure that BYPASSES the factories
  (the exact disease) still lands here.

**No-retrace sentinel.** ``arm()`` marks warmup complete; any XLA
backend compile observed while armed is a RETRACE: counted, timed,
recorded as a ``compile.retrace`` span, and emitted as a ``retrace``
event into the metrics jsonl. The sentinel auto-arms at the first
``train_done`` (one completed fit = the process's compile warmup), so a
second same-config trainer that re-compiles — the word2vec disease —
flags itself in telemetry with no harness involved. ``bench.py --smoke``
turns the sentinel into a CI guard: warm epoch, ``arm()``, second epoch
must add ZERO compiles, and a deliberately-injected fresh-closure
duplicate trainer must be caught.

**Device-memory accounting + drift.** :meth:`sample_memory` reads
``device.memory_stats()`` (None on the CPU backend — degrades to zeros)
and ``jax.live_arrays()`` into live HBM/host gauges, sampled at the
trainer's ``-telemetry_every`` cadence and kept fresh for ``/snapshot``/
``/metrics`` scrapes; the megastep dispatch boundary (ops.scan) tracks
peak-bytes-in-use per fused dispatch. The live-bytes stream feeds the
in-tree dual-stage :class:`~hivemall_tpu.models.anomaly.ChangeFinder`
(the same detector PR 8 pointed at serving latency) → ``mem_drift``
events; per-dispatch wall time feeds a second detector →
``train_drift`` events. Both detectors self-calibrate their thresholds
(Welford mean + ``sigma`` stds of their own score streams, the obs.slo
recipe) so no absolute threshold needs tuning per model.

**Profiler capture.** ``HIVEMALL_TPU_PROF=<dir>`` (legacy spelling
``HIVEMALL_TPU_PROFILE`` still honored) captures a ``jax.profiler``
trace of the first ``fit()`` in the process — routed through here so the
capture records a ``profile.capture`` span and a ``profile`` jsonl
event instead of being an invisible side effect.

Cost contract (the obs module's standing rule): everything is ~free when
idle. The monitoring listener only runs when XLA compiles (never on the
steady-state hot path); ``note_dispatch`` is one attribute check until
:meth:`activate` (``-telemetry_every``/``-obs_port``/
``HIVEMALL_TPU_DEVPROF=1``) turns the drift watches on; memory sampling
happens at telemetry cadence, never per step.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = ["DevProf", "DriftWatch", "get_devprof", "instrument_factory",
           "devprof_stub"]

#: keys of the ``memory`` sub-dict — zeros until the first sample so the
#: section is shape-stable (obs.registry stub contract)
_MEM_KEYS = ("live_arrays", "live_bytes", "bytes_in_use",
             "peak_bytes_in_use", "bytes_limit")


def devprof_stub() -> dict:
    """The shape of the ``devprof`` registry section before (or without)
    a live :class:`DevProf` — mirrors :meth:`DevProf.obs_section` key for
    key (pinned by tests/test_obs.py's stub-vs-live check)."""
    return {
        "active": False, "armed": False,
        "compiles": 0, "compile_seconds": 0.0, "traces": 0,
        "retraces": 0, "retrace_seconds": 0.0,
        "builds": {}, "build_seconds": 0.0, "shape_buckets": 0,
        "dispatches": 0, "dispatch_seconds": 0.0,
        "memory": {k: 0 for k in _MEM_KEYS},
        "peak_dispatch_bytes": 0,
        "drift": {"train_events": 0, "mem_events": 0},
        "profile_captures": 0,
    }


class DriftWatch:
    """One scalar stream -> drift events, the obs.slo recipe factored out:
    a dual-stage :class:`~hivemall_tpu.models.anomaly.ChangeFinder`
    (stage-1 outlier catches step regressions, stage-2 change catches
    gradual drifts; PAPER.md [B]) with Welford-self-calibrated
    ``mu + sigma*std`` thresholds per score stream. A flagged update is
    counted and emitted as an ``<event>`` record into the metrics jsonl
    — next to train/serve telemetry, no external alerting stack."""

    def __init__(self, series: str, event: str, *, sigma: float = 6.0,
                 warmup: int = 32):
        # lazy import: watching is opt-in, importing obs.devprof must not
        # pull the anomaly module (and numpy SDAR state) everywhere
        from ..models.anomaly import ChangeFinder
        self.series = series
        self.event = event
        self.sigma = float(sigma)
        self.warmup = int(warmup)
        self._cf = ChangeFinder()
        self._stats = {s: [0, 0.0, 0.0]        # n, mean, M2 per stage
                       for s in ("outlier", "change")}
        self._lock = threading.Lock()
        self.n = 0
        self.events = 0

    def update(self, x: float, **extra) -> Optional[dict]:
        """Feed one value; returns the emitted event dict when the update
        crossed a self-calibrated threshold, else None. Serialized: the
        memory watch can be fed from both the telemetry cadence and a
        scrape-freshness resample, and SDAR state must not interleave."""
        with self._lock:
            outlier, change = self._cf.update(float(x))
            self.n += 1
            flagged = None
            for stage, score in (("outlier", outlier), ("change", change)):
                st = self._stats[stage]
                st[0] += 1
                n = st[0]
                delta = score - st[1]
                st[1] += delta / n
                st[2] += delta * (score - st[1])
                if n <= self.warmup:
                    continue
                std = (st[2] / max(1, n - 1)) ** 0.5
                if std > 0 and score > st[1] + self.sigma * std:
                    flagged = flagged or stage
            if not flagged:
                return None
            self.events += 1
        ev = {"series": self.series, "stage": flagged,
              "value": round(float(x), 6),
              "outlier_score": round(float(outlier), 4),
              "change_score": round(float(change), 4), **extra}
        from ..utils.metrics import get_stream
        get_stream().emit(self.event, **ev)
        return ev


class DevProf:
    """The process-wide training profiler (:func:`get_devprof`).

    Thread-safe: the monitoring listener fires from whichever thread
    compiles (serve warmup threads, the fit loop), scrape threads read
    :meth:`obs_section` concurrently, and one lock guards the (scalar)
    counter updates."""

    def __init__(self):
        self._lock = threading.Lock()
        self.active = False                 # drift watches + mem cadence
        self.armed = False                  # no-retrace sentinel
        # XLA layer (jax.monitoring ground truth)
        self.compiles = 0
        self.compile_s = 0.0
        self.traces = 0
        self.retraces = 0
        self.retrace_s = 0.0
        # factory layer (attribution)
        self.builds: Dict[str, dict] = {}   # "model.fn" -> {count, seconds}
        self.build_s = 0.0
        self._buckets: set = set()          # (site, B, L) first-use
        # dispatch / memory
        self.dispatches = 0
        self.dispatch_s = 0.0
        self._mem: Dict[str, int] = {k: 0 for k in _MEM_KEYS}
        self._mem_ts = 0.0
        self.peak_dispatch_bytes = 0
        self.profile_captures = 0
        self._profiled = False              # first-fit-only capture latch
        self._train_watch: Optional[DriftWatch] = None
        self._mem_watch: Optional[DriftWatch] = None
        self._register_monitoring()

    # -- XLA compile layer ---------------------------------------------------
    def _register_monitoring(self) -> None:
        """Hook ``jax.monitoring`` duration events. Listener registration
        is global and append-only in jax, so this runs once per DevProf
        (one DevProf per process via get_devprof); failure degrades to
        factory-layer-only telemetry — profiling never takes training
        down."""
        try:
            import jax.monitoring as monitoring

            def on_duration(event: str, duration: float, **kw) -> None:
                if event.endswith("/backend_compile_duration"):
                    self._record_compile(float(duration))
                elif event.endswith("/jaxpr_trace_duration"):
                    with self._lock:
                        self.traces += 1

            monitoring.register_event_duration_secs_listener(on_duration)
        except Exception:                   # noqa: BLE001 — fail soft
            pass

    def _record_compile(self, dur: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_s += dur
            retrace = self.armed
            if retrace:
                self.retraces += 1
                self.retrace_s += dur
        from .trace import get_tracer
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add_span("compile.retrace" if retrace else "compile.xla",
                            dur)
        if retrace:
            # the sentinel's whole point: a post-warmup compile must land
            # in the stream where `hivemall_tpu obs` and the CI guard see
            # it, not only in a counter
            from ..utils.metrics import get_stream
            get_stream().emit("retrace", seconds=round(dur, 6),
                              compiles=self.compiles,
                              retraces=self.retraces)

    # -- sentinel ------------------------------------------------------------
    def arm(self) -> "DevProf":
        """Warmup is over: from here every XLA compile is a retrace."""
        self.armed = True
        return self

    def disarm(self) -> "DevProf":
        self.armed = False
        return self

    def note_train_done(self) -> None:
        """Auto-arm at the first completed fit: one full run compiles
        every shape a config needs, so later compiles in the same process
        are exactly the duplicate-instance disease the factories exist to
        prevent. Harness code that intentionally compiles new configs
        (benches, test suites) sees retrace COUNTERS grow, never a
        failure — the CI guard reads a delta over an explicitly armed
        window instead."""
        self.armed = True

    # -- factory layer -------------------------------------------------------
    def record_build(self, model: str, fn: str, seconds: float,
                     shape: Optional[Tuple[int, ...]] = None) -> None:
        key = f"{model}.{fn}"
        with self._lock:
            b = self.builds.get(key)
            if b is None:
                b = self.builds[key] = {"count": 0, "seconds": 0.0}
            b["count"] += 1
            b["seconds"] = round(b["seconds"] + seconds, 6)
            self.build_s += seconds
            if shape is not None:
                self._buckets.add((key,) + tuple(shape))
        from .trace import get_tracer
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add_span(f"compile.{key}", seconds)

    def note_bucket(self, site: str, *shape: int) -> None:
        """First use of a (site, shape-bucket) — the moment a bucketed
        scorer's next call will compile. Dedup'd, so steady-state scoring
        costs one set-lookup."""
        key = (site,) + tuple(int(s) for s in shape)
        if key in self._buckets:
            return
        with self._lock:
            self._buckets.add(key)

    # -- dispatch / drift ----------------------------------------------------
    def activate(self) -> "DevProf":
        """Turn on the drift watches + scrape-time memory freshness
        (``-telemetry_every`` / ``-obs_port`` / HIVEMALL_TPU_DEVPROF=1
        route here). Idempotent."""
        if not self.active:
            self._train_watch = DriftWatch("step_ms", "train_drift")
            self._mem_watch = DriftWatch("live_bytes", "mem_drift")
            self.active = True
        return self

    def note_dispatch(self, dur_s: float, steps: int = 1) -> None:
        """Per-dispatch wall time from the trainer's host boundary. One
        attribute check when inactive; when active, feeds the per-STEP
        wall (ms) into the train drift detector."""
        if not self.active:
            return
        with self._lock:
            self.dispatches += 1
            self.dispatch_s += dur_s
        w = self._train_watch
        if w is not None:
            w.update(dur_s / max(1, steps) * 1000.0)

    def note_megastep(self) -> None:
        """Called by ops.scan's megastep wrapper after each fused
        dispatch: track the device allocator's peak-bytes high-water mark
        across dispatches (None on backends without memory_stats)."""
        if not self.active:
            return
        try:
            import jax
            peak = sum(int((d.memory_stats() or {})
                           .get("peak_bytes_in_use") or 0)
                       for d in jax.local_devices())
        except Exception:                   # noqa: BLE001 — obs only
            return
        if peak > self.peak_dispatch_bytes:
            self.peak_dispatch_bytes = peak

    # -- memory --------------------------------------------------------------
    def sample_memory(self) -> dict:
        """One gauge sample: allocator stats summed over every local
        device (a GSPMD process drives several — a leak on device 3 must
        not hide behind device 0) + live jax.Array census. Feeds the
        mem-drift detector when active. Cheap enough for the telemetry
        cadence, NOT for the per-step path."""
        rec = {k: 0 for k in _MEM_KEYS}
        try:
            import jax
            for dev in jax.local_devices():
                stats = dev.memory_stats() or {}
                rec["bytes_in_use"] += int(stats.get("bytes_in_use") or 0)
                rec["peak_bytes_in_use"] += int(
                    stats.get("peak_bytes_in_use") or 0)
                rec["bytes_limit"] += int(stats.get("bytes_limit") or 0)
            arrs = jax.live_arrays()
            rec["live_arrays"] = len(arrs)
            rec["live_bytes"] = int(sum(getattr(a, "nbytes", 0)
                                        for a in arrs))
        except Exception:                   # noqa: BLE001 — a failed
            return dict(self._mem)          # sample keeps the last gauge
        with self._lock:
            self._mem = rec
            self._mem_ts = time.monotonic()
        if self.active and self._mem_watch is not None:
            # live-bytes in MB: keeps the SDAR state in a well-scaled
            # range (raw byte counts in the 1e9s degrade its f64 moments
            # no differently, but MB reads better in the event records)
            self._mem_watch.update(rec["live_bytes"] / 1e6)
        return rec

    def _fresh_memory(self, max_age: float = 2.0) -> dict:
        """The last sample, refreshed inline when a scrape finds it stale
        and the watch is active (a live fit with -obs_port but without
        -telemetry_every would otherwise serve startup zeros forever)."""
        if self.active and time.monotonic() - self._mem_ts > max_age:
            return self.sample_memory()
        return dict(self._mem)

    # -- profiler capture (HIVEMALL_TPU_PROF) --------------------------------
    @staticmethod
    def profile_dir() -> Optional[str]:
        """The documented env var, with the pre-unification spelling kept
        as an alias so existing launch scripts don't silently lose their
        profiles."""
        return (os.environ.get("HIVEMALL_TPU_PROF")
                or os.environ.get("HIVEMALL_TPU_PROFILE"))

    def start_profile_once(self) -> Optional[str]:
        """Start a jax.profiler trace for the FIRST fit in the process
        when ``HIVEMALL_TPU_PROF=<dir>`` is set; returns the capture dir
        (pass it to :meth:`stop_profile`) or None."""
        prof_dir = self.profile_dir()
        if not prof_dir or self._profiled:
            return None
        self._profiled = True
        try:
            import jax
            jax.profiler.start_trace(prof_dir)
        except Exception as e:              # noqa: BLE001 — fail soft,
            import warnings                 # but LOUDLY: the latch is set,
            warnings.warn(                  # no later fit will retry
                f"HIVEMALL_TPU_PROF capture into {prof_dir!r} failed "
                f"({type(e).__name__}: {e}); no profile will be written "
                f"this process", RuntimeWarning, stacklevel=2)
            return None
        self._prof_t0 = time.perf_counter()
        return prof_dir

    def stop_profile(self, prof_dir: Optional[str]) -> None:
        """Stop a capture started by :meth:`start_profile_once`: emits a
        ``profile.capture`` span and a ``profile`` jsonl event carrying
        the dir, so the capture is discoverable from the stream."""
        if not prof_dir:
            return
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:              # noqa: BLE001 — fail soft but
            import warnings                 # loudly (an unwritable dir
            warnings.warn(                  # often only fails at stop)
                f"HIVEMALL_TPU_PROF capture into {prof_dir!r} failed at "
                f"stop ({type(e).__name__}: {e}); the profile was lost",
                RuntimeWarning, stacklevel=2)
            return
        dur = time.perf_counter() - getattr(self, "_prof_t0",
                                            time.perf_counter())
        self.profile_captures += 1
        from .trace import get_tracer
        tracer = get_tracer()
        if tracer.enabled:
            tracer.add_span("profile.capture", dur)
        from ..utils.metrics import get_stream
        get_stream().emit("profile", dir=prof_dir,
                          seconds=round(dur, 3))

    # -- obs -----------------------------------------------------------------
    def obs_section(self) -> dict:
        """The ``devprof`` registry section (key set mirrored by
        :func:`devprof_stub`): flattens to ``/metrics`` gauges, rides
        ``/snapshot`` and the ``telemetry``/``train_done`` events."""
        with self._lock:
            builds = {k: dict(v) for k, v in self.builds.items()}
            d = {
                "active": self.active, "armed": self.armed,
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_s, 6),
                "traces": self.traces,
                "retraces": self.retraces,
                "retrace_seconds": round(self.retrace_s, 6),
                "builds": builds,
                "build_seconds": round(self.build_s, 6),
                "shape_buckets": len(self._buckets),
                "dispatches": self.dispatches,
                "dispatch_seconds": round(self.dispatch_s, 6),
                "peak_dispatch_bytes": self.peak_dispatch_bytes,
                "drift": {
                    "train_events": (self._train_watch.events
                                     if self._train_watch else 0),
                    "mem_events": (self._mem_watch.events
                                   if self._mem_watch else 0)},
                "profile_captures": self.profile_captures,
            }
        d["memory"] = self._fresh_memory()
        return d

    def _register_obs(self) -> None:
        from .registry import registry
        registry.register("devprof", self.obs_section)


_devprof: Optional[DevProf] = None
_devprof_lock = threading.Lock()


def get_devprof() -> DevProf:
    """The process-wide profiler, constructed (and registered as the obs
    registry's ``devprof`` section) on first use. HIVEMALL_TPU_DEVPROF=1
    activates the drift watches immediately."""
    global _devprof
    if _devprof is None:
        with _devprof_lock:
            if _devprof is None:
                dp = DevProf()
                if os.environ.get("HIVEMALL_TPU_DEVPROF", "") not in ("", "0"):
                    dp.activate()
                dp._register_obs()
                _devprof = dp
    return _devprof


def instrument_factory(model: str, fn_name: str, *,
                       shape_args: Tuple[int, ...] = ()):
    """Wrap a module-level ``lru_cache`` compile factory so cache MISSES
    (fresh closures actually built) record into the devprof ledger:

        @instrument_factory("linear", "step")
        @lru_cache(maxsize=128)
        def _linear_step_cached(...): ...

    ``shape_args`` names positional-arg indexes carrying shape-bucket
    dimensions (e.g. the packed-wrapper's (B, L)), recorded per bucket.
    The wrapped factory keeps ``cache_info``/``cache_clear`` and exposes
    the underlying cache as ``__wrapped__`` (the fresh-closure injection
    path of the CI guard digs through it on purpose)."""
    import functools

    def deco(cached):
        # serialize calls through THIS factory: miss detection diffs the
        # shared lru miss counter, and a concurrent miss on another key
        # would otherwise attribute a bogus near-zero build to a hit.
        # Builds are closure construction (microseconds — the XLA compile
        # happens at first call), so the lock costs nothing measurable;
        # no instrumented factory calls another, so no nesting deadlock.
        lock = threading.Lock()

        @functools.wraps(cached)
        def wrapper(*args, **kwargs):
            with lock:
                before = cached.cache_info().misses
                t0 = time.perf_counter()
                out = cached(*args, **kwargs)
                missed = cached.cache_info().misses != before
                dur = time.perf_counter() - t0
            if missed:
                shape = tuple(args[i] for i in shape_args) or None
                get_devprof().record_build(model, fn_name, dur, shape=shape)
            return out

        wrapper.cache_info = cached.cache_info
        wrapper.cache_clear = cached.cache_clear
        wrapper.__wrapped__ = cached
        return wrapper

    return deco
