from .base import LearnerBase, learner_option_spec  # noqa: F401
from .linear import (GeneralClassifier, GeneralRegressor, LogressTrainer,  # noqa: F401
                     AdaGradLogisticTrainer, AdaDeltaLogisticTrainer)
