"""LearnerBase — the trainer-UDTF lifecycle over TPU minibatch kernels.

Reference: hivemall.LearnerBaseUDTF + UDTFWithOptions (SURVEY.md §3.1, §4.1):
a trainer is fed rows one at a time (``process``), holds model state, and at
``close()`` emits the model as (feature, weight) rows. The rebuild keeps that
exact lifecycle — tests drive trainers the way the reference's unit tests
drive UDTFs by hand (SURVEY.md §5.1) — and adds a columnar fast path
(``fit(dataset)``) that skips per-row Python entirely.

Streaming semantics: rows buffer into fixed-shape minibatches (power-of-two
padded length so jit traces a few shapes); each full buffer dispatches one
jitted step. ``-iters > 1`` replays the recorded stream for further epochs
with reshuffling, the NioStatefulSegment analog.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..io.pipeline import PipelineStats
from ..io.sparse import (MegaBatch, PackedMegaBatch, SparseBatch,
                         SparseDataset, pow2_len, score_batches,
                         split_feature)
from ..obs.devprof import get_devprof
from ..obs.flight import FS, get_flight
from ..obs.trace import get_tracer
from ..utils.hashing import mhash
from ..utils.metrics import Meter, get_stream
from ..utils.options import OptionSpec, Parsed

__all__ = ["LearnerBase", "learner_option_spec",
           "add_mix_reliability_options", "sigmoid_np"]


def sigmoid_np(x: np.ndarray) -> np.ndarray:
    """Numerically-stable host-side sigmoid — THE margin->probability map
    of every classification scoring path (predict_proba and the serve
    engine share it, so online and offline probabilities bit-match)."""
    return np.where(x >= 0, 1.0 / (1.0 + np.exp(-x)),
                    np.exp(x) / (1.0 + np.exp(x)))


def add_mix_reliability_options(s: OptionSpec) -> OptionSpec:
    """MIX fault-tolerance knobs (docs/RELIABILITY.md): retry + backoff +
    circuit breaker replacing the old first-error permanent kill-switch.
    Shared by the general learner grammar and the bespoke specs of
    trainers that also mix (covariance classifiers etc.)."""
    s.add("mix_timeout", type=float, default=2.0, min=1e-3,
          help="per-socket-op MIX timeout in seconds")
    s.add("mix_retries", type=int, default=2, min=0,
          help="extra attempts per MIX exchange after the first fails "
               "(reconnect + resend with jittered exponential backoff)")
    s.add("mix_backoff", type=float, default=0.05, min=0.0,
          help="base MIX retry backoff seconds (doubled per attempt, "
               "jittered in [0.5x, 1.5x), capped at 2s)")
    s.add("mix_deadline", type=float, default=0.0, min=0.0,
          help="wall-clock budget per MIX exchange incl. retries; "
               "0 = 2x -mix_timeout")
    s.add("mix_breaker_threshold", type=int, default=3, min=1,
          help="consecutive failed exchanges that open the MIX circuit "
               "breaker (exchanges then drop instead of blocking on a "
               "dead server)")
    s.add("mix_breaker_cooldown", type=float, default=1.0, min=0.0,
          help="seconds the breaker stays open before a half-open "
               "reconnect probe")
    s.add("mix_breaker_trips", type=int, default=3, min=1,
          help="consecutive breaker opens (no success between) before "
               "the client degrades permanently to unmixed training")
    return s


def _mix_knob_defaults() -> dict:
    """The single source of truth for mix-knob defaults: derived from the
    option spec above, so bespoke trainer specs that predate a knob fall
    back to exactly the documented default (no second literal to drift)."""
    cached = getattr(_mix_knob_defaults, "_cache", None)
    if cached is None:
        spec = add_mix_reliability_options(OptionSpec("_mix_knobs"))
        cached = {o.name: o.default for o in spec.options}
        _mix_knob_defaults._cache = cached
    return cached


def learner_option_spec(name: str, *, classification: bool,
                        default_loss: str) -> OptionSpec:
    """The shared trainer grammar (reference: LearnerBaseUDTF +
    GeneralLearnerBaseUDTF options)."""
    s = OptionSpec(name)
    s.add("loss", "loss_function", default=default_loss,
          help="loss function")
    s.add("opt", "optimizer", default="adagrad", help="optimizer")
    s.add("reg", "regularization", default="rda",
          help="regularization: no|l1|l2|elasticnet|rda")
    s.add("lambda", type=float, default=1e-6, help="regularization strength")
    s.add("l1_ratio", type=float, default=0.5, help="elasticnet mixing")
    s.add("eta", default="inverse", help="eta scheme: fixed|simple|inverse")
    s.add("eta0", type=float, default=0.1, help="initial learning rate")
    s.add("total_steps", type=int, default=10_000, help="simple-eta horizon")
    s.add("power_t", type=float, default=0.1, help="inverse-eta exponent")
    s.add("iters", "iterations", type=int, default=1, help="epochs")
    s.add("mini_batch", "mini_batch_size", type=int, default=256,
          help="minibatch size dispatched per jitted step")
    s.add("ingest_workers", type=int, default=0,
          help="host batch-prep pool size for fit/fit_stream: 0 = auto "
               "(cores-1 capped at 8 on accelerators, 1 on CPU); 1 = "
               "strict sequential (bit-exact pre-pipeline behavior); "
               "N > 1 = N prep worker threads delivering in order")
    s.add("ingest_pool", default="auto",
          help="prep pool kind for -ingest_workers > 1: thread (default — "
               "the canonicalize/pack prep is GIL-releasing NumPy/C++) | "
               "process (true multi-process prep for string-parse-heavy "
               "Python-bound sources; the trainer's prep must be a "
               "picklable config-built function — FFM and the base "
               "trainers qualify) | auto (thread)")
    s.add("shard_cache_dir", default=None,
          help="ahead-of-time packed shard cache directory "
               "(io.shard_cache): after the first epoch parses/"
               "canonicalizes/packs a source, the prepared buffers "
               "persist keyed by (source identity, prep-config digest); "
               "later epochs, -iters replays and restarts mmap them and "
               "skip host prep entirely. Parquet shard directories also "
               "cache their decoded CSR columns here. See "
               "docs/PERFORMANCE.md 'Shard cache'")
    s.add("steps_per_dispatch", type=int, default=0,
          help="fused multi-step dispatch: stack K prepared minibatches "
               "into ONE h2d transfer and run all K optimizer steps in "
               "one jitted lax.scan (donated state — no per-step table "
               "copies). 0 = auto (8 on accelerators for trainers with "
               "a scannable step, 1 on CPU); 1 = per-batch dispatch "
               "(bit-exact pre-fusion behavior); ragged tails and mixed "
               "batch kinds fall back to 1")
    s.add("dims", "feature_dimensions", type=int, default=1 << 24,
          help="model table size (hashed feature space)")
    s.flag("dense", "densemodel",
           help="accepted for reference compatibility (model is always a "
                "dense TPU table)")
    s.flag("disable_halffloat",
           help="keep float32 weights (default); unset-able via -halffloat")
    s.flag("halffloat", help="store weights as bfloat16 (HalfFloat analog)")
    s.flag("int_feature", help="features are integer indices, no hashing")
    s.add("mesh", default=None,
          help="device mesh spec ('dp=2,tp=4' or 'auto'): run the train "
               "step GSPMD-sharded — batch over dp, weight tables over tp")
    s.add("mix", default=None, help="mix cohort spec (parallel.mix)")
    s.add("mix_threshold", type=int, default=16,
          help="local updates between mix exchanges")
    s.add("mix_session", default=None, help="mix session/group id")
    add_mix_reliability_options(s)
    s.flag("ssl", help="TLS-wrap the MIX connection (reference LearnerBase "
                       "-ssl); pair with -ssl_cafile to verify the server")
    s.add("ssl_cafile", default=None,
          help="CA / self-signed server certificate to verify against "
               "(omit for encrypted-but-unauthenticated, matching the "
               "reference's in-cluster -ssl)")
    s.add("loadmodel", default=None, help="warm-start from a saved model table")
    # elastic recovery (SURVEY.md §6): autosaved full-state bundles +
    # mid-stream resume — see docs/RELIABILITY.md
    s.add("checkpoint_dir", default=None,
          help="directory for autosaved checkpoint bundles; enables "
               "resume() and per-epoch fit() bundles")
    s.add("checkpoint_every", type=int, default=0, min=0,
          help="autosave a full-state bundle every N optimizer steps "
               "during fit_stream (atomic write, last -checkpoint_keep "
               "retained); 0 = off")
    s.add("checkpoint_keep", type=int, default=3, min=1,
          help="how many autosaved step bundles to retain")
    # unified telemetry (docs/OBSERVABILITY.md): registry snapshots into
    # the jsonl stream at a step cadence, plus the live HTTP surface
    s.add("telemetry_every", type=int, default=0, min=0,
          help="emit the full obs-registry snapshot as a 'telemetry' "
               "jsonl event every N optimizer steps (requires "
               "HIVEMALL_TPU_METRICS); 0 = off")
    s.add("obs_port", type=int, default=0, min=0,
          help="serve the obs registry over HTTP on this port: /snapshot "
               "(JSON) and /metrics (Prometheus text exposition) — the "
               "MixServer-JMX analog for the training runtime; 0 = off")
    s.flag("cv", help="track cumulative loss for convergence check")
    return s


def _identity_prep(batch):
    """Module-level identity prep — the picklable stand-in for trainers
    whose parallel prep leg is the base no-op, so ``-ingest_pool process``
    works for every trainer (a bound method would not cross the fork)."""
    return batch


_STEP_BUILDER_CACHE: dict = {}


def shared_step(trainer, tag: str, builder):
    """Config-cached jitted step: same-class trainers with identical
    scalar options share ONE compiled step instead of re-tracing per
    instance (the per-instance re-jit disease — measured costing
    word2vec 4x and LDA 10x before the same fix; fm/ffm/linear use
    module-level lru_caches, this is the generic form for trainers whose
    steps are built from bound-method closures over opts). Safe because
    the steps take all state as arguments and the closures are pure
    functions of the keyed option values (donation applies per CALL)."""
    key = (type(trainer).__name__, tag,
           tuple(sorted((k, v) for k, v in trainer.opts.items()
                        if isinstance(v, (int, float, str, bool))
                        or v is None)))
    fn = _STEP_BUILDER_CACHE.get(key)
    if fn is None:
        # bounded like the fm/linear lru_caches: a sweep over many
        # distinct configs must not grow compiled-step memory forever
        if len(_STEP_BUILDER_CACHE) >= 256:
            _STEP_BUILDER_CACHE.pop(next(iter(_STEP_BUILDER_CACHE)))
        t0 = time.perf_counter()
        fn = builder()
        _STEP_BUILDER_CACHE[key] = fn
        # the generic peer of the lru_cache factories' build telemetry
        get_devprof().record_build(type(trainer).__name__, tag,
                                  time.perf_counter() - t0)
    return fn


class LearnerBase:
    """Subclasses set NAME/CLASSIFICATION/DEFAULT_LOSS and _build/_step."""

    NAME = "learner"
    CLASSIFICATION = True
    DEFAULT_LOSS = "hingeloss"

    def _shared_step(self, tag: str, builder):
        return shared_step(self, tag, builder)

    @classmethod
    def spec(cls) -> OptionSpec:
        return learner_option_spec(cls.NAME, classification=cls.CLASSIFICATION,
                                   default_loss=cls.DEFAULT_LOSS)

    def __init__(self, options: str = ""):
        self.opts: Parsed = self.spec().parse(options)
        self.dims = int(self.opts.dims)
        self._names: Dict[int, str] = {}      # hashed id -> original name
        self._buf_rows: List[Tuple[np.ndarray, np.ndarray]] = []
        self._buf_labels: List[float] = []
        # -iters replay buffer: RAM up to a byte budget, then disk
        # segments (the NioStatefulSegment analog — io/replay_segment.py)
        from ..io.replay_segment import RowSegmentStore
        self._replay = RowSegmentStore()
        self._t = 0                           # global step (batches seen)
        self._stream_pos = 0                  # fit_stream batches consumed
        self._loss_sum = 0.0                  # host float64, exact
        self._loss_pending = 0.0              # on-device partial, folded in
        self._examples = 0
        self._meter = Meter()                 # rolling examples/sec (§6)
        self._tracer = get_tracer()           # span tracing (obs.trace)
        self._flight = get_flight()           # black box (obs.flight)
        self._devprof = get_devprof()         # compile/memory/drift (obs)
        self.pipeline_stats = PipelineStats()  # last fit's ingest metrics
        self._mixer = None
        self._ck_manager = None               # fit_stream's autosaver (obs)
        self._fit_ds = None                   # columnar dataset ref (fit)
        self.mesh = None                      # jax Mesh when -mesh is set
        self._tp_sizes = {self.dims}          # axis sizes sharded over 'tp'
        self._elision_off = False             # set on first non-unit batch
        self._init_state()
        if self.opts.get("mix"):
            # covariance trainers (CW/AROW/SCW) mix by argmin-KLD —
            # precision-weighted Gaussian posterior merge (SURVEY.md §3.16)
            from ..parallel.mix_service import (EVENT_ARGMIN_KLD,
                                                EVENT_AVERAGE, MixClient)
            has_covar = getattr(self, "sigma", None) is not None
            sslctx = None
            if self.opts.get("ssl"):
                from ..parallel.mix_service import make_client_ssl_context
                sslctx = make_client_ssl_context(self.opts.ssl_cafile)
            # bespoke trainer specs may predate a knob: fall back to the
            # spec-derived default rather than requiring every spec to
            # carry all of add_mix_reliability_options (None = unset,
            # 0 is a valid setting)
            defaults = _mix_knob_defaults()

            def knob(name):
                v = self.opts.get(name)
                return defaults[name] if v is None else v
            self._mixer = MixClient(
                self.opts.mix,
                group=self.opts.mix_session or self.NAME,
                threshold=int(self.opts.mix_threshold),
                event=EVENT_ARGMIN_KLD if has_covar else EVENT_AVERAGE,
                timeout=float(knob("mix_timeout")),
                ssl_context=sslctx,
                retries=int(knob("mix_retries")),
                backoff=float(knob("mix_backoff")),
                deadline=float(knob("mix_deadline")) or None,
                breaker_threshold=int(knob("mix_breaker_threshold")),
                breaker_cooldown=float(knob("mix_breaker_cooldown")),
                breaker_trips=int(knob("mix_breaker_trips")))
        if self.opts.loadmodel:
            self._warm_start(self.opts.loadmodel)
        if self.opts.get("mesh"):
            self._apply_mesh(self.opts.mesh)
        self._telemetry_every = int(self.opts.get("telemetry_every") or 0)
        self._register_obs()

    @classmethod
    def make_parser(cls, options: str = "") -> "LearnerBase":
        """A PARSE-ONLY instance: option grammar + feature hashing
        (`_parse_row`), with ``_init_state`` skipped — no device tables,
        no optimizer state. The serve engine's arena path uses this so a
        replica that scores from the mmap'd weight arena never allocates
        a dims-sized trainer just to hash request rows (the whole point
        of zero-copy serving). Only parsing methods are usable on the
        result; training/scoring surfaces raise AttributeError."""
        self = object.__new__(cls)
        self.opts = cls.spec().parse(options)
        self.dims = int(self.opts.dims)
        self._names = {}
        self.mesh = None
        self._init_parser()
        return self

    def _init_parser(self) -> None:
        """Hook for subclasses whose ``_parse_row`` needs extra state
        (FFM's field count). Default: nothing beyond make_parser's."""

    # -- subclass surface ----------------------------------------------------
    def _init_state(self) -> None:
        raise NotImplementedError

    def _train_batch(self, batch: SparseBatch):
        """Run one jitted step; returns the summed loss over valid rows as a
        device array (kept unconverted so async dispatch can pipeline; the
        base loop folds it via _fold_loss at cadence)."""
        raise NotImplementedError

    def _finalized_weights(self) -> np.ndarray:
        raise NotImplementedError

    # -- unified telemetry (obs.registry, docs/OBSERVABILITY.md) -------------
    def _register_obs(self) -> None:
        """Register this trainer's counter surfaces with the central obs
        registry: ``pipeline`` (ingest/stager/h2d stage counters),
        ``train`` (step/examples/rate/loss), and ``mix`` (client breaker +
        exchange counters) when mixing. Providers hold the trainer weakly
        (the registry is process-global, must not pin dead trainers) and
        are non-blocking — avg_loss reads the host-side folded sum only,
        never syncing the device from a scrape thread."""
        import weakref
        from ..obs.registry import CHECKPOINT_STUB, MIX_STUB, registry
        ref = weakref.ref(self)

        def pipeline() -> dict:
            t = ref()
            return t.pipeline_stats.as_dict() if t is not None else {}

        def train() -> dict:
            t = ref()
            if t is None:
                return {}
            return {"trainer": t.NAME, "step": t._t,
                    "examples": t._examples,
                    "examples_per_sec": round(t._meter.rate, 1),
                    "avg_loss": round(t._loss_sum / max(1, t._examples), 6)}

        def mix() -> dict:
            t = ref()
            if t is None or t._mixer is None:
                return dict(MIX_STUB)     # inactive form mirrors live keys
            c = dict(t._mixer.counters())
            c["active"] = True
            return c

        def checkpoint() -> dict:
            t = ref()
            m = getattr(t, "_ck_manager", None) if t is not None else None
            return m.obs_section() if m is not None \
                else dict(CHECKPOINT_STUB)

        # every section registers UNCONDITIONALLY, bound to THIS trainer:
        # a trainer without a mixer/autosaver reports inactive rather than
        # letting a previous trainer's live sections leak into its
        # snapshots (last-wins registration makes construction the reset)
        registry.register("pipeline", pipeline)
        registry.register("train", train)
        registry.register("mix", mix)
        registry.register("checkpoint", checkpoint)
        # a telemetry cadence or live obs surface means someone is
        # watching: turn on the devprof drift watches (per-dispatch step
        # drift, memory-leak drift) for this process. Without either the
        # watches stay off and note_dispatch is one attribute check.
        if self._telemetry_every or int(self.opts.get("obs_port") or 0):
            self._devprof.activate()
        if int(self.opts.get("obs_port") or 0):
            from ..obs.http import ensure_server
            ensure_server(int(self.opts.obs_port))

    def _emit_cadence_events(self, window: int) -> None:
        """The per-dispatch emission ladder. ``window`` is how many
        optimizer steps this dispatch advanced (K for a fused megastep).

        Loss-fold cadence (a 256-step boundary crossed): fold the device
        loss partial into the host float64, then — stream permitting —
        emit ``train_step`` (the reportProgress analog) and, when tracing,
        the per-stage ``span_rollup``. ``-telemetry_every`` boundaries
        additionally emit the full registry snapshot."""
        if self._t % 256 < window:
            self._fold_loss()
            fl = self._flight
            if fl.enabled:
                # the trainer's heartbeat in the black box: a fit that
                # dies (OOM'd retrain child, SIGKILLed worker) leaves its
                # last step/loss on disk for the post-mortem
                fl.record("fit.step",
                          f"step={self._t}{FS}ex={self._examples}{FS}"
                          f"loss={self._loss_sum / max(1, self._examples):.6f}")
            stream = get_stream()
            if stream.enabled:
                stream.emit("train_step", trainer=self.NAME, step=self._t,
                            examples=self._examples,
                            examples_per_sec=round(self._meter.rate, 1),
                            avg_loss=round(self._loss_sum
                                           / max(1, self._examples), 6))
                if self._tracer.enabled:
                    stream.emit("span_rollup", trainer=self.NAME,
                                step=self._t, stages=self._tracer.rollup())
        every = self._telemetry_every
        if every and self._t % every < window:
            # refresh the device-memory gauges FIRST so the snapshot about
            # to be emitted carries this boundary's sample (and the
            # live-bytes stream feeds the mem-drift detector at exactly
            # the telemetry cadence)
            self._devprof.sample_memory()
            stream = get_stream()
            if stream.enabled:
                from ..obs.registry import registry
                stream.emit("telemetry", trainer=self.NAME, step=self._t,
                            snapshot=registry.snapshot())

    def _emit_train_done(self) -> None:
        """``train_done`` carrying the merged registry snapshot — the
        one-record run summary both the jsonl surface and the ``obs`` CLI
        read — plus the Chrome-trace export when configured."""
        stream = get_stream()
        if stream.enabled:
            from ..obs.registry import registry
            stream.emit("train_done", trainer=self.NAME, step=self._t,
                        examples=self._examples,
                        avg_loss=round(self.cumulative_loss, 6),
                        telemetry=registry.snapshot())
        fl = self._flight
        if fl.enabled:
            fl.record("fit.done",
                      f"step={self._t}{FS}ex={self._examples}")
        self._tracer.maybe_export()
        # one completed fit = compile warmup over: arm the no-retrace
        # sentinel so a later same-config trainer that re-compiles (the
        # word2vec disease) flags itself as `retrace` telemetry
        self._devprof.note_train_done()

    def _emit_checkpoint_event(self, path: str, **fields) -> None:
        """The ONE checkpoint-event emitter (epoch bundles here and in
        fm.py's adareg loop, CheckpointManager's cadence saves)."""
        stream = get_stream()
        if stream.enabled:
            stream.emit("checkpoint", trainer=self.NAME, path=path, **fields)

    def _save_epoch_bundle(self, ckdir: str, epoch: int) -> str:
        """Per-epoch full-state bundle + its checkpoint event."""
        os.makedirs(ckdir, exist_ok=True)
        path = os.path.join(ckdir, f"{self.NAME}-ep{epoch}.npz")
        self.save_bundle(path)
        self._emit_checkpoint_event(path, epoch=epoch)
        return path

    # -- UDTF lifecycle ------------------------------------------------------
    def process(self, features: Sequence[str] | Tuple[np.ndarray, np.ndarray],
                label: float) -> None:
        """Feed one row: features as "name:value" strings (or pre-parsed
        (idx, val) arrays), label per trainer convention."""
        idx, val = self._parse_row(features)
        y = self._convert_label(label)
        self._buf_rows.append((idx, val))
        self._buf_labels.append(y)
        if len(self._buf_rows) >= int(self.opts.mini_batch):
            self._flush()

    def close(self) -> Iterator[Tuple[str, float]]:
        """Flush, run extra epochs (-iters), emit model rows."""
        self._flush()
        iters = int(self.opts.iters)
        if iters > 1 and self._replay.n_rows:
            # epoch replay over the recorded stream (NioStatefulSegment
            # analog): exact global shuffle while everything fits the RAM
            # budget; past it, rows live in disk segments and epochs
            # stream them back one segment at a time (segment order and
            # within-segment rows shuffled)
            rng = np.random.default_rng(42)
            bs = int(self.opts.mini_batch)
            for ep in range(1, iters):
                if not self._replay.spilled:
                    rows_all = self._replay.ram_rows
                    labels_all = self._replay.ram_labels
                    order = rng.permutation(len(rows_all))
                    for s in range(0, len(order), bs):
                        take = order[s:s + bs]
                        self._flush_chunk([rows_all[i] for i in take],
                                          [labels_all[i] for i in take])
                else:
                    for rows, labels in self._replay.epoch_rows(rng):
                        for s in range(0, len(rows), bs):
                            self._flush_chunk(rows[s:s + bs],
                                              labels[s:s + bs])
        self._replay.cleanup()
        if self._mixer is not None:
            self._mixer.close_group()
        self._emit_train_done()
        yield from self.model_rows()

    # -- columnar fast path --------------------------------------------------
    def fit(self, ds: SparseDataset, *, epochs: Optional[int] = None,
            shuffle: bool = True,
            prefetch: Optional[bool] = None) -> "LearnerBase":
        epochs = int(self.opts.iters) if epochs is None else epochs
        bs = int(self.opts.mini_batch)
        labels = self._convert_labels(ds.labels)
        sid = getattr(ds, "source_id", None)   # survives the label rebuild:
        ds = SparseDataset(ds.indices, ds.indptr, ds.values, labels, ds.fields)
        if sid:                                # the shard cache keys on it
            ds.source_id = sid
        if self._wants_fit_ds():
            self._fit_ds = ds             # emission-time metadata (FFM pairs)
        # elastic recovery (SURVEY.md §6): per-epoch bundle when requested
        # (-checkpoint_dir option, or the env var the pre-option path used)
        ckdir = self.opts.get("checkpoint_dir") \
            or os.environ.get("HIVEMALL_TPU_CHECKPOINT_DIR")
        # tracing/profiling (SURVEY.md §6): HIVEMALL_TPU_PROF=<dir>
        # captures a jax.profiler trace of the FIRST fit() in the process
        # — open with tensorboard/xprof. Routed through obs.devprof so
        # the capture is discoverable (a `profile.capture` span + a
        # `profile` jsonl event) instead of an invisible side effect.
        prof_dir = self._devprof.start_profile_once()
        self.pipeline_stats = PipelineStats()   # fresh counters per fit
        try:
            self._fit_epochs(ds, epochs, bs, shuffle, prefetch, ckdir)
        finally:
            self._devprof.stop_profile(prof_dir)
        # one train_done per completed fit (the columnar peer of close()/
        # fit_stream), carrying the merged registry snapshot; not emitted
        # on the exception path
        self._emit_train_done()
        return self

    def _fit_epochs(self, ds, epochs, bs, shuffle, prefetch, ckdir,
                    seed0: int = 42) -> None:
        # overlap host batch prep + h2d with compute on accelerators
        # (the prefetcher places on the default device; under -mesh the
        # dispatch path does its own sharded placement instead).
        # seed0: first epoch's shuffle seed — continuation callers (the
        # FFM replay cache's fallback) pass 42 + epochs_already_run so the
        # schedule matches an uninterrupted fit
        if prefetch is None:
            import jax
            prefetch = jax.default_backend() != "cpu" and self.mesh is None
        for ep in range(epochs):
            closers: List = []
            it = self._ingest_iter(
                ds.batches(bs, shuffle=shuffle, seed=seed0 + ep), closers)
            it = self._wrap_megabatch(it, prefetch=prefetch)
            if prefetch:
                it = self._wrap_prefetch(it, closers)
            try:
                for b in it:
                    self._dispatch(b)
            finally:
                for c in reversed(closers):
                    c()              # release the workers on early exit too
            if ckdir:
                self._save_epoch_bundle(ckdir, ep + 1)

    def _wants_fit_ds(self) -> bool:
        """Whether fit() should keep a reference to the training dataset for
        emission-time metadata. Default no — pinning a Criteo-scale dataset
        on the trainer for its whole lifetime is not free."""
        return False

    # Trainers whose jitted step accepts val=None (rebuilding it from idx
    # on device) set this True: unit-valued categorical batches then skip
    # the val h2d transfer entirely (a third of batch bytes — the link is
    # the measured e2e bottleneck; see io.sparse.SparseBatch).
    UNIT_VAL_ELISION = False

    def _preprocess_batch(self, batch: SparseBatch) -> SparseBatch:
        """Host-side per-batch hook, applied BEFORE device staging (so the
        prefetcher overlaps it with compute). Default: unit-value elision
        when the trainer's step supports it; FFM's joint layout overrides
        to canonicalize into field-major slots.

        The first non-unit batch disables the scan for the trainer's
        lifetime (real-valued datasets stay non-unit; a unit batch arriving
        later merely misses the optimization, which is always correct) —
        the O(B*L) check must not tax every epoch of data that can never
        elide."""
        if (self.UNIT_VAL_ELISION and not self._elision_off
                and isinstance(batch.val, np.ndarray)
                and isinstance(batch.idx, np.ndarray)):
            if np.array_equal(batch.val,
                              (batch.idx != 0).astype(np.float32)):
                return SparseBatch(batch.idx, None, batch.label, batch.field,
                                   n_valid=batch.n_valid,
                                   fieldmajor=batch.fieldmajor)
            self._elision_off = True
        return batch

    def _preprocess_train_batch(self, batch: SparseBatch):
        """TRAINING-ONLY per-batch hook (fit / fit_stream / process-flush):
        the serial leg then the parallel leg. Subclasses whose training
        dispatch accepts a representation scoring can't consume (e.g.
        FFM's packed uint8 transfer buffers) override the LEGS below,
        keeping _preprocess_batch — which the scoring paths share —
        representation-stable."""
        return self._preprocess_train_parallel(
            self._preprocess_train_serial(batch))

    def _preprocess_train_serial(self, batch: SparseBatch):
        """STREAM-ORDER-DEPENDENT training prep. Runs on ONE thread in
        source order even under -ingest_workers > 1 (the pipeline's
        submitter side), because the base elision latch (_elision_off)
        makes a batch's representation depend on the batches before it —
        fanning it out would make the output order-dependent and break
        the N-worker == sequential bit-exactness the tests pin."""
        return self._preprocess_batch(batch)

    def _preprocess_train_parallel(self, batch):
        """ORDER-INDEPENDENT training prep — the leg that fans out across
        the -ingest_workers pool. Must be a pure function of the batch
        (FFM's canonicalize + pack lives here)."""
        return batch

    # -- parallel host ingest (SURVEY.md §8: the input path IS the wall) ----
    def _resolved_ingest_workers(self) -> int:
        """-ingest_workers with 0 = auto: cores-1 (cap 8) on accelerators —
        host prep there runs against a waiting chip — and 1 (strict
        sequential) on CPU, where the train step already owns the cores.
        Auto also collapses to 1 when the trainer never overrode the
        parallel prep leg (base identity): a pool whose workers each run
        ``return batch`` is pure queue overhead. An EXPLICIT N is always
        honored (tests drive the pipeline machinery through it)."""
        n = int(self.opts.get("ingest_workers") or 0)
        if n > 0:
            return n
        if type(self)._preprocess_train_parallel \
                is LearnerBase._preprocess_train_parallel:
            return 1
        import jax
        if jax.default_backend() == "cpu":
            return 1
        from ..io.pipeline import auto_workers
        return auto_workers()

    def _resolved_ingest_pool(self) -> str:
        """-ingest_pool with auto = thread: the in-tree prep profile
        (padding fancy-indexing, canonicalize, pack) is GIL-releasing
        NumPy/C++, so threads win by skipping per-batch pickling; process
        is the explicit opt-in for Python-bound string-parse prep."""
        p = str(self.opts.get("ingest_pool") or "auto")
        if p not in ("auto", "thread", "process"):
            raise ValueError(
                f"-ingest_pool must be auto|thread|process, got {p!r}")
        return "thread" if p == "auto" else p

    def _picklable_prep(self):
        """The parallel prep leg as a PICKLABLE callable for
        ``-ingest_pool process`` (a bound trainer method cannot cross the
        fork: it would drag the whole trainer — device arrays included —
        through pickle per task). Base trainers' parallel leg is the
        identity, which is trivially picklable; trainers that override the
        leg must also override this (FFM builds one from a plain prep
        config dataclass) or process pools fall back to threads."""
        if type(self)._preprocess_train_parallel \
                is LearnerBase._preprocess_train_parallel:
            return _identity_prep
        return None

    def _ingest_iter(self, src, closers: List):
        """Route ``_preprocess_train_batch`` over ``src`` through the
        parallel ingest pipeline (io.pipeline). workers <= 1 is a strict
        sequential fallback — a plain ``map``, bit-exact with pre-pipeline
        behavior. An opened pipeline's close lands in ``closers`` for the
        caller's finally; batches arrive in source order either way.
        workers <= 1 uses the pipeline's inline sequential mode — literally
        next(src) then fn(item), no threads — so the stage counters emit
        on both paths.

        The serial leg (_preprocess_train_serial: the elision latch) is
        composed into the SOURCE, so the pipeline's single submitter
        thread runs it in stream order; only the order-independent
        parallel leg fans out. The composition equals
        _preprocess_train_batch exactly on every path.

        ``-ingest_pool process`` swaps the bound parallel leg for the
        trainer's picklable config-built equivalent (same function of the
        batch, pinned bit-exact by tests/test_pipeline.py); trainers
        without one fall back to the thread pool with a warning."""
        from ..io.pipeline import IngestPipeline
        pool = self._resolved_ingest_pool()
        fn = self._preprocess_train_parallel
        if pool == "process":
            pfn = self._picklable_prep()
            if pfn is None:
                import warnings
                warnings.warn(
                    f"{type(self).__name__} has no picklable prep for "
                    f"-ingest_pool process; falling back to threads",
                    RuntimeWarning, stacklevel=2)
                pool = "thread"
            else:
                fn = pfn
        pipe = IngestPipeline(map(self._preprocess_train_serial, src), fn,
                              workers=self._resolved_ingest_workers(),
                              pool=pool, stats=self.pipeline_stats)
        closers.append(pipe.close)
        return pipe

    def _wrap_prefetch(self, it, closers: List, depth: int = 2):
        """Stage ``it`` onto the device ahead of compute, sharing this
        trainer's PipelineStats so prep/transfer/compute waits land in one
        struct (the bench's stage decomposition reads it)."""
        from ..io.prefetch import DevicePrefetcher
        pf = DevicePrefetcher(it, depth=depth, stats=self.pipeline_stats)
        closers.append(pf.close)
        return pf

    # -- fused multi-step dispatch (-steps_per_dispatch, ops.scan) -----------
    def _supports_megastep(self) -> bool:
        """Whether this trainer's step is scannable: the jitted step
        carries its pure ``(state, batch) -> (state, loss)`` core
        (ops.scan.scannable) and the trainer uses the standard
        (params-or-w, opt_state) state pair. Trainers with bespoke state
        (covariance pairs, tree ensembles, ...) fall out here and keep
        per-batch dispatch."""
        return getattr(getattr(self, "_step", None), "core", None) \
            is not None

    def _resolved_steps_per_dispatch(self) -> int:
        """-steps_per_dispatch with 0 = auto: 8 on accelerators — the
        per-batch jit call + h2d latency is the post-PR-1 e2e wall there
        — and 1 (per-batch, bit-exact pre-fusion behavior) on CPU, where
        dispatch overhead is noise and the test suite pins the K=1
        trajectory. Collapses to 1 for trainers without a scannable step
        and under MIX (the mix client touches every batch's idx on host
        at step cadence — fusing K steps would skip exchanges)."""
        k = int(self.opts.get("steps_per_dispatch") or 0)
        if k < 0:
            raise ValueError(f"-steps_per_dispatch must be >= 0, got {k}")
        if not self._supports_megastep() or self._mixer is not None:
            return 1
        if k > 0:
            return k
        import jax
        return 8 if jax.default_backend() != "cpu" else 1

    def _wrap_megabatch(self, it, *, prefetch: bool):
        """Insert the K-step stacking stage between host prep and the
        h2d prefetcher. Staging-buffer reuse is only armed when a
        DevicePrefetcher consumes the stager (its stage_batch provides
        the transfer-complete barrier the buffer ring needs)."""
        k = self._resolved_steps_per_dispatch()
        if k <= 1:
            return it
        from ..io.prefetch import MegabatchStager
        return MegabatchStager(it, k, stats=self.pipeline_stats,
                               reuse=prefetch and self.mesh is None)

    # -- mesh sharding (SURVEY.md §3.17 / §8 M3) -----------------------------
    def _apply_mesh(self, spec: str) -> None:
        """Shard this trainer's state over a (dp, tp) device mesh.

        The PRODUCT multi-chip path (not a demo kernel): the same jitted
        sparse step the single-chip trainer runs is compiled under GSPMD —
        batch arrays sharded over 'dp' (XLA inserts the gradient psum that
        replaces MixServer averaging), every dims-sized state axis sharded
        over 'tp' (feature-dim sharding, the context-parallel analog), the
        rest replicated. fit()/process() are unchanged."""
        import jax
        import jax.numpy as jnp
        from ..parallel.mesh import make_mesh, parse_mesh_spec
        dp, tp = parse_mesh_spec(spec)
        if int(self.opts.mini_batch) % dp:
            raise ValueError(
                f"-mini_batch {self.opts.mini_batch} must be divisible by "
                f"the dp axis ({dp})")
        self.mesh = make_mesh(dp=dp, tp=tp)
        self._reshard_state()

    def _state_sharding(self, leaf):
        """NamedSharding for one state leaf: the first axis whose size is a
        registered table size (_tp_sizes: dims, FFM's Mr, ...) -> 'tp',
        everything else replicated (w0, counters, small tables)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        shape = getattr(leaf, "shape", ())
        for ax, s in enumerate(shape):
            if s in self._tp_sizes:
                return NamedSharding(
                    self.mesh,
                    P(*["tp" if a == ax else None for a in range(len(shape))]))
        return NamedSharding(self.mesh, P())

    def _reshard_state(self) -> None:
        """device_put every checkpointable array with its mesh sharding."""
        import jax
        import jax.numpy as jnp
        tree = self._checkpoint_arrays()
        tree = jax.tree_util.tree_map(
            lambda l: jax.device_put(jnp.asarray(l), self._state_sharding(l)),
            tree)
        self._restore_arrays(tree)

    def _shard_batch(self, batch: SparseBatch) -> SparseBatch:
        """Place one padded batch on the mesh: rows sharded over 'dp'.
        val=None (unit-value elision) skips that transfer; the jitted
        unit-val step rebuilds val from idx under the same sharding."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(a, spec):
            return jax.device_put(jnp.asarray(a), NamedSharding(self.mesh,
                                                                spec))
        return SparseBatch(
            put(batch.idx, P("dp", None)),
            None if batch.val is None else put(batch.val, P("dp", None)),
            put(batch.label, P("dp")),
            None if batch.field is None else put(batch.field, P("dp", None)),
            n_valid=batch.n_valid, fieldmajor=batch.fieldmajor)

    def fit_stream(self, batches: Iterable[SparseBatch], *,
                   convert_labels: bool = True,
                   resume: bool = False,
                   _emit_done: bool = True) -> "LearnerBase":
        """Out-of-core training over a stream of padded batches (e.g.
        io.arrow.ParquetStream.batches): each batch dispatches one jitted
        step; nothing is buffered, so resident memory is one shard.
        Epoch count is owned by the stream (ParquetStream re-reads shards
        per epoch — the NioStatefulSegment analog at corpus scale). On
        accelerators the shard read/parse overlaps device compute via the
        same DevicePrefetcher fit() uses; -ingest_workers > 1 additionally
        shards the batch prep (canonicalize/pack) across a worker pool.

        Fault tolerance (docs/RELIABILITY.md): with -checkpoint_dir +
        -checkpoint_every, a full-state bundle autosaves atomically every
        N steps plus once at stream end. After a crash, ``resume()`` then
        ``fit_stream(same_stream, resume=True)`` skips the checkpointed
        stream prefix and continues; at -steps_per_dispatch 1 the
        post-restore loss trajectory is bit-exact vs. an uninterrupted
        run (the stream must be deterministic — same shard order and
        shuffle seed)."""
        import jax
        self.pipeline_stats = PipelineStats()
        # HIVEMALL_TPU_PROF covers the streaming path too (the long-running
        # workloads one most wants to profile); the once-per-process latch
        # makes the repeated fit_stream calls of multi-epoch wrappers safe
        prof_dir = self._devprof.start_profile_once()
        if resume and self._stream_pos:
            from ..io.replay_segment import skip_batches
            batches = skip_batches(batches, self._stream_pos)
        elif not resume:
            # a fresh stream starts at position 0 — without this, a second
            # fit_stream on the same trainer (FFM's per-epoch loop, any
            # sequential reuse) would checkpoint positions offset by the
            # previous stream's length and resume would skip wrongly
            self._stream_pos = 0
        # the manager is pinned on the trainer (not a local) so the obs
        # registry's weakly-held `checkpoint` section — last_saved_step,
        # age_seconds, bundle count — outlives the stream and stays
        # readable between runs for as long as the trainer does
        autosaver = self._ck_manager = self._autosaver()

        def host_side() -> Iterator[SparseBatch]:
            # label conversion + pair tracking stay on HOST arrays and in
            # STREAM ORDER (the source side of the pipeline is serial);
            # _preprocess_train_batch then fans out over the prep workers
            for b in batches:
                if convert_labels:
                    b = SparseBatch(b.idx, b.val,
                                    self._convert_labels(b.label),
                                    b.field, n_valid=b.n_valid,
                                    fieldmajor=b.fieldmajor)
                self._note_batch(b)
                yield b

        closers: List = []
        it: Iterable[SparseBatch] = self._ingest_iter(host_side(), closers)
        prefetch = jax.default_backend() != "cpu" and self.mesh is None
        it = self._wrap_megabatch(it, prefetch=prefetch)
        if prefetch:
            it = self._wrap_prefetch(it, closers)
        try:
            for b in it:
                self._dispatch(b)
                # stream position = SOURCE batches consumed (a fused K-step
                # window is K source batches) — what resume() skips past
                self._stream_pos += int(getattr(b, "n_steps", 1))
                if autosaver is not None:
                    autosaver.maybe_save(self)
        finally:
            for c in reversed(closers):
                c()
            self._devprof.stop_profile(prof_dir)
        if autosaver is not None:
            # completed stream: make the final state durable too (cadence
            # saves only land on -checkpoint_every boundaries). No save on
            # the exception path — the last cadence bundle IS the recovery
            # point a crashed run resumes from.
            autosaver.save_final(self)
        # completed stream: one train_done record carrying the merged
        # registry snapshot (pipeline/train/mix/checkpoint/spans) — the
        # jsonl peer of `curl /snapshot`. Not emitted on the exception
        # path (a crashed stream has no "done") nor when this call is one
        # epoch inside a multi-epoch wrapper (_emit_done=False: FFM's
        # replay fit_stream emits ONE record for the whole run).
        if _emit_done:
            self._emit_train_done()
        return self

    def _autosaver(self):
        """CheckpointManager for this fit_stream, or None when autosave is
        not configured (-checkpoint_dir AND -checkpoint_every required)."""
        ckdir = self.opts.get("checkpoint_dir")
        every = int(self.opts.get("checkpoint_every") or 0)
        if not ckdir or every <= 0:
            return None
        from ..io.checkpoint import CheckpointManager
        return CheckpointManager(
            ckdir, self.NAME, keep=int(self.opts.get("checkpoint_keep") or 3),
            every=every, start_step=self._t)

    def resume(self, checkpoint_dir: Optional[str] = None) -> bool:
        """Restore the newest USABLE autosaved bundle from
        ``checkpoint_dir`` (default: the -checkpoint_dir option). Bundles
        failing validation — truncated file, digest mismatch, options
        mismatch — are skipped with a warning, falling back to the next
        newest (the retention window exists exactly for this). Returns
        True when state was restored; follow with
        ``fit_stream(same_stream, resume=True)`` to continue mid-stream."""
        import warnings
        import zipfile
        ckdir = checkpoint_dir or self.opts.get("checkpoint_dir")
        if not ckdir:
            return False
        from ..io.checkpoint import list_bundles
        for path in list_bundles(ckdir, self.NAME):
            try:
                self.load_bundle(path)
                return True
            except (ValueError, KeyError, OSError,
                    zipfile.BadZipFile) as e:
                warnings.warn(f"skipping unusable checkpoint {path}: {e}",
                              RuntimeWarning, stacklevel=2)
        return False

    def _note_batch(self, batch: SparseBatch) -> None:
        """Hook for emission-time metadata on the streaming path (FFM joint
        layout tracks observed (feature, field) pairs here)."""

    # -- shared plumbing -----------------------------------------------------
    def _parse_row(self, features) -> Tuple[np.ndarray, np.ndarray]:
        if (isinstance(features, tuple) and len(features) == 2
                and isinstance(features[0], np.ndarray)):
            return features
        idx: List[int] = []
        val: List[float] = []
        for f in features:
            if f is None or f == "":
                continue
            name, v = split_feature(f)
            try:
                i = int(name)
            except ValueError:
                if self.opts.int_feature:
                    raise ValueError(
                        f"-int_feature set but feature {name!r} not an int")
                i = mhash(name, self.dims - 1)  # ids in [1, dims-1]
                self._names.setdefault(i, name)
            idx.append(i)
            val.append(float(v))
        return np.asarray(idx, np.int32), np.asarray(val, np.float32)

    def _convert_label(self, label: float) -> float:
        if self.CLASSIFICATION:
            return 1.0 if float(label) > 0 else -1.0
        return float(label)

    def _convert_labels(self, labels: np.ndarray) -> np.ndarray:
        if self.CLASSIFICATION:
            return np.where(labels > 0, 1.0, -1.0).astype(np.float32)
        return labels.astype(np.float32)

    # shared shape bucket (io.sparse.pow2_len); kept as a method alias for
    # subclasses that call self._pow2_len
    _pow2_len = staticmethod(pow2_len)

    def _flush(self) -> None:
        if not self._buf_rows:
            return
        rows, labels = self._buf_rows, self._buf_labels
        self._buf_rows, self._buf_labels = [], []
        if int(self.opts.iters) > 1:
            self._replay.append(rows, labels)
        self._flush_chunk(rows, labels)

    def _flush_chunk(self, rows, labels) -> None:
        """Pad one chunk of buffered rows into a SparseBatch and dispatch."""
        B = int(self.opts.mini_batch)
        L = self._pow2_len(max(1, max(len(r[0]) for r in rows)))
        idx = np.zeros((B, L), np.int32)
        val = np.zeros((B, L), np.float32)
        lab = np.zeros(B, np.float32)
        for b, (i, v) in enumerate(rows):
            idx[b, :len(i)] = i
            val[b, :len(v)] = v
            lab[b] = labels[b]
        nv = len(rows)
        self._dispatch(self._preprocess_train_batch(
            SparseBatch(idx, val, lab, n_valid=nv if nv < B else None)))

    # test/debug hook: when set to a list, every dispatched step appends
    # its per-batch loss sum (host float) — the K>1 == K=1 trajectory
    # tests pin exact batch order through it. None (default) costs one
    # attribute check per dispatch and never syncs the device.
    _trace_losses: Optional[List[float]] = None

    def _dispatch(self, batch) -> None:
        if isinstance(batch, (MegaBatch, PackedMegaBatch)):
            return self._dispatch_mega(batch)
        nv = batch.n_valid or batch.batch_size
        if self.mesh is not None:
            batch = self._shard_batch(batch)
        # the span is the HOST-side dispatch boundary: synchronous compute
        # on CPU, dispatch latency on accelerators (async tails land in
        # the next blocking boundary) — the same semantics as the bench's
        # stage decomposition
        t0 = time.perf_counter()
        with self._tracer.span("dispatch.step"):
            loss_sum = self._train_batch(batch)
        self._devprof.note_dispatch(time.perf_counter() - t0, 1)
        self._t += 1
        # keep the per-step loss on device: float() here would block the host
        # on every minibatch and stall the dispatch pipeline. The device
        # partial is f32, so fold it into the exact host float64 sum every
        # 256 batches before the running magnitude can swamp the increments.
        self._loss_pending = self._loss_pending + loss_sum
        self._examples += nv
        self._meter.add(nv)
        if self._trace_losses is not None:
            self._trace_losses.append(float(loss_sum))
        self._emit_cadence_events(1)        # reportProgress analog (§6)
        if self._mixer is not None:
            self._mixer.touch(batch.idx[:nv])
            self._mixer.maybe_mix(self)

    def _dispatch_mega(self, mb) -> None:
        """Dispatch one K-step megabatch: ONE jitted lax.scan call runs
        all K optimizer steps with the state donated through the scan
        carry (no per-step table copies, no per-step Python). The [K]
        per-step loss vector stays on device; its sum folds into the
        host float64 at the same 256-step cadence as the K=1 path, so no
        step ever blocks the host."""
        K = mb.n_steps
        nv_total = mb.n_examples
        if self.mesh is not None:
            mb = self._shard_megabatch(mb)
        t0 = time.perf_counter()
        with self._tracer.span("dispatch.megastep"):
            losses = self._train_megabatch(mb)      # [K] device array
        self._devprof.note_dispatch(time.perf_counter() - t0, K)
        self._t += K
        self._loss_pending = self._loss_pending + losses.sum()
        self._examples += nv_total
        self._meter.add(nv_total)
        if self._trace_losses is not None:
            import numpy as np
            self._trace_losses.extend(
                float(v) for v in np.asarray(losses))
        # emit when this window crossed a multiple-of-256 step boundary
        # (the K=1 condition `t % 256 == 0` is the K=1 case of this)
        self._emit_cadence_events(K)

    def _megastep_state(self) -> Tuple[Any, Any]:
        """(model-state, optimizer-state) pair threaded through the scan
        carry. Covers the standard attribute names; trainers with other
        state override this and `_set_megastep_state` as a pair."""
        s1 = getattr(self, "params", None)
        if s1 is None:
            s1 = self.w
        return s1, self.opt_state

    def _set_megastep_state(self, s1, s2) -> None:
        if getattr(self, "params", None) is not None:
            self.params = s1
        else:
            self.w = s1
        self.opt_state = s2

    def _mega_field(self, mb):
        """Per-step field arrays for the megastep (FFM pairs path only —
        the base/linear/FM cores take no field argument, so a stacked
        field array, if the dataset carries one, is simply not fed)."""
        return None

    def _mega_lams(self):
        """Broadcast (non-scanned) extra for the megastep — train_fm's
        -adareg runtime lambdas. None for everyone else."""
        return None

    def _train_megabatch(self, mb):
        """Run K steps through the shared megastep built from this
        trainer's scannable step core (ops.scan.megastep_for). Returns
        the [K] per-step loss sums as a device array."""
        import jax.numpy as jnp
        from ..ops.scan import megastep_for
        mega = megastep_for(self._step, none_val=True)
        nv = mb.nv_dev if mb.nv_dev is not None else jnp.asarray(mb.nv)
        s1, s2 = self._megastep_state()
        s1, s2, losses = mega(s1, s2, float(self._t), nv, mb.idx, mb.val,
                              mb.label, self._mega_field(mb),
                              self._mega_lams())
        self._set_megastep_state(s1, s2)
        return losses

    def _shard_megabatch(self, mb):
        """Mesh placement for one stacked window: per-step batch rows
        sharded over 'dp' (axis 1 — axis 0 is the scan axis), nv
        replicated. The scan body then compiles under GSPMD exactly like
        the K=1 step (same per-step shardings)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..io.sparse import MegaBatch

        def put(a, spec):
            return jax.device_put(jnp.asarray(a),
                                  NamedSharding(self.mesh, spec))
        return MegaBatch(
            put(mb.idx, P(None, "dp", None)),
            None if mb.val is None else put(mb.val, P(None, "dp", None)),
            put(mb.label, P(None, "dp")),
            None if mb.field is None else put(mb.field,
                                              P(None, "dp", None)),
            nv=mb.nv, nv_dev=put(mb.nv, P()), fieldmajor=mb.fieldmajor)

    def _fold_loss(self) -> None:
        self._loss_sum += float(self._loss_pending)
        self._loss_pending = 0.0

    @property
    def cumulative_loss(self) -> float:
        self._fold_loss()
        return self._loss_sum / max(1, self._examples)

    # -- scoring surface (offline predict + online serve share it) ----------
    def _make_margin_fn(self):
        """Raw-score closure over the trainer's CURRENT weights:
        ``fn(padded SparseBatch) -> [B] margins``. Anything expensive to
        derive from training state (the optimizer finalization of the
        linear family) is captured ONCE here, not per batch — the serve
        engine calls this at model-load/swap time and then scores with the
        frozen closure. Trainers without a row-scoring surface (anomaly,
        topic models, ...) leave this unimplemented."""
        raise NotImplementedError(
            f"{type(self).__name__} has no row-scoring surface")

    def make_scorer(self):
        """Output-space scoring closure: ``fn(padded SparseBatch) ->
        np.float32 [B]`` — probabilities for classification trainers
        (sigmoid_np over the margin, exactly what ``predict_proba``
        computes), raw margins for regression. The serve engine's predict
        core; weights are captured at call time, so a hot-reload builds a
        fresh scorer and swaps it atomically with the model."""
        margin = self._make_margin_fn()
        if getattr(self, "classification",
                   getattr(self, "CLASSIFICATION", False)):
            return lambda b: sigmoid_np(
                np.asarray(margin(b), np.float32))
        return lambda b: np.asarray(margin(b), np.float32)

    def _score_dataset(self, ds: SparseDataset,
                       batch_size: Optional[int] = None) -> np.ndarray:
        """Margin-score a whole dataset through the shared shape-bucketed
        batch iterator (io.sparse.score_batches): one compiled kernel per
        (pow2-B, pow2-L) bucket instead of per dataset shape, ragged tails
        padded to their own power-of-two bucket. The decision_function of
        every scoring trainer routes through here."""
        margin = self._make_margin_fn()
        bs = int(batch_size or self.opts.mini_batch)
        out = np.empty(len(ds), np.float32)
        for s, b in score_batches(ds, bs):
            nv = b.n_valid or b.batch_size
            # output path: the per-batch score fetch IS the product
            # graftcheck: disable=GC07
            out[s:s + nv] = np.asarray(margin(b))[:nv]
        return out

    def score_dataset(self, ds: SparseDataset,
                      batch_size: Optional[int] = None) -> np.ndarray:
        """Output-space scores for a whole dataset — the bulk peer of
        :meth:`make_scorer`: probabilities for classification trainers
        (sigmoid over the margin, exactly the ``predict_proba`` space),
        raw margins otherwise. Same shape-bucketed iterator as
        ``_score_dataset``, so the bulk scoring path's jitted kernel
        backend reuses the offline compile buckets."""
        m = self._score_dataset(ds, batch_size)
        if getattr(self, "classification",
                   getattr(self, "CLASSIFICATION", False)):
            return sigmoid_np(m)
        return m

    # -- model emission (the close()-time forward of (feature, weight)) -----
    def model_rows(self) -> Iterator[Tuple[str, float]]:
        w = np.asarray(self._finalized_weights())
        nz = np.nonzero(w)[0]
        for i in nz:
            yield self._names.get(int(i), str(int(i))), float(w[i])

    def model_table(self) -> Dict[str, float]:
        return dict(self.model_rows())

    def _warm_start(self, path: str) -> None:
        """-loadmodel: read a previously saved model table (feature\tweight)."""
        w = np.asarray(self._finalized_weights()).copy()
        seen = set()
        with open(path) as f:
            for line in f:
                feat, _, weight = line.rstrip("\n").partition("\t")
                try:
                    i = int(feat)
                except ValueError:
                    i = mhash(feat, self.dims - 1)
                    self._names.setdefault(i, feat)
                if 0 <= i < len(w):
                    # first touch replaces the warm base; later touches of the
                    # same slot accumulate — feature-hashing collisions share
                    # additively, matching StreamingScorer's loader
                    if i in seen:
                        w[i] += float(weight)
                    else:
                        w[i] = float(weight)
                        seen.add(i)
        self._load_weights(w)

    def save_model(self, path: str) -> None:
        with open(path, "w") as f:
            for feat, weight in self.model_rows():
                f.write(f"{feat}\t{weight:.9g}\n")

    def _load_weights(self, w: np.ndarray) -> None:
        raise NotImplementedError

    # -- sparse weight access (mix delta exchange, O(touched) not O(dims)) ---
    def _weight_table(self):
        """The [dims] device weight array, or None when the trainer's state
        is not a flat table (then sparse access falls back to O(dims))."""
        w = getattr(self, "w", None)
        if w is not None:
            return w
        p = getattr(self, "params", None)
        if isinstance(p, dict) and "w" in p:
            return p["w"]
        return None

    def _store_weight_table(self, t) -> None:
        if getattr(self, "w", None) is not None:
            self.w = t
        else:
            self.params["w"] = t

    def _get_weights_at(self, keys: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        t = self._weight_table()
        if t is None:
            return np.asarray(self._finalized_weights())[keys]
        return np.asarray(t[jnp.asarray(keys)], np.float32)

    def _set_weights_at(self, keys: np.ndarray, vals: np.ndarray) -> None:
        import jax.numpy as jnp
        t = self._weight_table()
        if t is None:
            w = np.array(self._finalized_weights())
            w[keys] = vals
            self._load_weights(w)
            return
        self._store_weight_table(
            t.at[jnp.asarray(keys)].set(jnp.asarray(vals, t.dtype)))

    def _get_covar_at(self, keys: np.ndarray):
        import jax.numpy as jnp
        sig = getattr(self, "sigma", None)
        if sig is None:
            return None
        return np.asarray(sig[jnp.asarray(keys)], np.float32)

    def _set_covar_at(self, keys: np.ndarray, vals: np.ndarray) -> None:
        import jax.numpy as jnp
        sig = getattr(self, "sigma", None)
        if sig is not None:
            self.sigma = sig.at[jnp.asarray(keys)].set(
                jnp.asarray(vals, sig.dtype))

    # -- full-state checkpointing (io.checkpoint bundles, SURVEY.md §6) ------
    def _checkpoint_arrays(self):
        """Pytree of device arrays forming the resumable training state.
        The default covers the standard attribute names; trainers with other
        state override this and `_restore_arrays` as a pair."""
        tree = {}
        for attr in ("w", "sigma", "params", "opt_state", "u", "gg"):
            if getattr(self, attr, None) is not None:
                tree[attr] = getattr(self, attr)
        if not tree:
            raise NotImplementedError(
                f"{type(self).__name__} has no checkpointable arrays")
        return tree

    def _restore_arrays(self, tree) -> None:
        for k, v in tree.items():
            setattr(self, k, v)

    def save_bundle(self, path: str) -> None:
        from ..io.checkpoint import save_bundle
        save_bundle(self, path)

    def load_bundle(self, path: str) -> None:
        from ..io.checkpoint import load_bundle
        load_bundle(self, path)
