"""train_slim — sparse linear item-item recommender (SURVEY.md §3.7 row 7).

Reference: hivemall.recommend.SlimUDTF (v0.5-era): learn W[I, I] (diag 0,
commonly restricted to each item's top-k nearest neighbors) minimizing
  0.5 ||R[:, i] - R_-i W[:, i]||^2 + 0.5 l2 ||W||^2 + l1 ||W||_1
by coordinate descent with soft-thresholding.

TPU shape: the per-coordinate residual updates are sequential by nature, but
all ITEMS are independent — so the rebuild runs CD jointly for every item
column at once: each sweep updates coordinate j of all columns i via one
[U, I] matmul-like residual pass (vmapped soft-threshold), keeping the MXU
busy instead of looping scalar cells.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.options import OptionSpec

__all__ = ["SlimTrainer", "train_slim"]

SLIM_SPEC = (OptionSpec("train_slim")
             .add("l1", type=float, default=0.001, help="L1 strength")
             .add("l2", type=float, default=0.0005, help="L2 strength")
             .add("iters", "iterations", type=int, default=30,
                  help="CD sweeps")
             .add("knn", type=int, default=0,
                  help="restrict W to top-k co-rated neighbors (0 = all)"))


def train_slim(R: np.ndarray, options: str = "") -> np.ndarray:
    """Fit W from a dense user-item matrix R[U, I]; returns W[I, I], diag 0.

    Rating prediction: R_hat = R @ W (column i uses every other item)."""
    ns = SLIM_SPEC.parse(options)
    R = jnp.asarray(R, jnp.float32)
    U, I = R.shape
    l1, l2 = float(ns.l1), float(ns.l2)
    col_sq = (R * R).sum(0)                      # [I] Gram diagonal

    if ns.knn:
        sim = np.asarray(R.T @ R)
        np.fill_diagonal(sim, -np.inf)
        k = min(int(ns.knn), I - 1)
        keep = np.zeros((I, I), np.float32)
        top = np.argpartition(-sim, k - 1, axis=1)[:, :k]
        np.put_along_axis(keep, top, 1.0, axis=1)
        allow = jnp.asarray(keep.T)              # allow[j, i]: j may explain i
    else:
        allow = jnp.ones((I, I), jnp.float32)
    allow = allow * (1.0 - jnp.eye(I))           # never self-explain

    def sweep(W, _):
        def update_coord(j, W):
            # residual excluding j's current contribution, for ALL columns i
            pred = R @ W                          # [U, I]
            rj = R[:, j]                          # [U]
            resid = R - pred + jnp.outer(rj, W[j])
            rho = rj @ resid                      # [I] correlation with resid
            wj = jnp.sign(rho) * jnp.maximum(
                jnp.abs(rho) - l1, 0.0) / (col_sq[j] + l2 + 1e-12)
            wj = wj * allow[j]
            return W.at[j].set(wj)

        W = jax.lax.fori_loop(0, I, update_coord, W)
        return W, None

    W0 = jnp.zeros((I, I), jnp.float32)
    W, _ = jax.lax.scan(sweep, W0, None, length=int(ns.iters))
    return np.asarray(W)


class SlimTrainer:
    """UDTF-style wrapper: process(user, item, rating) rows, close() emits
    (item_j, item_i, w_ji) rows for nonzero coefficients."""

    NAME = "train_slim"

    @classmethod
    def spec(cls) -> OptionSpec:
        return SLIM_SPEC

    def __init__(self, options: str = ""):
        self.options = options
        self._rows = []

    def process(self, user: int, item: int, rating: float) -> None:
        self._rows.append((int(user), int(item), float(rating)))

    def close(self) -> Iterator[Tuple[int, int, float]]:
        if not self._rows:
            return
        users = sorted({r[0] for r in self._rows})
        items = sorted({r[1] for r in self._rows})
        umap = {u: k for k, u in enumerate(users)}
        imap = {i: k for k, i in enumerate(items)}
        R = np.zeros((len(users), len(items)), np.float32)
        for u, i, r in self._rows:
            R[umap[u], imap[i]] = r
        W = train_slim(R, self.options)
        for j in range(len(items)):
            for i in range(len(items)):
                if W[j, i] != 0.0:
                    yield (items[j], items[i], float(W[j, i]))
