"""General linear trainers — train_classifier / train_regressor and the
historical logistic-regression family.

Reference classes (SURVEY.md §3.3, §3.5):
  - hivemall.classifier.GeneralClassifierUDTF  (train_classifier) [B]
  - hivemall.regression.GeneralRegressorUDTF   (train_regressor)  [B]
  - hivemall.regression.LogressUDTF            (logress / train_logregr)
  - hivemall.regression.AdaGradUDTF            (train_adagrad_regr)
  - hivemall.regression.AdaDeltaUDTF           (train_adadelta_regr)

Pluggable loss x optimizer x regularization over a dense hashed weight table;
one jitted step per minibatch (ops.linear). bf16 storage via -halffloat is the
HalfFloat analog (SURVEY.md §3.20).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..io.sparse import SparseBatch, SparseDataset
from ..ops.linear import make_linear_predict, make_linear_step
from ..ops.losses import get_loss
from ..ops.optimizers import make_optimizer_cached
from .base import LearnerBase, sigmoid_np as _sigmoid

__all__ = ["GeneralClassifier", "GeneralRegressor", "LogressTrainer",
           "AdaGradLogisticTrainer", "AdaDeltaLogisticTrainer"]



# config-cached step/optimizer builders (round 4 — see models/fm.py: a
# fresh jitted closure per trainer instance re-traces/compiles for every
# identical config; these are pure functions of the keyed options).
# instrument_factory records every cache MISS (a fresh closure actually
# built) into the obs devprof ledger — docs/OBSERVABILITY.md "Training
# profiling"
from functools import lru_cache as _lru_cache

from ..obs.devprof import instrument_factory as _instrument


@_instrument("linear", "step")
@_lru_cache(maxsize=128)
def _linear_step_cached(loss_name, opt_name, eta_scheme, eta0, total_steps,
                        power_t, reg, lam, l1_ratio):
    return make_linear_step(
        get_loss(loss_name),
        make_optimizer_cached(opt_name, eta_scheme, eta0,
                              total_steps, power_t, reg, lam, l1_ratio))


@_instrument("linear", "predict")
@_lru_cache(maxsize=1)
def _linear_predict_cached():
    return make_linear_predict()

class _LinearLearner(LearnerBase):
    UNIT_VAL_ELISION = True      # ops.linear.make_linear_step takes val=None
    """Shared machinery for dense-table linear trainers."""

    FIXED_LOSS: Optional[str] = None       # set by historical subclasses
    FIXED_OPT: Optional[str] = None
    ZERO_ONE_LABELS = False                # logress-style 0/1 labels

    def _init_state(self) -> None:
        o = self.opts
        self.loss = get_loss(self.FIXED_LOSS or o.loss)
        if self.CLASSIFICATION and not self.loss.for_classification:
            raise ValueError(f"loss {self.loss.name} is regression-only")
        opt_name = str(self.FIXED_OPT or o.opt)
        loss_name = str(self.FIXED_LOSS or o.loss)
        opt_key = (opt_name, str(o.eta), float(o.eta0), o.total_steps,
                   o.power_t, str(o.reg), o["lambda"], o.l1_ratio)
        self.optimizer = make_optimizer_cached(*opt_key)
        dtype = jnp.bfloat16 if o.halffloat else jnp.float32
        self.w = jnp.zeros(self.dims, dtype)
        self.opt_state = self.optimizer.init(self.dims)
        self._step = _linear_step_cached(loss_name, *opt_key)
        self._predict = _linear_predict_cached()

    def _convert_label(self, label: float) -> float:
        if self.ZERO_ONE_LABELS:
            # logress semantics: float target in [0,1]; map to ±1 margin space
            return 1.0 if float(label) > 0.5 else -1.0
        return super()._convert_label(label)

    def _train_batch(self, batch: SparseBatch) -> float:
        self.w, self.opt_state, loss_sum = self._step(
            self.w, self.opt_state, float(self._t),
            batch.idx, batch.val, batch.label, batch.row_mask)
        return loss_sum

    def _finalize_device(self):
        """Optimizer-finalized weights as a DEVICE array — the one
        finalization expression; _finalized_weights and the sharded
        margin fn must never diverge (the online/offline bit-match
        hangs on it)."""
        return self.optimizer.finalize(self.w.astype(jnp.float32),
                                       self.opt_state)

    def _finalized_weights(self) -> np.ndarray:
        return np.asarray(self._finalize_device())

    def _load_weights(self, w: np.ndarray) -> None:
        self.w = jnp.asarray(w, self.w.dtype)

    # -- scoring (the predict-is-a-join path, SURVEY.md §4.2) ---------------
    def _make_margin_fn(self):
        # optimizer finalization (RDA truncation etc.) captured ONCE per
        # scorer — the serve engine swaps scorers per model version, the
        # offline path builds one per decision_function call
        if self.mesh is not None:
            # GSPMD-sharded scorer (serving tables too big for one chip):
            # finalize on device and keep the weight table tp-sharded —
            # np round-tripping here would gather the whole dims-sized
            # table onto one device and un-shard every predict
            import jax
            w = self._finalize_device()
            w = jax.device_put(w, self._state_sharding(w))
        else:
            w = jnp.asarray(self._finalized_weights())
        predict = self._predict
        return lambda b: predict(w, b.idx, b.val)

    def decision_function(self, ds: SparseDataset) -> np.ndarray:
        return self._score_dataset(ds)

    def predict_proba(self, ds: SparseDataset) -> np.ndarray:
        return _sigmoid(self.decision_function(ds))

    def serving_tables(self):
        """Arena extraction (io.weight_arena): the ONE finalized f32
        inference table — optimizer finalization (RDA truncation etc.)
        baked in, exactly what _make_margin_fn captures."""
        meta = {"family": "linear", "w0": 0.0,
                "classification": bool(self.CLASSIFICATION)}
        return meta, {"w": np.asarray(self._finalized_weights(),
                                      np.float32)}


class GeneralClassifier(_LinearLearner):
    """SQL: train_classifier — reference hivemall.classifier.GeneralClassifierUDTF."""
    NAME = "train_classifier"
    CLASSIFICATION = True
    DEFAULT_LOSS = "hingeloss"


class GeneralRegressor(_LinearLearner):
    """SQL: train_regressor — reference hivemall.regression.GeneralRegressorUDTF."""
    NAME = "train_regressor"
    CLASSIFICATION = False
    DEFAULT_LOSS = "squaredloss"


class LogressTrainer(_LinearLearner):
    """SQL: logress / train_logregr — reference hivemall.regression.LogressUDTF.
    Logistic regression by SGD, the historically canonical Hivemall example."""
    NAME = "train_logregr"
    CLASSIFICATION = True
    DEFAULT_LOSS = "logloss"
    FIXED_LOSS = "logloss"
    FIXED_OPT = "sgd"
    ZERO_ONE_LABELS = True

    @classmethod
    def spec(cls):
        s = super().spec()
        for o in s.options:        # logress default regularization is none
            if o.name == "reg":
                o.default = "no"
        return s


class AdaGradLogisticTrainer(LogressTrainer):
    """SQL: train_adagrad_regr — reference hivemall.regression.AdaGradUDTF."""
    NAME = "train_adagrad_regr"
    FIXED_OPT = "adagrad"


class AdaDeltaLogisticTrainer(LogressTrainer):
    """SQL: train_adadelta_regr — reference hivemall.regression.AdaDeltaUDTF."""
    NAME = "train_adadelta_regr"
    FIXED_OPT = "adadelta"
