"""Online linear classifiers — Perceptron, Passive-Aggressive, and the
covariance family (CW / AROW / SCW), plus AdaGrad-RDA and kernelized PA.

Reference (SURVEY.md §3.3): hivemall.classifier.{PerceptronUDTF,
PassiveAggressiveUDTF (+PA1/PA2), ConfidenceWeightedUDTF,
AROWClassifierUDTF (+arowh), SoftConfideceWeightedUDTF (SCW1/SCW2 — upstream
class name carries that historical spelling), AdaGradRDAUDTF,
KernelExpansionPassiveAggressiveUDTF}.

Batching semantics (SURVEY.md §8 "hard parts"): these algorithms are
per-row sequential in the reference. Here each minibatch computes every row's
closed-form step size against the BATCH-START weights and aggregates the
deltas by scatter-add — with ``-mini_batch 1`` this is exactly the reference's
sequential update (the unit tests pin that equivalence against numpy
oracles); larger batches trade per-row adaptivity for TPU throughput, the
documented delta. Measured guidance (tests/test_covariance_batching.py, a9a
fragment, 1 epoch AUC): ``-mini_batch 16`` matches the sequential oracle
within 0.002; 64 loses 0.03-0.27 AUC in one epoch but recovers with ~4
epochs; 256 is not recommended (CW can diverge). Use 1 for exactness,
16 for throughput at parity, 64 only with extra -iters. Covariance
trainers keep a diagonal sigma table (the WeightValueWithCovar analog) and
emit (feature, weight, covar) rows so argmin-KLD mixing/merging stays
available.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.sparse import SparseBatch, SparseDataset
from ..utils.options import OptionSpec
from .base import LearnerBase, learner_option_spec
from .linear import _sigmoid

__all__ = ["PerceptronTrainer", "PassiveAggressiveTrainer", "PA1Trainer",
           "PA2Trainer", "ConfidenceWeightedTrainer", "AROWTrainer",
           "AROWhTrainer", "SCW1Trainer", "SCW2Trainer", "AdaGradRDATrainer",
           "KernelizedPATrainer", "PARegressionTrainer", "PA1aRegressionTrainer",
           "PA2RegressionTrainer", "PA2aRegressionTrainer",
           "AROWRegressionTrainer", "AROWeRegressionTrainer",
           "AROWe2RegressionTrainer"]


def _online_spec(name: str) -> OptionSpec:
    s = OptionSpec(name)
    s.add("c", "aggressiveness", type=float, default=1.0,
          help="aggressiveness parameter C (PA1/PA2/SCW)")
    s.add("phi", "confidence", type=float, default=1.0,
          help="confidence parameter phi = Phi^-1(eta) (CW/SCW)")
    s.add("eta", "hyper_eta", type=float, default=0.85,
          help="CW confidence level eta in (0.5, 1]; phi derived when set")
    s.add("r", "regularization", type=float, default=0.1,
          help="AROW regularization r")
    s.add("epsilon", type=float, default=0.1,
          help="epsilon-insensitive band (regression variants)")
    s.add("dims", "feature_dimensions", type=int, default=1 << 24,
          help="model table size")
    s.add("mini_batch", "mini_batch_size", type=int, default=1,
          help="rows per step (1 = exact reference semantics)")
    s.add("batch_mode", default="aggregate",
          help="how a >1-row minibatch updates the model: aggregate "
               "(one closed-form step over the batch — fast, documented "
               "semantic delta) | sequential (lax.scan row-by-row inside "
               "ONE device dispatch — bit-equivalent to -mini_batch 1 "
               "reference semantics at minibatch dispatch rate)")
    s.add("iters", "iterations", type=int, default=1, help="epochs")
    s.flag("int_feature", help="features are integer indices")
    s.add("mix", default=None, help="mix cohort spec")
    s.add("mix_threshold", type=int, default=16)
    s.add("mix_session", default=None)
    from .base import add_mix_reliability_options
    add_mix_reliability_options(s)
    s.add("loadmodel", default=None)
    s.flag("dense", "densemodel", help="compat flag (always dense table)")
    s.flag("halffloat", help="bf16 weights")
    s.flag("disable_halffloat", help="compat flag")
    s.add("loss", default=None, help="compat (loss fixed per algorithm)")
    s.add("opt", default=None, help="compat (update rule fixed)")
    s.add("reg", default=None, help="compat")
    s.add("lambda", type=float, default=1e-6, help="RDA l1 (AdaGrad-RDA)")
    s.add("eta0", type=float, default=0.1, help="eta0 (AdaGrad-RDA)")
    s.add("total_steps", type=int, default=10_000)
    s.add("power_t", type=float, default=0.1)
    s.add("l1_ratio", type=float, default=0.5)
    s.flag("cv")
    return s


class _OnlineBase(LearnerBase):
    """Shared scaffolding: dense w (+ optional sigma) tables and a jitted
    closed-form aggregated step built by `_rates`."""

    HAS_COVAR = False
    CLASSIFICATION = True

    @classmethod
    def spec(cls) -> OptionSpec:
        return _online_spec(cls.NAME)

    def _init_state(self) -> None:
        dtype = jnp.bfloat16 if self.opts.halffloat else jnp.float32
        self.w = jnp.zeros(self.dims, dtype)
        self.sigma = jnp.ones(self.dims, jnp.float32) if self.HAS_COVAR \
            else None
        mode = str(getattr(self.opts, "batch_mode", "aggregate"))
        if mode not in ("aggregate", "sequential"):
            raise ValueError(f"-batch_mode must be aggregate|sequential, "
                             f"got {mode!r}")
        self._step = self._shared_step(
            mode, self._make_step_sequential if mode == "sequential"
            else self._make_step)

    # subclass: (margin_y, v, xx, y, params) -> (alpha_like, beta_like)
    #   margin_y = y * (w.x); v = sigma-weighted or plain ||x||^2
    def _rates(self):
        raise NotImplementedError

    def _make_step(self):
        rates = self._rates()
        has_covar = self.HAS_COVAR

        @jax.jit
        def step(w, sigma, idx, val, label, row_mask):
            wf = w.astype(jnp.float32)
            wg = wf[idx]
            m = (wg * val).sum(-1) * label                   # y * margin
            if has_covar:
                sg = sigma[idx]
                v = (sg * val * val).sum(-1)
            else:
                sg = jnp.ones_like(val)
                v = (val * val).sum(-1)
            alpha, beta = rates(m, v)
            alpha = alpha * row_mask
            beta = beta * row_mask
            dw = jnp.zeros_like(wf).at[idx.ravel()].add(
                ((alpha * label)[:, None] * sg * val).ravel())
            w2 = (wf + dw).astype(w.dtype)
            if has_covar:
                ds = jnp.zeros_like(sigma).at[idx.ravel()].add(
                    (beta[:, None] * (sg * val) ** 2).ravel())
                sigma2 = jnp.maximum(sigma - ds, 1e-8)
            else:
                sigma2 = sigma
            # cumulative hinge-ish loss for -cv reporting
            loss_sum = (jnp.maximum(0.0, 1.0 - m) * row_mask).sum()
            return w2, sigma2, loss_sum

        return step

    def _make_step_sequential(self):
        """Reference-exact row-by-row updates at minibatch dispatch rate.

        Round-2 shape (a lax.scan carrying the full [dims] tables through
        every row) measured ~1.8k rows/s: each scan iteration moved
        whole-table state. Round 3 processes SLABS of G=128 rows: gather
        the slab's touched entries once, run the exact per-row loop on the
        small [G, L] in-register slab — cross-row feature sharing inside
        the slab is propagated through an idx-match mask, so every row
        sees exactly the f32 values true row-by-row dispatch would — and
        scatter the final values back once per slab. Bit-equivalent to
        -mini_batch 1 for rows with distinct features (the covariance
        batching tests pin it); a feature repeated WITHIN one row keeps
        add-semantics for w (same as the reference's accumulating update)
        and delta-semantics for sigma. This is the SURVEY §8
        'online-learner semantics under batching' hard part solved
        exactly rather than approximated."""
        rates = self._rates()
        has_covar = self.HAS_COVAR
        G = 128

        @jax.jit
        def step(w, sigma, idx, val, label, row_mask):
            B, L = idx.shape
            pad = (-B) % G
            if pad:
                idx = jnp.pad(idx, ((0, pad), (0, 0)))
                val = jnp.pad(val, ((0, pad), (0, 0)))
                label = jnp.pad(label, (0, pad))
                row_mask = jnp.pad(row_mask, (0, pad))
            nS = (B + pad) // G
            wf = w.astype(jnp.float32)
            sig0 = sigma if has_covar else jnp.zeros((1,), jnp.float32)

            def slab(carry, rows):
                cw, cs = carry
                sidx, sval, sy, smsk = rows
                Ws = cw[sidx]                               # [G, L]
                Ss = cs[sidx] if has_covar else jnp.ones_like(sval)

                def row_body(j, st):
                    Ws, Ss, acc = st
                    rv, y, msk = sval[j], sy[j], smsk[j]
                    wg, sg = Ws[j], Ss[j]
                    m = (wg * rv).sum() * y
                    v = ((sg * rv * rv).sum() if has_covar
                         else (rv * rv).sum())
                    alpha, beta = rates(m, v)
                    alpha = alpha * msk
                    beta = beta * msk
                    dw = alpha * y * sg * rv                # [L]
                    match = sidx[:, :, None] == sidx[j][None, None, :]
                    Ws = Ws + jnp.where(match, dw[None, None, :],
                                        0.0).sum(-1)
                    if has_covar:
                        new_s = jnp.maximum(sg - beta * (sg * rv) ** 2,
                                            1e-8)
                        dsg = jnp.where(msk > 0, new_s - sg, 0.0)
                        Ss = Ss + jnp.where(match, dsg[None, None, :],
                                            0.0).sum(-1)
                    return Ws, Ss, acc + jnp.maximum(0.0, 1.0 - m) * msk

                Ws, Ss, acc = jax.lax.fori_loop(
                    0, G, row_body, (Ws, Ss, jnp.float32(0.0)))
                # every slab entry of a shared feature tracked the same
                # value, so duplicate-index .set is well-defined
                cw = cw.at[sidx].set(Ws)
                if has_covar:
                    cs = cs.at[sidx].set(Ss)
                return (cw, cs), acc

            (wf, sig), losses = jax.lax.scan(
                slab, (wf, sig0),
                (idx.reshape(nS, G, L), val.reshape(nS, G, L),
                 label.reshape(nS, G), row_mask.reshape(nS, G)))
            return (wf.astype(w.dtype),
                    sig if has_covar else sigma, losses.sum())

        return step

    def _train_batch(self, batch: SparseBatch) -> float:
        self.w, self.sigma, loss = self._step(
            self.w, self.sigma, batch.idx, batch.val, batch.label,
            batch.row_mask)
        return loss

    def _finalized_weights(self) -> np.ndarray:
        return np.asarray(self.w.astype(jnp.float32))

    def _load_weights(self, w: np.ndarray) -> None:
        self.w = jnp.asarray(w, self.w.dtype)

    def covar_table(self) -> Optional[np.ndarray]:
        return None if self.sigma is None else np.asarray(self.sigma)

    def model_rows(self):
        w = self._finalized_weights()
        nz = np.nonzero(w)[0]
        if self.sigma is None:
            for i in nz:
                yield self._names.get(int(i), str(int(i))), float(w[i])
        else:
            sig = np.asarray(self.sigma)
            for i in nz:
                yield (self._names.get(int(i), str(int(i))), float(w[i]),
                       float(sig[i]))

    def _make_margin_fn(self):
        from .linear import _linear_predict_cached
        w = jnp.asarray(self._finalized_weights())
        predict = _linear_predict_cached()   # shared jitted gather+sum
        return lambda b: predict(w, b.idx, b.val)

    def decision_function(self, ds: SparseDataset) -> np.ndarray:
        return self._score_dataset(ds, max(int(self.opts.mini_batch), 256))

    def predict_proba(self, ds: SparseDataset) -> np.ndarray:
        return _sigmoid(self.decision_function(ds))


class PerceptronTrainer(_OnlineBase):
    """SQL: train_perceptron — mistake-driven, unit step."""
    NAME = "train_perceptron"

    def _rates(self):
        def rates(m, v):
            return (m <= 0).astype(jnp.float32), jnp.zeros_like(m)
        return rates


class PassiveAggressiveTrainer(_OnlineBase):
    """SQL: train_pa — tau = hinge/||x||^2 (Crammer et al. PA-0)."""
    NAME = "train_pa"

    def _tau_factory(self):
        # returns a closure over SCALARS only — capturing a bound method
        # here pinned the first trainer instance (and its dims-sized
        # tables) inside the global step cache forever
        return lambda loss, xx: loss / jnp.maximum(xx, 1e-12)

    def _rates(self):
        tau_fn = self._tau_factory()

        def rates(m, v):
            loss = jnp.maximum(0.0, 1.0 - m)
            return jnp.where(loss > 0, tau_fn(loss, v), 0.0), \
                jnp.zeros_like(m)
        return rates


class PA1Trainer(PassiveAggressiveTrainer):
    """SQL: train_pa1 — tau capped at C."""
    NAME = "train_pa1"

    def _tau_factory(self):
        c = float(self.opts.c)
        return lambda loss, xx: jnp.minimum(
            c, loss / jnp.maximum(xx, 1e-12))


class PA2Trainer(PassiveAggressiveTrainer):
    """SQL: train_pa2 — tau = loss / (||x||^2 + 1/(2C))."""
    NAME = "train_pa2"

    def _tau_factory(self):
        c = float(self.opts.c)
        return lambda loss, xx: loss / (xx + 1.0 / (2.0 * c))


def _phi_of(opts) -> float:
    """phi = Phi^-1(eta) when -eta given, else the explicit -phi."""
    eta = float(opts.eta)
    if eta and eta != 0.85:
        # inverse normal CDF via erfinv
        return float(math.sqrt(2.0) * _erfinv(2.0 * eta - 1.0))
    return float(opts.phi)


def _erfinv(x: float) -> float:
    # Winitzki's approximation — adequate for confidence params
    a = 0.147
    ln1mx2 = math.log(max(1e-12, 1.0 - x * x))
    t1 = 2.0 / (math.pi * a) + ln1mx2 / 2.0
    return math.copysign(math.sqrt(math.sqrt(t1 * t1 - ln1mx2 / a) - t1), x)


def _cw_beta(alpha, v, phi):
    u = 0.25 * (-alpha * v * phi
                + jnp.sqrt(alpha ** 2 * v ** 2 * phi ** 2 + 4.0 * v)) ** 2
    return alpha * phi / (jnp.sqrt(u) + v * alpha * phi + 1e-12)


class ConfidenceWeightedTrainer(_OnlineBase):
    """SQL: train_cw — Dredze/Crammer confidence-weighted (diagonal)."""
    NAME = "train_cw"
    HAS_COVAR = True

    def _rates(self):
        phi = _phi_of(self.opts)
        zeta = 1.0 + phi * phi
        psi = 1.0 + phi * phi / 2.0

        def rates(m, v):
            alpha = jnp.maximum(0.0, (-m * psi + jnp.sqrt(
                m * m * phi ** 4 / 4.0 + v * phi * phi * zeta))
                / jnp.maximum(v * zeta, 1e-12))
            return alpha, _cw_beta(alpha, v, phi)
        return rates


class AROWTrainer(_OnlineBase):
    """SQL: train_arow — adaptive regularization of weight vectors."""
    NAME = "train_arow"
    HAS_COVAR = True

    def _rates(self):
        r = float(self.opts.r)

        def rates(m, v):
            beta = 1.0 / (v + r)
            alpha = jnp.maximum(0.0, 1.0 - m) * beta
            update = (m < 1.0).astype(jnp.float32)
            return alpha * update, beta * update
        return rates


class AROWhTrainer(AROWTrainer):
    """SQL: train_arowh — AROW with hinge threshold (same closed form;
    the reference variant differs only in its loss bookkeeping)."""
    NAME = "train_arowh"


class SCW1Trainer(_OnlineBase):
    """SQL: train_scw — soft confidence-weighted I (Wang et al. 2012)."""
    NAME = "train_scw"
    HAS_COVAR = True

    def _rates(self):
        phi = _phi_of(self.opts)
        zeta = 1.0 + phi * phi
        psi = 1.0 + phi * phi / 2.0
        C = float(self.opts.c)

        def rates(m, v):
            alpha = jnp.maximum(0.0, (-m * psi + jnp.sqrt(
                m * m * phi ** 4 / 4.0 + v * phi * phi * zeta))
                / jnp.maximum(v * zeta, 1e-12))
            alpha = jnp.minimum(alpha, C)
            return alpha, _cw_beta(alpha, v, phi)
        return rates


class SCW2Trainer(_OnlineBase):
    """SQL: train_scw2 — soft confidence-weighted II."""
    NAME = "train_scw2"
    HAS_COVAR = True

    def _rates(self):
        phi = _phi_of(self.opts)
        C = float(self.opts.c)

        def rates(m, v):
            n = v + 1.0 / (2.0 * C)
            gamma = phi * jnp.sqrt(
                phi * phi * m * m * v * v + 4.0 * n * v * (n + v * phi * phi))
            alpha = jnp.maximum(0.0, (-(2.0 * m * n + phi * phi * m * v)
                                      + gamma)
                                / (2.0 * (n * n + n * v * phi * phi) + 1e-12))
            return alpha, _cw_beta(alpha, v, phi)
        return rates


class AdaGradRDATrainer(_OnlineBase):
    """SQL: train_adagrad_rda — AdaGrad + L1 regularized dual averaging
    (reference AdaGradRDAUDTF: hinge loss)."""
    NAME = "train_adagrad_rda"

    def _init_state(self) -> None:
        self.w = jnp.zeros(self.dims, jnp.float32)
        self.sigma = None
        self.u = jnp.zeros(self.dims, jnp.float32)
        self.gg = jnp.zeros(self.dims, jnp.float32)
        self._step = self._shared_step("rda", self._make_rda_step)

    def _make_rda_step(self):
        lam = float(self.opts["lambda"])
        eta0 = float(self.opts.eta0)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(w, u, gg, t, idx, val, label, row_mask):
            m = (w[idx] * val).sum(-1) * label
            active = ((m < 1.0).astype(jnp.float32)) * row_mask
            g = jnp.zeros_like(w).at[idx.ravel()].add(
                ((-label * active)[:, None] * val).ravel())
            u2 = u + g
            gg2 = gg + g * g
            tt = t + 1.0
            thresh = jnp.maximum(0.0, jnp.abs(u2) / tt - lam)
            w2 = -jnp.sign(u2) * eta0 * tt * thresh / (jnp.sqrt(gg2) + 1e-6)
            loss = (jnp.maximum(0.0, 1.0 - m) * row_mask).sum()
            return w2, u2, gg2, loss

        return step

    def _train_batch(self, batch: SparseBatch) -> float:
        self.w, self.u, self.gg, loss = self._step(
            self.w, self.u, self.gg, float(self._t), batch.idx, batch.val,
            batch.label, batch.row_mask)
        return loss


class KernelizedPATrainer(PA1Trainer):
    """SQL: train_kpa — polynomial-kernel PA via explicit degree-2 expansion
    (reference KernelExpansionPassiveAggressiveUDTF expands
    (1 + x.z)^2 into bias + linear + pairwise-cross feature space)."""
    NAME = "train_kpa"

    def _parse_row(self, features):
        idx, val = super()._parse_row(features)
        from ..utils.hashing import mhash
        n = len(idx)
        ei: list = list(idx)
        ev: list = list(val)
        for a in range(n):
            for b in range(a, n):
                key = (f"{min(idx[a], idx[b])}^{max(idx[a], idx[b])}"
                       .encode())
                h = mhash(key, self.dims - 1)
                ei.append(h)
                ev.append(float(val[a]) * float(val[b]))
        return np.asarray(ei, np.int32), np.asarray(ev, np.float32)


# --- regression variants (SURVEY.md §3.5 rows 4-5) -------------------------

class _PARegressionBase(_OnlineBase):
    """Epsilon-insensitive PA regression: rows (features, float target)."""
    CLASSIFICATION = False
    CAP_C = False       # PA1-style cap
    SQUARED = False     # PA2-style denominator

    def _make_step(self):
        eps = float(self.opts.epsilon)
        C = float(self.opts.c)
        cap = self.CAP_C
        sq = self.SQUARED

        @jax.jit
        def step(w, sigma, idx, val, label, row_mask):
            wf = w.astype(jnp.float32)
            pred = (wf[idx] * val).sum(-1)
            err = label - pred
            loss = jnp.maximum(0.0, jnp.abs(err) - eps)
            xx = (val * val).sum(-1)
            if sq:
                tau = loss / (xx + 1.0 / (2.0 * C))
            else:
                tau = loss / jnp.maximum(xx, 1e-12)
                if cap:
                    tau = jnp.minimum(tau, C)
            tau = tau * jnp.sign(err) * row_mask
            dw = jnp.zeros_like(wf).at[idx.ravel()].add(
                (tau[:, None] * val).ravel())
            return (wf + dw).astype(w.dtype), sigma, (loss * row_mask).sum()

        return step


class PARegressionTrainer(_PARegressionBase):
    """SQL: train_pa1_regr — reference PassiveAggressiveRegressionUDTF."""
    NAME = "train_pa1_regr"
    CAP_C = True


class PA1aRegressionTrainer(_PARegressionBase):
    """SQL: train_pa1a_regr — uncapped variant."""
    NAME = "train_pa1a_regr"


class PA2RegressionTrainer(_PARegressionBase):
    """SQL: train_pa2_regr."""
    NAME = "train_pa2_regr"
    SQUARED = True


class PA2aRegressionTrainer(_PARegressionBase):
    """SQL: train_pa2a_regr."""
    NAME = "train_pa2a_regr"
    SQUARED = True


class _AROWRegressionBase(_OnlineBase):
    """AROW regression with epsilon-insensitive loss and diagonal covar."""
    CLASSIFICATION = False
    HAS_COVAR = True

    def _make_step(self):
        eps = float(self.opts.epsilon)
        r = float(self.opts.r)

        @jax.jit
        def step(w, sigma, idx, val, label, row_mask):
            wf = w.astype(jnp.float32)
            sg = sigma[idx]
            pred = (wf[idx] * val).sum(-1)
            err = label - pred
            loss = jnp.maximum(0.0, jnp.abs(err) - eps)
            v = (sg * val * val).sum(-1)
            beta = 1.0 / (v + r)
            alpha = loss * beta * jnp.sign(err)
            active = (loss > 0).astype(jnp.float32) * row_mask
            dw = jnp.zeros_like(wf).at[idx.ravel()].add(
                ((alpha * active)[:, None] * sg * val).ravel())
            ds = jnp.zeros_like(sigma).at[idx.ravel()].add(
                ((beta * active)[:, None] * (sg * val) ** 2).ravel())
            return ((wf + dw).astype(w.dtype),
                    jnp.maximum(sigma - ds, 1e-8),
                    (loss * row_mask).sum())

        return step


class AROWRegressionTrainer(_AROWRegressionBase):
    """SQL: train_arow_regr — reference AROWRegressionUDTF."""
    NAME = "train_arow_regr"


class AROWeRegressionTrainer(_AROWRegressionBase):
    """SQL: train_arowe_regr — epsilon variant (same closed form, eps set
    by -epsilon)."""
    NAME = "train_arowe_regr"


class AROWe2RegressionTrainer(_AROWRegressionBase):
    """SQL: train_arowe2_regr — squared-step variant; beta uses v + 1/(2C)."""
    NAME = "train_arowe2_regr"

    def _make_step(self):
        eps = float(self.opts.epsilon)
        C = float(self.opts.c)

        @jax.jit
        def step(w, sigma, idx, val, label, row_mask):
            wf = w.astype(jnp.float32)
            sg = sigma[idx]
            pred = (wf[idx] * val).sum(-1)
            err = label - pred
            loss = jnp.maximum(0.0, jnp.abs(err) - eps)
            v = (sg * val * val).sum(-1)
            beta = 1.0 / (v + 1.0 / (2.0 * C))
            alpha = loss * beta * jnp.sign(err)
            active = (loss > 0).astype(jnp.float32) * row_mask
            dw = jnp.zeros_like(wf).at[idx.ravel()].add(
                ((alpha * active)[:, None] * sg * val).ravel())
            ds = jnp.zeros_like(sigma).at[idx.ravel()].add(
                ((beta * active)[:, None] * (sg * val) ** 2).ravel())
            return ((wf + dw).astype(w.dtype),
                    jnp.maximum(sigma - ds, 1e-8),
                    (loss * row_mask).sum())

        return step
