"""Topic models — train_lda / train_plsa (SURVEY.md §3.10).

Reference: hivemall.topicmodel.{LDAUDTF,OnlineLDAModel,LDAPredictUDAF,
PLSAUDTF,IncrementalPLSAModel,PLSAPredictUDAF}: online variational-Bayes LDA
(Hoffman et al.) and incremental pLSA, minibatched inside the UDTF with decay
rho_t = (tau0 + t)^-kappa.

TPU shape: a minibatch of docs becomes padded (word-id, count) arrays; the
per-doc E-step (gamma/phi fixed-point) runs as a lax.fori_loop vectorized
over the batch; the M-step is one dense update of lambda [K, V]. Vocabulary
is hashed into [0, V) like the linear models' feature space.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.sparse import pow2_len
from ..obs.devprof import instrument_factory as _instrument
from ..utils.hashing import mhash, mhash_batch
from ..utils.options import OptionSpec

__all__ = ["LDATrainer", "PLSATrainer", "lda_predict", "plsa_predict"]


def _digamma(x):
    return jax.scipy.special.digamma(x)


class LDATrainer:
    """SQL: train_lda(words[, options]) — online VB LDA."""

    NAME = "train_lda"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = OptionSpec(cls.NAME)
        s.add("topics", "k", type=int, default=10, help="number of topics")
        s.add("alpha", type=float, default=1 / 2.0, help="doc-topic prior "
              "(reference default alpha = 1/topics at init; set explicitly)")
        s.add("eta", type=float, default=1 / 20.0, help="topic-word prior")
        s.add("tau0", type=float, default=64.0, help="decay offset")
        s.add("kappa", type=float, default=0.7, help="decay exponent")
        s.add("iter", "inner_iters", type=int, default=32,
              help="E-step fixed-point iterations")
        s.add("delta", type=float, default=1e-3,
              help="accepted for reference compat (convergence tol)")
        s.add("vocab", "vocab_size", type=int, default=1 << 16,
              help="hashed vocabulary size")
        s.add("mini_batch", type=int, default=128, help="docs per step")
        s.add("max_doc_len", type=int, default=256,
              help="distinct words kept per doc")
        s.add("seed", type=int, default=131, help="init seed")
        s.add("total_docs", type=int, default=1 << 20,
              help="corpus-size estimate D for the M-step scale")
        return s

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        o = self.opts
        self.K = int(o.topics)
        self.V = int(o.vocab)
        key = jax.random.PRNGKey(int(o.seed))
        # lambda init ~ Gamma(100, 1/100) as in Hoffman's onlineldavb
        self.lam = jax.random.gamma(key, 100.0, (self.K, self.V)) / 100.0
        self._t = 0
        self._buf: List[Tuple[np.ndarray, np.ndarray]] = []
        self._vocab_names: Dict[int, str] = {}
        self._step = self._make_step()

    def _word_ids(self, words: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        counts: Dict[int, float] = {}
        for w in words:
            if w in (None, ""):
                continue
            name, sep, v = str(w).rpartition(":")
            if sep and _floatable(v):
                c = float(v)
            else:
                name, c = str(w), 1.0
            i = mhash(name, self.V) - 1
            self._vocab_names.setdefault(i, name)
            counts[i] = counts.get(i, 0.0) + c
        ids = np.fromiter(counts.keys(), np.int32, len(counts))
        cts = np.fromiter(counts.values(), np.float32, len(counts))
        m = int(self.opts.max_doc_len)
        return ids[:m], cts[:m]

    # -- full-state checkpointing (io.checkpoint bundles, SURVEY.md §6) ------
    def _checkpoint_arrays(self):
        return {"lam": self.lam}

    def _restore_arrays(self, tree) -> None:
        self.lam = tree["lam"]

    def _checkpoint_scalars(self):
        return {"vocab_names": {str(k): v
                                for k, v in self._vocab_names.items()}}

    def _restore_scalars(self, scalars) -> None:
        self._vocab_names.update(
            {int(k): v for k, v in scalars.get("vocab_names", {}).items()})

    def save_bundle(self, path: str) -> None:
        from ..io.checkpoint import save_bundle
        self._flush()
        save_bundle(self, path)

    def load_bundle(self, path: str) -> None:
        from ..io.checkpoint import load_bundle
        load_bundle(self, path)

    def _make_step(self):
        o = self.opts
        # module-level cache: a fresh jitted closure per trainer instance
        # re-COMPILES for identical configs (measured: 1.5 s of the 2.3 s
        # LDA bench was XLA compile of the second instance's step)
        return _lda_step_cached(self.K, self.V, float(o.alpha),
                                float(o.eta), int(o.iter),
                                float(o.total_docs), float(o.tau0),
                                float(o.kappa))

    # -- lifecycle -----------------------------------------------------------
    def process(self, words: Sequence[str]) -> None:
        ids, cts = self._word_ids(words)
        if len(ids) == 0:
            return
        self._buf.append((ids, cts))
        if len(self._buf) >= int(self.opts.mini_batch):
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        docs = self._buf
        self._buf = []
        B = int(self.opts.mini_batch)
        Lp = pow2_len(max(len(d[0]) for d in docs))
        ids = np.zeros((B, Lp), np.int32)
        cts = np.zeros((B, Lp), np.float32)
        mask = np.zeros((B, Lp), np.float32)
        for b, (i, c) in enumerate(docs):
            ids[b, :len(i)] = i
            cts[b, :len(c)] = c
            mask[b, :len(i)] = 1.0
        self.lam, self._last_gamma = self._step(self.lam, float(self._t),
                                                ids, cts, mask)
        self._t += 1

    def close(self, top_n: int = 0) -> Iterator[Tuple[int, str, float]]:
        """Emit (topic, word, p(word|topic)) rows for seen words."""
        self._flush()
        lam = np.asarray(self.lam)
        probs = lam / lam.sum(1, keepdims=True)
        seen = sorted(self._vocab_names)
        for k in range(self.K):
            order = sorted(seen, key=lambda i: -probs[k, i])
            if top_n:
                order = order[:top_n]
            for i in order:
                yield (k, self._vocab_names[i], float(probs[k, i]))

    def _word_ids_flat(self, docs: Sequence[Sequence[str]]
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Vectorized batch form of _word_ids: ALL docs' tokens hash in one
        mhash_batch call (the C++ murmur path that runs LIBSVM ingest at
        700k rows/s) and per-doc aggregation is one sort + reduceat —
        round 4 profiled the per-doc Python tokenize/hash loop at
        ~70 us/doc, leaving the TPU idle (LDA was host-bound at 13.5k
        docs/s). Returns (unique ids, summed counts, doc_starts); within
        each doc the uniques come in FIRST-OCCURRENCE order, so
        max_doc_len truncation keeps the same words _word_ids' insertion-
        ordered dict keeps (the E-step itself is order-invariant)."""
        # token interning: hashing / ":count" parsing / vocab-name upkeep
        # run once per UNIQUE token — corpora repeat tokens heavily, and
        # mhash_batch's per-string packing measured ~1 us/token while a
        # dict intern runs the whole stream at ~0.3 us/token
        intern: Dict[str, int] = {}
        get = intern.setdefault
        lens = []
        tok: List[int] = []
        for d in docs:
            n0 = len(tok)
            tok.extend(get(str(w), len(intern))
                       for w in d if w not in (None, ""))
            lens.append(len(tok) - n0)
        if not tok:
            return (np.zeros(0, np.int32), np.zeros(0, np.float32),
                    np.zeros(len(docs) + 1, np.int64))
        uniq = list(intern)
        u_cts = np.ones(len(uniq), np.float32)
        names = uniq
        if any(":" in u for u in uniq):
            names = list(uniq)
            for i, u in enumerate(uniq):       # rare "word:count" tokens
                if ":" in u:
                    name, _, v = u.rpartition(":")
                    if _floatable(v):
                        names[i] = name
                        u_cts[i] = float(v)
        u_ids = (mhash_batch(names, self.V) - 1).astype(np.int64)
        for i, nm in zip(u_ids, names):        # one dict op per unique
            self._vocab_names.setdefault(int(i), nm)
        tok_a = np.asarray(tok, np.int64)
        ids = u_ids[tok_a]
        cts = u_cts[tok_a]
        # per-(doc, id) count aggregation: sort + segment reduceat
        doc_idx = np.repeat(np.arange(len(docs), dtype=np.int64),
                            np.asarray(lens, np.int64))
        key = doc_idx * self.V + ids
        order = np.argsort(key, kind="stable")
        sk, sc = key[order], cts[order]
        starts = np.flatnonzero(np.concatenate(
            [[True], sk[1:] != sk[:-1]]))
        sums = np.add.reduceat(sc, starts).astype(np.float32)
        uk = sk[starts]
        u_doc = uk // self.V
        u_ids = (uk % self.V).astype(np.int32)
        # re-order each doc's uniques by FIRST OCCURRENCE (stable sort =>
        # positions within a group ascend, so order[starts] is the
        # group's first original position) — max_doc_len truncation then
        # drops the same late-appearing words the streaming dict drops,
        # not an arbitrary hash-ordered subset
        first_pos = order[starts]
        ord2 = np.lexsort((first_pos, u_doc))
        u_ids, sums, u_doc = u_ids[ord2], sums[ord2], u_doc[ord2]
        doc_starts = np.searchsorted(u_doc, np.arange(len(docs) + 1))
        return u_ids, sums, doc_starts

    def fit(self, docs: Sequence[Sequence[str]]) -> "LDATrainer":
        """Batch fit: vectorized tokenize/hash/aggregate + vectorized
        batch padding — no per-doc Python on the hot path (the round-4
        ingest loop left the chip idle at 13.5k docs/s)."""
        B = int(self.opts.mini_batch)
        chunk = max(B * 8, 2048)       # bound the flat token buffer
        for s in range(0, len(docs), chunk):
            sub = docs[s:s + chunk]
            # host-side tokenize/hash over Python token lists — the
            # np.asarray inside builds HOST arrays, no device sync
            # graftcheck: disable=GC07
            uids, sums, doc_starts = self._word_ids_flat(sub)
            rl = np.minimum(np.diff(doc_starts),
                            int(self.opts.max_doc_len)).astype(np.int64)
            keep = np.flatnonzero(rl > 0)     # empty docs never dispatch
            rl_k = rl[keep]
            for b0 in range(0, len(keep), B):
                sel = keep[b0:b0 + B]
                rls = rl_k[b0:b0 + B]
                n = len(sel)
                Lp = pow2_len(int(rls.max()))
                ids = np.zeros((B, Lp), np.int32)
                cts = np.zeros((B, Lp), np.float32)
                mask = np.zeros((B, Lp), np.float32)
                rows = np.repeat(np.arange(n), rls)
                cols = (np.arange(len(rows), dtype=np.int64)
                        - np.repeat(np.cumsum(rls) - rls, rls))
                src = np.repeat(doc_starts[sel], rls) + cols
                ids[rows, cols] = uids[src]
                cts[rows, cols] = sums[src]
                mask[rows, cols] = 1.0
                if n == B and not self._buf:
                    self.lam, self._last_gamma = self._step(
                        self.lam, float(self._t), ids, cts, mask)
                    self._t += 1
                else:
                    # short tail, or a pre-existing process() buffer that
                    # must keep its position: route through the streaming
                    # buffer (flushing at B exactly as process() does)
                    for b in range(n):
                        self._buf.append((ids[b, :rls[b]].copy(),
                                          cts[b, :rls[b]].copy()))
                        if len(self._buf) >= B:
                            self._flush()
        self._flush()
        return self

    def transform(self, words: Sequence[str]) -> np.ndarray:
        """Per-doc topic proportions (the lda_predict role)."""
        ids, cts = self._word_ids(words)
        B = 1
        ids_a = ids[None].astype(np.int32)
        cts_a = cts[None].astype(np.float32)
        mask = np.ones_like(cts_a)
        _, gamma = self._step(self.lam, float(self._t), ids_a, cts_a, mask)
        g = np.asarray(gamma)[0]
        return g / g.sum()


def _floatable(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


class PLSATrainer(LDATrainer):
    """SQL: train_plsa — incremental pLSA (EM over P(z|d), P(w|z))."""

    NAME = "train_plsa"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = super().spec()
        return s

    def _make_step(self):
        o = self.opts
        return _plsa_step_cached(self.K, self.V, float(o.alpha),
                                 int(o.iter), float(o.tau0),
                                 float(o.kappa))

    def __init__(self, options: str = ""):
        super().__init__(options)
        key = jax.random.PRNGKey(int(self.opts.seed))
        p = jax.random.uniform(key, (self.K, self.V)) + 0.1
        self.lam = p / p.sum(1, keepdims=True)    # lam slot holds P(w|z)


# --- predict UDAFs (join-side reassembly) ----------------------------------

def lda_predict(words: Sequence[str], model_rows: Sequence[Tuple[int, str, float]],
                topics: int, alpha: float = 0.5, iters: int = 64
                ) -> List[Tuple[int, float]]:
    """SQL: lda_predict — per-doc topic proportions from emitted model rows.
    model_rows: (topic, word, p(word|topic))."""
    pword: Dict[str, np.ndarray] = {}
    for k, w, p in model_rows:
        pword.setdefault(w, np.zeros(topics))[k] = p
    gamma = np.full(topics, alpha)
    doc = [w.rpartition(":")[0] or w for w in words]
    mats = np.stack([pword.get(w, np.full(topics, 1e-12)) for w in doc]) \
        if doc else np.zeros((0, topics))
    for _ in range(iters):
        theta = gamma / gamma.sum()
        resp = mats * theta[None, :]
        resp = resp / np.maximum(resp.sum(1, keepdims=True), 1e-100)
        gamma = alpha + resp.sum(0)
    theta = gamma / gamma.sum()
    return [(k, float(theta[k])) for k in range(topics)]


def plsa_predict(words: Sequence[str], model_rows, topics: int,
                 alpha: float = 0.5, iters: int = 64):
    """SQL: plsa_predict — same reassembly against P(w|z) rows."""
    return lda_predict(words, model_rows, topics, alpha, iters)


@_instrument("lda", "step")
@lru_cache(maxsize=32)
def _lda_step_cached(K: int, V: int, alpha: float, eta: float, inner: int,
                     D: float, tau0: float, kappa: float):
    """One online-VB LDA step (Hoffman's onlineldavb), jitted and cached
    per static config so trainer instances share a single compile."""
    @jax.jit
    def step(lam, t, ids, cts, mask):
        """ids/cts/mask: [B, L]; returns updated lambda and gamma."""
        B, L = ids.shape
        Elogbeta = _digamma(lam) - _digamma(lam.sum(1, keepdims=True))
        expElogbeta = jnp.exp(Elogbeta)                 # [K, V]
        eb = expElogbeta[:, ids]                        # [K, B, L]
        eb = jnp.moveaxis(eb, 0, 1)                     # [B, K, L]

        def estep(_, gamma):
            Elogth = _digamma(gamma) - _digamma(
                gamma.sum(1, keepdims=True))            # [B, K]
            expElogth = jnp.exp(Elogth)
            phinorm = jnp.einsum("bk,bkl->bl", expElogth, eb) + 1e-100
            gamma_new = alpha + expElogth * jnp.einsum(
                "bl,bkl->bk", cts * mask / phinorm, eb)
            return gamma_new

        gamma0 = jnp.ones((B, K))
        gamma = jax.lax.fori_loop(0, inner, estep, gamma0)
        Elogth = _digamma(gamma) - _digamma(gamma.sum(1, keepdims=True))
        expElogth = jnp.exp(Elogth)
        phinorm = jnp.einsum("bk,bkl->bl", expElogth, eb) + 1e-100
        # sufficient stats scattered back to the full vocab
        sstats_rows = expElogth[:, :, None] * (
            cts * mask / phinorm)[:, None, :]           # [B, K, L]
        sstats = jnp.zeros((K, V)).at[:, ids.reshape(-1)].add(
            jnp.moveaxis(sstats_rows, 1, 0).reshape(K, -1))
        sstats = sstats * expElogbeta
        rho = jnp.power(tau0 + t + 1.0, -kappa)
        docs_seen = jnp.maximum(mask.max(1).sum(), 1.0)
        lam_new = (1 - rho) * lam + rho * (
            eta + D * sstats / docs_seen)
        return lam_new, gamma

    return step


@_instrument("plsa", "step")
@lru_cache(maxsize=32)
def _plsa_step_cached(K: int, V: int, alpha: float, inner: int,
                      tau0: float, kappa: float):
    """One incremental-pLSA EM step, jitted and cached per static config
    (same per-instance recompile rationale as _lda_step_cached)."""
    @jax.jit
    def step(pwz, t, ids, cts, mask):
        """pwz: P(w|z) [K, V]; returns updated P(w|z) + per-doc P(z|d)."""
        B, L = ids.shape
        pw = pwz[:, ids]                       # [K, B, L]
        pw = jnp.moveaxis(pw, 0, 1)            # [B, K, L]

        def em(_, pzd):
            # E: P(z|d,w) ~ P(z|d) P(w|z)
            num = pzd[:, :, None] * pw         # [B, K, L]
            pzdw = num / (num.sum(1, keepdims=True) + 1e-100)
            # M (doc side): P(z|d) ~ sum_w n(d,w) P(z|d,w)
            pzd_new = (pzdw * (cts * mask)[:, None, :]).sum(-1) + alpha
            return pzd_new / pzd_new.sum(1, keepdims=True)

        pzd = jnp.full((B, K), 1.0 / K)
        pzd = jax.lax.fori_loop(0, inner, em, pzd)
        num = pzd[:, :, None] * pw
        pzdw = num / (num.sum(1, keepdims=True) + 1e-100)
        stats = (pzdw * (cts * mask)[:, None, :])       # [B, K, L]
        sstats = jnp.zeros((K, V)).at[:, ids.reshape(-1)].add(
            jnp.moveaxis(stats, 1, 0).reshape(K, -1))
        rho = jnp.power(tau0 + t + 1.0, -kappa)
        pwz_new = (1 - rho) * pwz + rho * (
            (sstats + 1e-3) / (sstats.sum(1, keepdims=True) + 1e-3 * V))
        return pwz_new, pzd

    return step
