"""train_fm / train_ffm — factorization-machine trainers (BASELINE config #2).

Reference (SURVEY.md §3.6): hivemall.fm.FactorizationMachineUDTF (train_fm,
options -factors/-iters/-eta*/-lambda*/-sigma/-classification/-int_feature),
FieldAwareFactorizationMachineUDTF (train_ffm, "field:index:value" features,
per-(feature,field) latent vectors, AdaGrad/FTRL), FMPredictGenericUDAF /
FFMPredictUDF for scoring.

TPU design: dense hashed tables w[N], V[N,K] (FM) / V[N,F,K] (FFM) in HBM,
bf16-able; one jitted value_and_grad step per minibatch (ops.fm). The FFM
(feature,field) table is the TP-sharding target for multi-chip (SURVEY.md §8
M3); see parallel.dp / __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.sparse import (PackedBatch, SparseBatch, SparseDataset,
                         canonicalize_fieldmajor, pack_unit_fieldmajor)
from ..ops.fm import (ffm_row_hash, ffm_score, fm_pack_geometry, fm_score,
                      make_ffm_score_fieldmajor, make_ffm_score_fused,
                      make_ffm_step, make_ffm_step_fused,
                      make_fm_score_fused, make_fm_step, make_fm_step_fused)
from ..ops.losses import get_loss
from ..ops.optimizers import (make_optimizer,
                              make_optimizer_cached)
from ..utils.hashing import mhash
from ..utils.options import OptionSpec
from .base import LearnerBase, learner_option_spec

__all__ = ["FMTrainer", "FFMTrainer", "fm_predict", "ffm_predict"]


# --- config-cached step builders (round 4) ---------------------------------
# A fresh jitted closure per TRAINER instance re-traces/compiles for every
# identical config (the disease that cost word2vec 4x and LDA 10x e2e —
# each bench/CV iteration constructing a new trainer paid seconds of XLA
# compile). Steps/scorers are pure functions of the OPTION subset below, so
# module-level lru_caches keyed on it let instances share one compile;
# sharing jitted fns is safe (donation applies per CALL to that call's
# buffers, and all trainer state is passed in, never closed over).

from functools import lru_cache as _lru_cache
from functools import partial as _partial

from ..obs.devprof import instrument_factory as _instrument


@_instrument("fm", "step_fused")
@_lru_cache(maxsize=64)
def _fm_step_fused_cached(loss_name, opt, eta_scheme, eta0, total_steps,
                          power_t, lambdas, k):
    return make_fm_step_fused(
        get_loss(loss_name),
        make_optimizer_cached(opt, eta_scheme, eta0, total_steps,
                              power_t),
        lambdas, k)


@_instrument("fm", "step_minibatch")
@_lru_cache(maxsize=64)
def _fm_step_minibatch_cached(loss_name, opt, eta_scheme, eta0, total_steps,
                              power_t, lambdas, k):
    from ..ops.fm import make_fm_step_minibatch
    return make_fm_step_minibatch(
        get_loss(loss_name),
        make_optimizer_cached(opt, eta_scheme, eta0, total_steps,
                              power_t),
        lambdas, k)


@_instrument("fm", "step")
@_lru_cache(maxsize=64)
def _fm_step_cached(loss_name, opt, eta_scheme, eta0, total_steps,
                    power_t, lambdas):
    return make_fm_step(
        get_loss(loss_name),
        make_optimizer_cached(opt, eta_scheme, eta0, total_steps,
                              power_t),
        lambdas)


@_instrument("ffm", "step_fused")
@_lru_cache(maxsize=64)
def _ffm_step_fused_cached(loss_name, opt, eta_scheme, eta0, total_steps,
                           power_t, lambdas, F, k, fieldmajor, unit_val):
    return make_ffm_step_fused(
        get_loss(loss_name),
        make_optimizer_cached(opt, eta_scheme, eta0, total_steps,
                              power_t),
        lambdas, F, k, fieldmajor=fieldmajor, unit_val=unit_val)


@_instrument("ffm", "step")
@_lru_cache(maxsize=64)
def _ffm_step_cached(loss_name, opt, eta_scheme, eta0, total_steps,
                     power_t, lambdas):
    return make_ffm_step(
        get_loss(loss_name),
        make_optimizer_cached(opt, eta_scheme, eta0, total_steps,
                              power_t),
        lambdas)


@_instrument("ffm", "parts_step")
@_lru_cache(maxsize=64)
def _parts_step_cached(loss_name, eta_scheme, eta0, total_steps, power_t,
                       lambdas, F, k, MRF, unit_val, interpret):
    from ..ops.fm_pallas import make_parts_step
    from ..ops.schedules import make_eta
    return make_parts_step(get_loss(loss_name),
                           make_eta(eta_scheme, eta0, total_steps, power_t),
                           lambdas, F, k, MRF, unit_val=unit_val,
                           interpret=interpret)


@_instrument("ffm", "parts_score")
@_lru_cache(maxsize=64)
def _parts_score_cached(F, k, MRF):
    from ..ops.fm_pallas import make_parts_score
    return make_parts_score(F, k, MRF)


@_instrument("fm", "score_fused")
@_lru_cache(maxsize=64)
def _fm_score_fused_cached(k):
    return make_fm_score_fused(k)


@_instrument("ffm", "score_fused")
@_lru_cache(maxsize=64)
def _ffm_score_fused_cached(F, k):
    return make_ffm_score_fused(F, k)


@_instrument("ffm", "score_fieldmajor")
@_lru_cache(maxsize=64)
def _ffm_score_fieldmajor_cached(F, k):
    return make_ffm_score_fieldmajor(F, k)


def _unpack_on_device(buf, nv, B: int, L: int):
    """Device-side decode of ONE io.sparse.PackedBatch wire buffer:
    3-byte little-endian idx lanes reassembled via shifts, f32 labels via
    bitcast, valid-row mask from the nv scalar. The single source of the
    packed wire format on the consume side — the K=1 wrapper and the
    K-step scan body below both call it, so a layout change can never
    reach one dispatch path and not the other. Elementwise, fuses into
    the step; the win is on the h2d link (see io.sparse.PackedBatch)."""
    ni = B * L * 3
    b3 = buf[:ni].reshape(B, L, 3).astype(jnp.int32)
    idx = b3[..., 0] | (b3[..., 1] << 8) | (b3[..., 2] << 16)
    label = jax.lax.bitcast_convert_type(
        buf[ni:].reshape(B, 4), jnp.float32)
    mask = (jnp.arange(B) < nv).astype(jnp.float32)
    return idx, label, mask


@_instrument("ffm", "packed_megastep", shape_args=(1, 2))
@_lru_cache(maxsize=128)
def _packed_megawrap_cached(base_step, B: int, L: int):
    """K-step fused dispatch for the PACKED flagship path
    (-steps_per_dispatch > 1 + pack_input): one jitted lax.scan over a
    [K, nbytes] stacked uint8 buffer, each step unpacking its window
    (_unpack_on_device) and running the SAME unit-val field-major step
    core the K=1 path compiled. Model/optimizer state is donated through
    the scan carry — XLA updates the tables in place across all K
    steps."""
    core = getattr(base_step, "core", base_step)

    @_partial(jax.jit, donate_argnums=(0, 1))
    def fn(params, opt_state, t0, bufs, nvs):
        def body(carry, x):
            p, s, t = carry
            idx, label, mask = _unpack_on_device(x["buf"], x["nv"], B, L)
            p, s, loss = core(p, s, t, idx, label, mask)
            return (p, s, t + 1.0), loss

        (p, s, _), losses = jax.lax.scan(
            body, (params, opt_state, t0), {"buf": bufs, "nv": nvs})
        return p, s, losses

    # same devprof dispatch boundary as ops.scan.megastep_for: the packed
    # flagship path must not be the one fused dispatch whose peak-bytes
    # tracking silently reads zero
    from ..ops.scan import _profiled_megastep
    return _profiled_megastep(fn)


@_instrument("ffm", "packed_step", shape_args=(1, 2))
@_lru_cache(maxsize=128)
def _packed_wrap_cached(base_step, B: int, L: int):
    """Jitted wrapper (cached per (shared base step, batch shape)) that
    unpacks a PackedBatch buffer on device (_unpack_on_device) then runs
    the regular unit-val field-major step."""
    @jax.jit
    def fn(params, opt_state, t, buf, nv):
        idx, label, mask = _unpack_on_device(buf, nv, B, L)
        return base_step(params, opt_state, t, idx, label, mask)

    return fn

def _factor_spec(name: str, default_factors: int, default_opt: str
                 ) -> OptionSpec:
    s = learner_option_spec(name, classification=True,
                            default_loss="squaredloss")
    s.add("factors", "factor", type=int, default=default_factors,
          help="latent dimension k")
    s.add("sigma", type=float, default=0.1, help="init stddev for V")
    s.flag("classification", help="optimize logloss on +-1 labels "
                                  "(default: regression, squared loss)")
    s.add("lambda0", type=float, default=0.01, help="L2 for w0")
    s.add("lambda_w", type=float, default=0.01, help="L2 for linear weights")
    s.add("lambda_v", type=float, default=0.01, help="L2 for latent factors")
    s.add("min_target", type=float, default=None, help="clip regression target")
    s.add("max_target", type=float, default=None, help="clip regression target")
    s.add("seed", type=int, default=42, help="init seed")
    s.add("fm_table", default="auto",
          help="train_fm table layout: fused (one [N, K+pad] row per "
               "feature holding V and w — half the gather/scatter index "
               "ops, see docs/PERFORMANCE.md) | split (separate w/V) | "
               "auto (fused when the optimizer has a sparse form)")
    for o in s.options:
        if o.name == "opt":
            o.default = default_opt
        if o.name == "reg":
            o.default = "no"       # factor models carry their own L2 lambdas
    return s


class FMTrainer(LearnerBase):
    """SQL: train_fm — reference hivemall.fm.FactorizationMachineUDTF."""

    NAME = "train_fm"
    CLASSIFICATION = False     # label handling driven by -classification
    _adareg = False            # class default: FFMTrainer inherits the
    # _batch_args/_fit_epochs hooks without running FM's _init_state

    @classmethod
    def spec(cls) -> OptionSpec:
        s = _factor_spec(cls.NAME, default_factors=5, default_opt="sgd")
        # reference train_fm options (SURVEY.md §3.6 FM row): adaptive
        # regularization against a held-out validation fraction
        s.flag("adareg", "adaptive_regularization",
               help="adapt -lambda_w/-lambda_v per epoch against a "
                    "held-out validation split (see -va_ratio)")
        s.add("va_ratio", "validation_ratio", type=float, default=0.05,
              help="fraction of rows held out for -adareg validation")
        s.add("fm_update", default="auto",
              help="fused-layout update shape: minibatch (one scatter-add "
                   "into a dense G + dense AdaGrad — accumulators see the "
                   "summed batch gradient, 2 index ops/slot) | occurrence "
                   "(per-occurrence sparse AdaGrad chain, 5 index "
                   "ops/slot) | auto (minibatch for -opt adagrad)")
        return s

    def _init_state(self) -> None:
        o = self.opts
        self.classification = bool(o.classification)
        self._loss_name = ("logloss" if self.classification
                           else (o.loss or "squaredloss"))
        self.loss = get_loss(self._loss_name)
        self._opt_key = (str(o.opt), str(o.eta), float(o.eta0),
                         o.total_steps, o.power_t)
        self.optimizer = make_optimizer_cached(*self._opt_key)
        self.k = int(o.factors)
        dtype = jnp.bfloat16 if o.halffloat else jnp.float32
        key = jax.random.PRNGKey(int(o.seed))
        self.fm_layout = str(getattr(o, "fm_table", "auto"))
        if self.fm_layout not in ("fused", "split", "auto"):
            raise ValueError(f"-fm_table must be fused|split|auto, "
                             f"got {self.fm_layout!r}")
        # fused needs zero-grad sparse updates to be exact no-ops on the
        # sibling features packed into the same 128-lane row; FTRL/RDA
        # re-materialize every scattered element (they'd wipe siblings'
        # lazy init), so only the elementwise .add families qualify
        fusable = self.optimizer.name in ("sgd", "adagrad")
        self._adareg = False
        upd = str(getattr(o, "fm_update", "auto"))
        if upd not in ("auto", "minibatch", "occurrence"):
            raise ValueError(f"-fm_update must be auto|minibatch|"
                             f"occurrence, got {upd!r}")
        if self.fm_layout == "auto":
            self.fm_layout = "fused" if fusable else "split"
        if self.fm_layout == "fused" and not fusable:
            raise ValueError(f"-fm_table fused needs -opt sgd|adagrad "
                             f"(-opt {self.optimizer.name} re-materializes "
                             f"packed sibling rows); use -fm_table split")
        if self.fm_layout == "fused":
            # packed fused rows: [V(K) | w | pad] x P features per 128-lane
            # physical row — one gather + one sparse update per step
            # instead of two tables' worth of narrow-row chains
            self.W, self.P = fm_pack_geometry(self.k)
            self.Np = -(-self.dims // self.P)
            Tinit = jnp.concatenate([
                jax.random.normal(key, (self.Np * self.P, self.k)) *
                float(o.sigma),
                jnp.zeros((self.Np * self.P, self.W - self.k)),
            ], axis=1).astype(dtype).reshape(self.Np, self.P * self.W)
            self.params = {"w0": jnp.zeros((), dtype), "T": Tinit}
            self.opt_state = {
                "w0": self.optimizer.init(()),
                "T": self.optimizer.init((self.Np, self.P * self.W))}
            self._adareg = bool(getattr(o, "adareg", False))
            self._va_ratio = float(getattr(o, "va_ratio", 0.05))
            if self._adareg:
                if not 0.0 < self._va_ratio < 0.5:
                    raise ValueError(
                        f"-va_ratio must be in (0, 0.5), got "
                        f"{self._va_ratio}")
                # runtime lambdas (adapted per epoch) -> dynamic-lambda
                # step variants (lambdas=None builders)
                self._lams = np.asarray(
                    [o.lambda0, o.lambda_w, o.lambda_v], np.float32)
            # minibatch: ONE scatter-add into a dense G + dense optimizer
            # pass (2 table-row index ops/slot) instead of the
            # per-occurrence sparse chain's 5 — the update shape the FFM
            # fused/parts paths already use. AdaGrad only: SGD's sparse
            # form is already 2 index ops, and the dense pass would be
            # pure overhead there.
            if upd == "minibatch" and self.optimizer.name != "adagrad":
                raise ValueError("-fm_update minibatch needs -opt adagrad")
            if upd == "auto":
                upd = ("minibatch" if self.optimizer.name == "adagrad"
                       else "occurrence")
            # -adareg: lambdas become a runtime step argument (the None
            # sentinel below) so per-epoch adaptation re-uses one compile
            lam_key = (None if self._adareg
                       else (o.lambda0, o.lambda_w, o.lambda_v))
            if upd == "minibatch":
                self._step = _fm_step_minibatch_cached(
                    self._loss_name, *self._opt_key, lam_key, self.k)
            else:
                self._step = _fm_step_fused_cached(
                    self._loss_name, *self._opt_key, lam_key, self.k)
            self._fused_score = _fm_score_fused_cached(self.k)
            self._tp_sizes.add(self.Np)    # mesh: shard packed rows over tp
            self.UNIT_VAL_ELISION = True   # fused step accepts val=None
        else:
            if bool(getattr(o, "adareg", False)):
                raise ValueError("-adareg needs the fused table layout "
                                 "(-fm_table fused, i.e. -opt sgd|adagrad)")
            if upd != "auto":
                raise ValueError("-fm_update applies to the fused table "
                                 "layout only (-fm_table fused)")
            self.params = {
                "w0": jnp.zeros((), dtype),
                "w": jnp.zeros(self.dims, dtype),
                "V": (jax.random.normal(key, (self.dims, self.k)) *
                      float(o.sigma)).astype(dtype),
            }
            self.opt_state = {k: self.optimizer.init(v.shape)
                              for k, v in self.params.items()}
            self._step = _fm_step_cached(
                self._loss_name, *self._opt_key,
                (o.lambda0, o.lambda_w, o.lambda_v))

    def _convert_label(self, label: float) -> float:
        if self.classification:
            return 1.0 if float(label) > 0 else -1.0
        y = float(label)
        if self.opts.min_target is not None:
            y = max(y, self.opts.min_target)
        if self.opts.max_target is not None:
            y = min(y, self.opts.max_target)
        return y

    def _convert_labels(self, labels: np.ndarray) -> np.ndarray:
        if self.classification:
            return np.where(labels > 0, 1.0, -1.0).astype(np.float32)
        y = labels.astype(np.float32)
        if self.opts.min_target is not None:
            y = np.maximum(y, self.opts.min_target)
        if self.opts.max_target is not None:
            y = np.minimum(y, self.opts.max_target)
        return y

    def _batch_args(self, batch: SparseBatch) -> tuple:
        if self._adareg:
            return (jnp.asarray(self._lams),)
        return ()

    def _mega_lams(self):
        # -adareg runtime lambdas ride the megastep as a BROADCAST extra
        # (not scanned): all K steps in a window see the same lambdas,
        # exactly as K consecutive K=1 steps within one epoch do
        # (adaptation happens per epoch, between fits)
        if self._adareg:
            return jnp.asarray(self._lams)
        return None

    def _train_batch(self, batch: SparseBatch) -> float:
        self.params, self.opt_state, loss_sum = self._step(
            self.params, self.opt_state, float(self._t), batch.idx, batch.val,
            batch.label, batch.row_mask, *self._batch_args(batch))
        return loss_sum

    # -- adaptive regularization (-adareg, SURVEY.md §3.6 train_fm row) -----
    _ADAREG_UP, _ADAREG_DOWN = 2.0, 0.9

    def _fit_epochs(self, ds, epochs, bs, shuffle, prefetch, ckdir,
                    seed0: int = 42) -> None:
        """-adareg: hold out -va_ratio of the rows, train each epoch on
        the rest, and adapt lambda_w/lambda_v against the held-out loss —
        validation got WORSE since the last epoch -> multiply lambdas by
        2 (regularize harder), got better -> decay by 0.9 (the reference's
        SGDA-style per-update lambda gradient becomes this per-epoch
        multiplicative trust region; direction is pinned by test). The
        step reads lambdas at RUNTIME (dynamic-lambda variant), so
        adaptation never recompiles."""
        if not self._adareg or len(ds) < 20:
            return super()._fit_epochs(ds, epochs, bs, shuffle, prefetch,
                                       ckdir, seed0)
        rng = np.random.default_rng(int(self.opts.seed))
        n = len(ds)
        n_va = max(1, int(round(n * self._va_ratio)))
        perm = rng.permutation(n)
        ds_va = ds.take(perm[:n_va])
        ds_tr = ds.take(perm[n_va:])
        prev = None
        for ep in range(epochs):
            # ckdir handled HERE so bundle names carry the REAL epoch
            # number (the inner call's local epoch is always 1)
            super()._fit_epochs(ds_tr, 1, bs, shuffle, prefetch, None,
                                seed0=seed0 + ep)
            if ckdir:
                self._save_epoch_bundle(ckdir, ep + 1)
            # per-EPOCH validation eval, not per step: one sync per
            # epoch is the adaptive-regularization design
            # graftcheck: disable=GC07
            va = self._mean_loss(ds_va)
            if prev is not None:
                scale = (self._ADAREG_UP if va > prev * (1 + 1e-9)
                         else self._ADAREG_DOWN)
                self._lams[1:] *= scale
            prev = va

    def _mean_loss(self, ds: SparseDataset) -> float:
        phi = self.decision_function(ds)
        return float(np.mean(np.asarray(self.loss.loss(
            jnp.asarray(phi), jnp.asarray(ds.labels)))))

    # -- scoring -------------------------------------------------------------
    def _score_batch(self, batch: SparseBatch) -> np.ndarray:
        p = self.params
        if getattr(self, "fm_layout", "split") == "fused":
            return np.asarray(self._fused_score(
                p["w0"], p["T"], jnp.asarray(batch.idx),
                jnp.asarray(batch.val)))
        return np.asarray(fm_score(p["w0"], p["w"], p["V"],
                                   batch.idx, batch.val))

    def _make_margin_fn(self):
        # _score_batch reads self.params at call time (no finalization
        # pass to freeze); the serve engine still swaps trainer + scorer
        # as one ref, so a hot-reload can never mix versions mid-batch
        return self._score_batch

    def decision_function(self, ds: SparseDataset) -> np.ndarray:
        return self._score_dataset(ds)

    def predict(self, ds: SparseDataset) -> np.ndarray:
        phi = self.decision_function(ds)
        if self.classification:
            return 1.0 / (1.0 + np.exp(-phi))
        return phi

    def make_scorer(self):
        # mirror predict()'s historical sigmoid form exactly so online
        # scores bit-match the offline FM predict path
        margin = self._make_margin_fn()
        if self.classification:
            return lambda b: np.asarray(
                1.0 / (1.0 + np.exp(-np.asarray(margin(b), np.float32))),
                np.float32)
        return lambda b: np.asarray(margin(b), np.float32)

    def serving_tables(self):
        """Arena extraction (io.weight_arena): the canonical (w, V)
        split-layout f32 tables — _wv_tables already normalizes the
        fused packed layout, so one arena family serves both."""
        w, V = self._wv_tables()
        meta = {"family": "fm", "k": self.k,
                "w0": float(np.asarray(self.params["w0"],
                                       np.float32)),
                "classification": bool(self.classification)}
        return meta, {"w": np.ascontiguousarray(w, np.float32),
                      "V": np.ascontiguousarray(V, np.float32)}

    def _fused_rows(self):
        """Per-feature [>=dims, Wf] view of the packed fused table (device).
        Row i = feature i's [V(K) | w | pad] block — the [Np, P*Wf]
        physical layout unpacks with one reshape."""
        return self.params["T"].reshape(self.Np * self.P, self.W)

    def _wv_tables(self):
        """(w [N], V [N, K]) float32 views for emission, either layout."""
        if getattr(self, "fm_layout", "split") == "fused":
            R = np.asarray(self._fused_rows().astype(jnp.float32))
            return R[:self.dims, self.k], R[:self.dims, :self.k]
        return (np.asarray(self.params["w"].astype(jnp.float32)),
                np.asarray(self.params["V"].astype(jnp.float32)))

    # -- model emission: (feature, Wi, Vi[]) rows ---------------------------
    def model_rows(self):
        w, V = self._wv_tables()
        touched = np.nonzero((np.abs(V).sum(-1) > 0) | (w != 0))[0]
        yield ("0", float(np.asarray(self.params["w0"])), None)
        for i in touched:
            if i == 0:
                continue
            yield (self._names.get(int(i), str(int(i))), float(w[i]),
                   V[i].tolist())

    def model_table(self):
        return {row[0]: row[1:] for row in self.model_rows()}

    def save_model(self, path: str) -> None:
        """Binary model bundle (params + optimizer state), orbax-style npz."""
        # save path: one fetch per param tensor (a handful), not per step
        np.savez(path, **{k: np.asarray(v.astype(jnp.float32))  # graftcheck: disable=GC07
                          for k, v in self.params.items()})

    def _warm_start(self, path: str) -> None:
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        missing = [k for k in self.params if k not in z.files]
        if missing:
            raise ValueError(
                f"-loadmodel {path}: saved model has keys "
                f"{sorted(z.files)} but this trainer expects "
                f"{sorted(self.params)} — table-layout mismatch "
                f"(-fm_table/-ffm_table changed since the save?)")
        for k in self.params:
            if tuple(z[k].shape) != tuple(self.params[k].shape):
                raise ValueError(
                    f"-loadmodel {path}: saved {k!r} has shape "
                    f"{tuple(z[k].shape)}, trainer expects "
                    f"{tuple(self.params[k].shape)} — options mismatch "
                    f"(-dims/-factors/-fields/-fm_table/-ffm_table)?")
            self.params[k] = jnp.asarray(z[k], self.params[k].dtype)

    # -- sparse weight access for the mix client (fused layout: w is col k) --
    def _weight_table(self):
        if getattr(self, "fm_layout", "split") == "fused":
            return None                # w lives inside T; use overrides
        return super()._weight_table()

    def _get_weights_at(self, keys: np.ndarray) -> np.ndarray:
        if getattr(self, "fm_layout", "split") != "fused":
            return super()._get_weights_at(keys)
        rr = jnp.asarray(np.asarray(keys))
        return np.asarray(self._fused_rows()[rr, self.k], np.float32)

    def _set_weights_at(self, keys: np.ndarray, vals: np.ndarray) -> None:
        if getattr(self, "fm_layout", "split") != "fused":
            return super()._set_weights_at(keys, vals)
        R = self._fused_rows()
        rr = jnp.asarray(np.asarray(keys))
        R = R.at[rr, self.k].set(jnp.asarray(vals, R.dtype))
        self.params["T"] = R.reshape(self.Np, self.P * self.W)

    def _finalized_weights(self) -> np.ndarray:
        if getattr(self, "fm_layout", "split") == "fused":
            return np.asarray(
                self._fused_rows()[:self.dims, self.k].astype(jnp.float32))
        return np.asarray(self.params["w"].astype(jnp.float32))

    def _load_weights(self, w: np.ndarray) -> None:
        if getattr(self, "fm_layout", "split") == "fused":
            R = self._fused_rows()
            R = R.at[:self.dims, self.k].set(jnp.asarray(w, R.dtype))
            self.params["T"] = R.reshape(self.Np, self.P * self.W)
            return
        self.params["w"] = jnp.asarray(w, self.params["w"].dtype)


# --- FFM host prep as pure config-parameterized functions -------------------
# The parallel prep leg (canonicalize -> parts row pad -> pack) must exist
# in TWO callable forms with identical semantics: the bound trainer methods
# (thread pools, sequential fallback) and a PICKLABLE config-built callable
# for -ingest_pool process — a bound method would drag the whole trainer
# (device tables included) through pickle per task and cannot cross the
# fork. Both forms call the module functions below, so they can never
# drift; tests/test_pipeline.py pins process == thread == sequential
# bit-exact.

from dataclasses import dataclass as _dataclass


def _ffm_canonicalize(batch: SparseBatch, F: int, canon_on: bool,
                      forced: bool) -> SparseBatch:
    """Canonicalize one host batch into field-major slots (slot s holds a
    feature of field s % F) so the jitted step can run the static
    field-grouped interaction — no L^2 intermediate, no per-slot field
    array. Skipped (general pair path) when the trainer/layout doesn't use
    it (``canon_on``), when a row has > 4 same-field features, or when the
    canonical width m*F would more than double the batch (rows sparse
    relative to the field space — the pair kernel is cheaper there).
    ``forced`` (-ffm_interaction fieldmajor) disables the width bail and
    raises on overflow instead of falling back."""
    if not canon_on or batch.fieldmajor or batch.field is None:
        return batch
    L = int(batch.idx.shape[1])
    if not forced and F > 2 * L:            # even m=1 inflates > 2x
        return batch
    res = canonicalize_fieldmajor(
        np.asarray(batch.idx), np.asarray(batch.val),
        np.asarray(batch.field), F)
    if res is None or (not forced and res[2] * F > 2 * L):
        if forced and res is None:
            raise ValueError(
                "-ffm_interaction fieldmajor: a row has more than 4 "
                "features in one field; use -ffm_interaction auto")
        return batch
    idx2, val2, _ = res
    if np.array_equal(val2, (idx2 != 0).astype(np.float32)):
        # unit-value elision: skip the val array entirely (a third of
        # the h2d bytes; the step rebuilds it from idx on device)
        val2 = None
    return SparseBatch(idx2, val2, batch.label, None,
                       n_valid=batch.n_valid, fieldmajor=True)


def _parts_row_target(B: int, dp: int = 1) -> int:
    """The parts kernel's allocated row count for ``B`` logical rows:
    whole 128-row tiles (the SMEM row-id packing) up to 2048, then whole
    2048-row chunks, scaled by the dp axis. The ONE copy of the grid rule
    — the streamed pad (_ffm_pad_parts) and the shard cache's batch
    assembly (_cache_row_pad) must agree or cached batches stop matching
    the compiled buckets."""
    mult = 128 * dp if B <= 2048 * dp else 2048 * dp
    return -(-B // mult) * mult


def _ffm_pad_parts(batch: SparseBatch, dp: int = 1) -> SparseBatch:
    """Pad the batch's row count to the Pallas parts kernel's grid
    multiple (_parts_row_target); padded rows carry idx 0 and are masked
    out of the loss by n_valid. Under -mesh each dp rank must receive
    whole tiles, so the multiple scales by dp on both branches."""
    B = batch.batch_size
    target = _parts_row_target(B, dp)
    if target == B:
        return batch
    pad = target - B
    idx = np.pad(np.asarray(batch.idx), ((0, pad), (0, 0)))
    val = None if batch.val is None else np.pad(
        np.asarray(batch.val), ((0, pad), (0, 0)))
    lab = np.pad(np.asarray(batch.label), (0, pad))
    nv = batch.n_valid if batch.n_valid is not None else B
    return SparseBatch(idx, val, lab, None, n_valid=nv, fieldmajor=True)


@_dataclass(frozen=True)
class FFMPrep:
    """Picklable FFM train-prep: a plain dataclass of the option-derived
    booleans the bound prep reads off the trainer, so a process-pool
    worker rebuilds the exact same function from ~5 scalars instead of a
    pickled trainer. ``__call__`` IS ``_preprocess_train_parallel``."""

    F: int
    canon: bool          # a field-major step exists (joint/parts layouts)
    forced: bool         # -ffm_interaction fieldmajor
    parts: bool          # parts layout: kernel-grid row padding
    pack: bool           # packed uint8 wire format conditions all hold
    parts_dp: int = 1

    def __call__(self, batch: SparseBatch):
        batch = _ffm_canonicalize(batch, self.F, self.canon, self.forced)
        if self.parts and batch.fieldmajor:
            batch = _ffm_pad_parts(batch, self.parts_dp)
        if (self.pack and batch.fieldmajor and batch.val is None
                and isinstance(batch.idx, np.ndarray)):
            return pack_unit_fieldmajor(batch)
        return batch


def _tee_into_writer(src, writer, order, bs: int):
    """Yield prepared batches unchanged while scattering each one's rows
    into a shard-cache writer (batch i covers order[i*bs:(i+1)*bs] — the
    same chunking ds.batches applied to the same permutation). Runs on
    whatever single thread consumes the prep pipeline."""
    i = 0
    for b in src:
        writer.add(b, order[i * bs:(i + 1) * bs])
        i += 1
        yield b


class FFMTrainer(FMTrainer):
    """SQL: train_ffm — reference hivemall.fm.FieldAwareFactorizationMachineUDTF.

    Features are "field:index:value" triples (ftvec.trans.ffm_features).
    Two latent-table layouts (-ffm_table):

      joint (default) — the fused feature-row layout: one table
        T[Mr, F*K + 8] where row ffm_row_hash(feature) holds ALL F of that
        feature's per-field latent vectors plus its linear weight, and
        Mr * F_pow2 = -dims total (feature, field) capacity. The TPU analog
        of the reference's packed-long keys, laid out so one train step
        costs exactly one row-gather + one row-scatter (TPU scatter cost is
        per-row, not per-byte — see ops.fm.make_ffm_step_fused; measured
        95x over a flat per-pair table). Criteo-scale ``-dims 2^24
        -fields 64 -halffloat`` is ~140 MB of weights + ~280 MB f32
        AdaGrad state, single-chip friendly; shards over 'tp'.
      dense — V[N, F, K] field cube, exact (feature, field) cells, for
        small field counts.
    """

    NAME = "train_ffm"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = _factor_spec(cls.NAME, default_factors=4, default_opt="adagrad")
        s.add("fields", "num_fields", type=int, default=64,
              help="field-space size F")
        s.add("ffm_table", default="auto",
              help="latent-table layout: joint (hashed flat [M,K], "
                   "Criteo-scale) | parts (field-partitioned fused rows "
                   "with the Pallas VMEM scatter+AdaGrad kernel — fastest "
                   "on TPU for adagrad/-halffloat/fieldmajor configs) | "
                   "dense ([N,F,K] field cube) | auto (joint when -dims "
                   "is a power of two, else dense)")
        s.add("ffm_interaction", default="auto",
              help="pair-interaction kernel for the joint layout: "
                   "fieldmajor (canonical field-major batches, no L^2 "
                   "intermediate — fastest when rows are near field-dense, "
                   "e.g. Criteo) | pairs (general one-hot einsum) | auto "
                   "(fieldmajor per batch when it fits, else pairs)")
        s.flag("no_w0", help="drop the global bias term")
        s.flag("no_wi", help="drop the linear terms (libffm-style)")
        s.add("pack_input", default="auto",
              help="pack canonical unit-value batches into one 3-byte-lane "
                   "uint8 buffer per h2d transfer (idx exact for dims <= "
                   "2^24; ~27%% fewer input bytes and one transfer instead "
                   "of three): auto (accelerators only) | on | off")
        return s

    def _init_state(self) -> None:
        o = self.opts
        self.classification = bool(o.classification)
        self._loss_name = ("logloss" if self.classification
                           else (o.loss or "squaredloss"))
        self.loss = get_loss(self._loss_name)
        self._opt_key = (str(o.opt), str(o.eta), float(o.eta0),
                         o.total_steps, o.power_t)
        self.optimizer = make_optimizer_cached(*self._opt_key)
        self.k = int(o.factors)
        self.F = int(o.fields)
        self.layout = str(o.ffm_table)
        if self.layout not in ("joint", "dense", "auto", "parts"):
            raise ValueError(f"-ffm_table must be joint|parts|dense|auto, "
                             f"got {self.layout!r}")
        self.interaction = str(getattr(o, "ffm_interaction", "auto"))
        if self.interaction not in ("auto", "pairs", "fieldmajor"):
            raise ValueError("-ffm_interaction must be auto|pairs|fieldmajor,"
                             f" got {self.interaction!r}")
        pow2 = (self.dims & (self.dims - 1)) == 0
        if self.layout == "auto":
            self.layout = "joint" if pow2 else "dense"
        if self.layout in ("joint", "parts") and not pow2:
            raise ValueError(f"-ffm_table {self.layout} needs a "
                             f"power-of-two -dims (got {self.dims})")
        dtype = jnp.bfloat16 if o.halffloat else jnp.float32
        key = jax.random.PRNGKey(int(o.seed))
        if self.layout == "parts":
            from ..ops.fm_pallas import parts_geometry, parts_supported
            if not parts_supported(self.F, self.k, self.optimizer.name,
                                   dtype):
                raise ValueError(
                    "-ffm_table parts requires -opt adagrad, -halffloat, "
                    f"and F*K+8 <= 248 (got opt={self.optimizer.name}, "
                    f"dtype={dtype.__name__}, F={self.F}, K={self.k}); "
                    "use -ffm_table joint")
            self.MRF, self.Wp, self.HP = parts_geometry(self.dims, self.F,
                                                        self.k)
            FK = self.F * self.k
            Tl = jnp.concatenate([
                jax.random.normal(key, (self.F * self.MRF, FK))
                * float(o.sigma),
                jnp.zeros((self.F * self.MRF, self.Wp - FK)),
            ], axis=1)
            self.params = {
                "w0": jnp.zeros((), jnp.float32),
                "T2": Tl.reshape(self.F * self.MRF * self.HP,
                                 128).astype(dtype)}
            self.opt_state = {
                "w0": self.optimizer.init(()),
                "T2": {"gg": jnp.zeros((self.F * self.MRF * self.HP, 128),
                                       jnp.float32)}}
            interp = jax.default_backend() != "tpu"
            lamt = (o.lambda0, o.lambda_w, o.lambda_v)
            eta_key = (str(o.eta), float(o.eta0), o.total_steps, o.power_t)
            self._step = None
            self._step_fm = _parts_step_cached(
                self._loss_name, *eta_key, lamt, self.F, self.k, self.MRF,
                False, interp)
            self._step_fm_unit = _parts_step_cached(
                self._loss_name, *eta_key, lamt, self.F, self.k, self.MRF,
                True, interp)
            self._fused_score = None
            self._fused_score_fm = _parts_score_cached(self.F, self.k,
                                                       self.MRF)
            self.interaction = "fieldmajor"   # parts is fieldmajor-only
            self._pairs = set()
            self._fit_ds = None
            return
        if self.layout == "joint":
            f_pow2 = 1
            while f_pow2 < self.F:
                f_pow2 <<= 1
            self.Mr = max(1 << 10, self.dims // f_pow2)
            FK = self.F * self.k
            self.W = FK + 8            # [V(F*K) | w | pad] fused row
            Tinit = jnp.concatenate([
                jax.random.normal(key, (self.Mr, FK)) * float(o.sigma),
                jnp.zeros((self.Mr, self.W - FK)),
            ], axis=1).astype(dtype)
            self.params = {"w0": jnp.zeros((), dtype), "T": Tinit}
            self.opt_state = {"w0": self.optimizer.init(()),
                              "T": self.optimizer.init((self.Mr, self.W))}
            opt_key = self._opt_key
            lamt = (o.lambda0, o.lambda_w, o.lambda_v)
            self._step = _ffm_step_fused_cached(
                self._loss_name, *opt_key, lamt, self.F, self.k,
                False, False)
            self._step_fm = None if self.interaction == "pairs" else \
                _ffm_step_fused_cached(
                    self._loss_name, *opt_key, lamt, self.F, self.k,
                    True, False)
            self._step_fm_unit = None if self.interaction == "pairs" else \
                _ffm_step_fused_cached(
                    self._loss_name, *opt_key, lamt, self.F, self.k,
                    True, True)
            self._fused_score = _ffm_score_fused_cached(self.F, self.k)
            self._fused_score_fm = _ffm_score_fieldmajor_cached(self.F,
                                                                self.k)
            self._tp_sizes.add(self.Mr)     # mesh: shard T rows over tp
        else:
            self.params = {
                "w0": jnp.zeros((), dtype),
                "w": jnp.zeros(self.dims, dtype),
                "V": (jax.random.normal(key, (self.dims, self.F, self.k)) *
                      float(o.sigma)).astype(dtype),
            }
            self.opt_state = {k: self.optimizer.init(v.shape)
                              for k, v in self.params.items()}
            if self.interaction == "fieldmajor":
                raise ValueError("-ffm_interaction fieldmajor needs the "
                                 "joint layout (-ffm_table joint, "
                                 "power-of-two -dims)")
            self._step = _ffm_step_cached(
                self._loss_name, *self._opt_key,
                (o.lambda0, o.lambda_w, o.lambda_v))
            self._step_fm = None
            self._step_fm_unit = None
            self.interaction = "pairs"
        self._pairs: set = set()       # (feature_id, field) seen, stream path
        self._fit_ds = None            # dataset ref, columnar path

    def _apply_mesh(self, spec: str) -> None:
        if getattr(self, "layout", None) == "parts":
            self._apply_mesh_parts(spec)
            return
        super()._apply_mesh(spec)

    def _apply_mesh_parts(self, spec: str) -> None:
        """Shard the parts layout over a (dp, tp) mesh: field partitions
        over 'tp' (the shard boundary is a partition boundary, so slab
        gathers stay rank-local), batch over 'dp' with a G psum before the
        optimizer tail (ops.fm_pallas.make_parts_step_sharded). The fused
        single-chip kernel remains the mesh=None path."""
        import jax
        from ..ops.fm_pallas import make_parts_step_sharded
        from ..ops.schedules import make_eta
        from ..parallel.mesh import make_mesh, parse_mesh_spec
        o = self.opts
        dp, tp = parse_mesh_spec(spec)
        if self.F % tp:
            raise ValueError(f"-ffm_table parts: -fields {self.F} must be "
                             f"divisible by the tp axis ({tp})")
        B = int(o.mini_batch)
        Bd = B // dp
        if B % (dp * 128) or (Bd > 2048 and Bd % 2048):
            raise ValueError(f"-ffm_table parts: -mini_batch "
                             f"{o.mini_batch} must be a multiple of "
                             f"128*dp ({128 * dp}) and, when the per-rank "
                             f"batch exceeds 2048, of 2048*dp — each dp "
                             "rank feeds the kernel whole chunk tiles")
        self.mesh = make_mesh(dp=dp, tp=tp)
        eta_fn = make_eta(o.eta, o.eta0, o.total_steps, o.power_t)
        lamt = (o.lambda0, o.lambda_w, o.lambda_v)
        interp = jax.default_backend() != "tpu"
        self._step_fm = make_parts_step_sharded(
            self.loss, eta_fn, lamt, self.F, self.k, self.MRF, self.mesh,
            interpret=interp)
        self._step_fm_unit = make_parts_step_sharded(
            self.loss, eta_fn, lamt, self.F, self.k, self.MRF, self.mesh,
            unit_val=True, interpret=interp)
        self._tp_sizes.add(self.F * self.MRF * self.HP)
        self._reshard_state()

    def _batch_args(self, batch: SparseBatch) -> tuple:
        if batch.field is None:
            raise ValueError("train_ffm needs field ids; use "
                             "'field:index:value' features (ffm_features)")
        return (batch.field,)

    def _preprocess_batch(self, batch: SparseBatch) -> SparseBatch:
        batch = self._canonicalize_batch(batch)
        if self.layout == "parts" and batch.fieldmajor:
            batch = self._pad_parts_rows(batch)
        return batch

    def _preprocess_train_serial(self, batch: SparseBatch):
        # FFM prep has no cross-batch state (no elision latch: unit-ness
        # is decided per batch inside _canonicalize_batch) — everything
        # runs on the parallel leg, nothing on the serial one
        return batch

    def _preprocess_train_parallel(self, batch: SparseBatch):
        # packing lives on the TRAIN hook only: scoring shares
        # _preprocess_batch and consumes .idx/.val, which a PackedBatch
        # deliberately doesn't carry. Canonicalize + pack are pure
        # per-batch NumPy (GIL-releasing) — the heavy leg the
        # -ingest_workers pool shards.
        batch = self._preprocess_batch(batch)
        if (batch.fieldmajor and batch.val is None
                and self._pack_input_on() and self._step_fm_unit is not None
                and isinstance(batch.idx, np.ndarray)
                and self.dims <= (1 << 24)):
            return pack_unit_fieldmajor(batch)
        return batch

    def _picklable_prep(self):
        # the process-pool form of the leg above: same module functions,
        # parameterized by a plain dataclass instead of bound state
        return FFMPrep(
            F=self.F, canon=self._step_fm is not None,
            forced=self.interaction == "fieldmajor",
            parts=self.layout == "parts",
            parts_dp=(self.mesh.shape["dp"] if self.mesh is not None
                      else 1),
            pack=(self._pack_input_on() and self._step_fm_unit is not None
                  and self.dims <= (1 << 24)))

    _DEVICE_CACHE_MB = 2048      # HBM budget for the -iters replay cache

    # -- the on-disk packed shard cache (-shard_cache_dir, io.shard_cache) --
    def _prep_cache_config(self) -> dict:
        """The prep-config identity the shard cache keys on: everything
        that changes the canonical packed bytes a source row preps into —
        layout geometry AND label conversion. Batch size is deliberately
        absent (the cache is row-level; any bs re-slices the same
        records)."""
        o = self.opts
        return {"trainer": self.NAME, "record": 1, "dims": self.dims,
                "fields": self.F, "layout": self.layout,
                "interaction": self.interaction,
                "classification": bool(self.classification),
                "min_target": o.min_target, "max_target": o.max_target}

    def _packed_cache(self):
        """PackedShardCache when -shard_cache_dir is set AND this config's
        prep lands on the packed wire format (the cache stores exactly
        those bytes); None otherwise — dense layout, pairs-only
        interaction, mesh/mix (pack off), or dims past the 3-byte lane
        range all decline."""
        ckdir = self.opts.get("shard_cache_dir")
        if not ckdir or self.layout == "dense":
            return None
        if self._step_fm is None or self._step_fm_unit is None:
            return None
        if not self._pack_input_on() or self.dims > (1 << 24):
            return None
        from ..io.shard_cache import PackedShardCache
        return PackedShardCache(ckdir, self._prep_cache_config(),
                                F=self.F, name=self.NAME)

    def _cache_row_pad(self, B: int) -> int:
        """Allocated row count for a cached batch of ``B`` logical rows —
        the parts layout's kernel-grid padding (single-chip rule; the
        cache is off under -mesh), identity for joint."""
        return _parts_row_target(B) if self.layout == "parts" else B

    def _streamed_epoch(self, ds, bs, shuffle, seed, prefetch, writer,
                        order) -> None:
        """One base-loop epoch (prep pipeline -> megabatch stacking ->
        prefetch -> dispatch), optionally teeing every prepared
        PackedBatch into a shard-cache writer."""
        closers: list = []
        it = self._ingest_iter(ds.batches(bs, shuffle=shuffle, seed=seed),
                               closers)
        if writer is not None:
            it = _tee_into_writer(it, writer, order, bs)
        it = self._wrap_megabatch(it, prefetch=prefetch)
        if prefetch:
            it = self._wrap_prefetch(it, closers)
        try:
            for b in it:
                self._dispatch(b)
        finally:
            for c in reversed(closers):
                c()

    def _cached_epoch(self, shard, bs, order, prefetch) -> None:
        """One epoch served from the mmap'd shard cache: parse,
        canonicalize and pack never run — record gather + h2d + step is
        the whole host leg."""
        closers: list = []
        it = shard.batches(bs, order, stats=self.pipeline_stats,
                           pad_rows=self._cache_row_pad)
        it = self._wrap_megabatch(it, prefetch=prefetch)
        if prefetch:
            it = self._wrap_prefetch(it, closers)
        try:
            for b in it:
                self._dispatch(b)
        finally:
            for c in reversed(closers):
                c()

    def _fit_epochs(self, ds, epochs, bs, shuffle, prefetch, ckdir,
                    seed0: int = 42) -> None:
        """Multi-epoch fit with TWO replay caches.

        DEVICE-RESIDENT replay (round 4): the reference's -iters pattern
        re-reads the corpus every epoch; the round-3 disk replay did too —
        and through this relay every epoch re-paid the full h2d wall. When
        the packed input path is active and the dataset fits the HBM
        budget, epoch 1 streams normally but RETAINS its staged device
        buffers; epochs >= 2 reshuffle with ONE on-device row gather
        (~26 ns/row — thousands of times cheaper than re-transferring) and
        run at near-kernel rate. Padded tail rows stay at the END of the
        replay matrix so per-batch validity remains a prefix (the packed
        step's nv-scalar contract).

        ON-DISK packed shard cache (round 6, -shard_cache_dir): the cold
        epoch additionally tees its prepared PackedBatches into a
        digest-keyed cache file; RESTARTS, repeat fits, and any epoch the
        HBM replay can't cover (over budget, -checkpoint_dir runs, CPU
        hosts) then mmap the prepared records and skip parse/canonicalize/
        pack entirely — shuffled or not, bit-exact vs the streamed path
        (warm epoch ep reuses the exact seed0+ep permutation). Both caches
        compose: a warm shard-cache epoch 1 still feeds the HBM retention
        for on-device epochs >= 2."""
        cache = self._packed_cache()
        if cache is None and (epochs <= 1 or ckdir or self.mesh is not None
                              or not self._pack_input_on()):
            return super()._fit_epochs(ds, epochs, bs, shuffle, prefetch,
                                       ckdir, seed0)
        if prefetch is None:
            prefetch = jax.default_backend() != "cpu"
        shard = writer = None
        if cache is not None:
            shard = cache.load(ds)
            if shard is None:
                writer = cache.writer(ds)   # None: uncacheable rows

        def order_for(ep):
            return (np.random.default_rng(seed0 + ep).permutation(len(ds))
                    if shuffle else np.arange(len(ds)))

        device_replay = (epochs > 1 and not ckdir and self.mesh is None
                         and self._pack_input_on())
        if not device_replay:
            # shard-cache orchestration for the configs HBM replay
            # excludes (single epoch, -checkpoint_dir): warm epochs serve
            # from the cache, the first cold epoch tees into the writer
            for ep in range(epochs):
                if shard is not None:
                    self._cached_epoch(shard, bs, order_for(ep), prefetch)
                else:
                    self._streamed_epoch(ds, bs, shuffle, seed0 + ep,
                                         prefetch,
                                         writer if ep == 0 else None,
                                         order_for(ep))
                    if ep == 0 and writer is not None:
                        shard = writer.commit()   # None: build fell open
                        writer = None
                if ckdir:
                    self._save_epoch_bundle(ckdir, ep + 1)
            return

        # ---- epoch 1: streamed (or shard-cache-served) epoch, retaining
        # staged buffers for the on-device replay of epochs >= 2 ----
        closers: list = []
        if shard is not None:
            it = shard.batches(bs, order_for(0), stats=self.pipeline_stats,
                               pad_rows=self._cache_row_pad)
        else:
            it = self._ingest_iter(
                ds.batches(bs, shuffle=shuffle, seed=seed0), closers)
            if writer is not None:
                it = _tee_into_writer(it, writer, order_for(0), bs)
        if prefetch:
            it = self._wrap_prefetch(it, closers)
        try:
            staged = self._dispatch_retaining(it)
        finally:
            for c in reversed(closers):
                c()
        if writer is not None:
            shard = writer.commit()
        mat = self._staged_matrix(staged)
        del staged           # free the per-batch buffers BEFORE replay:
        # peak device memory stays ~M (+Mp), not M + the staged copies
        if mat is None:
            # HBM replay unsafe or over budget: warm shard-cache epochs
            # when available (exactly the -iters-over-budget case the disk
            # cache exists for), else re-stream on the uninterrupted
            # seed schedule
            for ep in range(1, epochs):
                if shard is not None:
                    self._cached_epoch(shard, bs, order_for(ep), prefetch)
                else:
                    super()._fit_epochs(ds, 1, bs, shuffle, prefetch, None,
                                        seed0=seed0 + ep)
            return
        if mat == ():
            return                       # empty dataset, nothing to replay
        self._replay_epochs(mat, epochs - 1, shuffle)

    def _dispatch_retaining(self, it) -> Optional[list]:
        """Dispatch every batch from `it`, retaining PackedBatches for
        on-device replay. Returns the staged list, or None when replay is
        unsafe: an unpacked batch appeared, or the cumulative staged
        bytes exceeded the admission budget (budget/3 of
        _DEVICE_CACHE_MB: construction transiently holds the staged
        buffers + the rows_m copies + M, and shuffled epochs hold M + Mp
        — the cap bounds the PEAK, not just M)."""
        budget = (self._DEVICE_CACHE_MB << 20) // 3
        staged: list = []
        cache_on = True
        cached_bytes = 0
        for b in it:
            if cache_on and isinstance(b, PackedBatch):
                cached_bytes += int(b.buf.size)
                if cached_bytes > budget:
                    # over budget mid-epoch: free the cache NOW (the
                    # streamed path never retains buffers) and finish
                    # the epoch + remaining epochs streamed
                    staged.clear()
                    cache_on = False
                else:
                    staged.append(b)
            elif cache_on:
                # a batch failed the pack conditions: replay unsafe
                staged.clear()
                cache_on = False
            self._dispatch(b)
        return staged if cache_on else None

    def _staged_matrix(self, staged):
        """Collapse retained PackedBatches into the replay matrix.
        Returns (M, n_real, B, L), () for an empty epoch, or None when
        replay is unsafe (mixed shapes / staged is None).

        Rows matrix has REAL rows first, padding rows last (prefix
        validity per tail batch); idx bytes and label bytes re-packed
        row-major so a row gather moves one contiguous 3L+4 record."""
        if staged is None:
            return None
        if not staged:
            return ()
        B, L = staged[0].B, staged[0].L
        if any(s.B != B or s.L != L for s in staged):
            return None
        mats = []
        n_real = 0
        pad_rows = []
        for s in staged:
            nv = s.B if s.n_valid is None else s.n_valid
            ni = s.B * L * 3
            rows_m = jnp.concatenate(
                [s.buf[:ni].reshape(s.B, L * 3),
                 s.buf[ni:].reshape(s.B, 4)], axis=1)     # [B, rb]
            mats.append(rows_m[:nv])
            n_real += nv
            if nv < s.B:
                pad_rows.append(rows_m[nv:])
        M = jnp.concatenate(mats + pad_rows)              # [N_total, rb]
        return (M, n_real, B, L)

    def _replay_epochs(self, mat, n_epochs: int, shuffle: bool,
                       seed: int = 43) -> None:
        """Run `n_epochs` epochs from the device-resident replay matrix:
        per epoch ONE on-device row gather (~26 ns/row) reshuffles; no
        bytes re-cross the link."""
        M, n_real, B, L = mat
        n_total = M.shape[0]
        rng = np.random.default_rng(seed)
        for ep in range(n_epochs):
            if shuffle:
                perm = rng.permutation(n_real)
                if n_total > n_real:
                    perm = np.concatenate(
                        [perm, np.arange(n_real, n_total)])
                Mp = M[jnp.asarray(perm.astype(np.int32))]
            else:
                Mp = M
            for s0 in range(0, n_total, B):
                rows_b = Mp[s0:s0 + B]
                buf = jnp.concatenate(
                    [rows_b[:, :L * 3].reshape(-1),
                     rows_b[:, L * 3:].reshape(-1)])
                nv = min(B, max(0, n_real - s0))
                if nv == 0:
                    break
                self._dispatch(PackedBatch(buf, B, L, n_valid=nv))

    def fit_stream(self, batches, *, convert_labels: bool = True,
                   epochs: int = 1, replay_shuffle: bool = True,
                   resume: bool = False) -> "FFMTrainer":
        """Out-of-core epochs with the device replay cache (VERDICT r4
        weak #5: -iters over Parquet re-paid the link every epoch).

        `batches` may be an iterable (single epoch, base behavior) or a
        zero-arg FACTORY returning one epoch's stream — with epochs > 1
        the factory form lets failed replay fall open to re-streaming.
        When the packed input path is active and the epoch fits the HBM
        budget, epoch 1 streams normally while RETAINING its staged
        device buffers; epochs >= 2 replay on device exactly like
        fit(-iters) does (same admission, same fail-open).

        ``resume`` (docs/RELIABILITY.md) is the base single-stream
        contract; the multi-epoch replay form has no checkpointed stream
        position to skip into, so the combination is rejected."""
        if epochs <= 1:
            it = batches() if callable(batches) else batches
            return super().fit_stream(it, convert_labels=convert_labels,
                                      resume=resume)
        if resume:
            raise ValueError(
                "fit_stream(resume=True) needs the single-stream form "
                "(epochs=1); the epochs>1 replay path has no stream "
                "position to resume into")
        if not callable(batches):
            raise ValueError(
                "fit_stream(epochs>1) needs a zero-arg factory returning "
                "one epoch's batch stream, e.g. "
                "lambda: stream.batches(B, epochs=1)")
        if self.mesh is not None or not self._pack_input_on():
            for _ in range(epochs):
                super().fit_stream(batches(),
                                   convert_labels=convert_labels,
                                   _emit_done=False)
            self._emit_train_done()    # ONE record for the whole run
            return self

        def host_side():
            for b in batches():
                if convert_labels:
                    b = SparseBatch(b.idx, b.val,
                                    self._convert_labels(b.label),
                                    b.field, n_valid=b.n_valid,
                                    fieldmajor=b.fieldmajor)
                # ingest-side stats over HOST arrays (np.asarray of
                # already-host data) — no device sync happens here
                # graftcheck: disable=GC07
                self._note_batch(b)
                yield b

        from ..io.pipeline import PipelineStats
        self.pipeline_stats = PipelineStats()
        closers: list = []
        it = self._ingest_iter(host_side(), closers)
        prefetch = jax.default_backend() != "cpu"
        if prefetch:
            it = self._wrap_prefetch(it, closers)
        try:
            staged = self._dispatch_retaining(it)
        finally:
            for c in reversed(closers):
                c()
        mat = self._staged_matrix(staged)
        del staged           # peak device memory ~M (+Mp), not M + copies
        if mat == ():
            self._emit_train_done()
            return self
        if mat is None:                      # fail-open: re-stream
            for _ in range(epochs - 1):
                super().fit_stream(batches(),
                                   convert_labels=convert_labels,
                                   _emit_done=False)
            self._emit_train_done()
            return self
        self._replay_epochs(mat, epochs - 1, replay_shuffle)
        # the packed replay path never re-enters base fit_stream after
        # epoch 1, so the run's single train_done is emitted here
        self._emit_train_done()
        return self

    def _pack_input_on(self) -> bool:
        # the mesh/mixer exclusions outrank an explicit "on": _shard_batch
        # and MixClient.touch consume .idx, which packed buffers don't have
        if self.mesh is not None or self._mixer is not None:
            return False
        mode = str(self.opts.pack_input)
        if mode == "on":
            return True
        if mode == "off":
            return False
        import jax
        return jax.default_backend() != "cpu"

    def _packed_step(self, B: int, L: int):
        # module-cached on (base step, B, L): the base steps are
        # themselves config-cached, so same-config trainers share the
        # packed wrapper's compile too (an instance-keyed dict here undid
        # the cross-instance sharing on the flagship packed path)
        return _packed_wrap_cached(self._step_fm_unit, B, L)

    def _pad_parts_rows(self, batch: SparseBatch) -> SparseBatch:
        """Parts-layout kernel-grid row padding (see _ffm_pad_parts — the
        module function is the single implementation, shared with the
        picklable process-pool prep)."""
        return _ffm_pad_parts(
            batch, self.mesh.shape["dp"] if self.mesh is not None else 1)

    def _canonicalize_batch(self, batch: SparseBatch) -> SparseBatch:
        """Field-major canonicalization (see _ffm_canonicalize — the
        module function is the single implementation, shared with the
        picklable process-pool prep)."""
        return _ffm_canonicalize(batch, self.F, self._step_fm is not None,
                                 self.interaction == "fieldmajor")

    # -- fused multi-step dispatch (-steps_per_dispatch) ---------------------
    def _supports_megastep(self) -> bool:
        # the FFM dispatch picks among THREE steps per batch kind (pairs /
        # fieldmajor / fieldmajor-unit+packed); fusion is on when any of
        # them is scannable — a window of a non-scannable kind (only
        # possible under the mesh-sharded parts steps, which also null
        # self._step) simply never forms. parts layout keeps
        # self._step = None, so the base check alone would disable the
        # flagship path.
        return any(
            getattr(s, "core", None) is not None
            for s in (self._step, self._step_fm, self._step_fm_unit))

    def _mega_field(self, mb):
        # pairs-path megabatches carry stacked per-step field arrays; the
        # pairs core takes them as its trailing batch argument
        return mb.field

    def _train_megabatch(self, mb):
        """Route one stacked window to the megastep of the SAME step the
        K=1 dispatch would pick for its kind: PackedMegaBatch -> the
        packed scan wrapper over the unit-val field-major core (one uint8
        buffer, per-step unpack on device); field-major MegaBatch -> the
        field-major (unit or real-valued) core; anything else -> the base
        generic megastep over the pairs core."""
        from ..io.sparse import PackedMegaBatch
        from ..ops.scan import megastep_for
        if isinstance(mb, PackedMegaBatch):
            nv = (mb.nv_dev if mb.nv_dev is not None
                  else jnp.asarray(mb.nv))
            mega = _packed_megawrap_cached(self._step_fm_unit, mb.B, mb.L)
            self.params, self.opt_state, losses = mega(
                self.params, self.opt_state, float(self._t), mb.buf, nv)
            return losses
        if mb.fieldmajor and self._step_fm is not None:
            step = self._step_fm_unit if mb.val is None else self._step_fm
            mega = megastep_for(step)
            nv = (mb.nv_dev if mb.nv_dev is not None
                  else jnp.asarray(mb.nv))
            self.params, self.opt_state, losses = mega(
                self.params, self.opt_state, float(self._t), nv, mb.idx,
                mb.val, mb.label, None, None)
            return losses
        return super()._train_megabatch(mb)

    def _train_batch(self, batch: SparseBatch) -> float:
        if isinstance(batch, PackedBatch):
            nv = batch.B if batch.n_valid is None else batch.n_valid
            self.params, self.opt_state, loss_sum = self._packed_step(
                batch.B, batch.L)(self.params, self.opt_state,
                                  float(self._t), batch.buf, np.int32(nv))
            return loss_sum
        if batch.fieldmajor and self._step_fm is not None:
            if batch.val is None:
                self.params, self.opt_state, loss_sum = self._step_fm_unit(
                    self.params, self.opt_state, float(self._t), batch.idx,
                    batch.label, batch.row_mask)
            else:
                self.params, self.opt_state, loss_sum = self._step_fm(
                    self.params, self.opt_state, float(self._t), batch.idx,
                    batch.val, batch.label, batch.row_mask)
            return loss_sum
        return super()._train_batch(batch)

    def _parse_row(self, features):
        """Parse "field:index:value" (value defaults to 1)."""
        if (isinstance(features, tuple) and len(features) == 3):
            return features           # (idx, val, field) pre-parsed
        idx: List[int] = []
        val: List[float] = []
        fld: List[int] = []
        for f in features:
            if f is None or f == "":
                continue
            parts = str(f).split(":")
            if len(parts) == 2:
                fstr, istr, vstr = parts[0], parts[1], "1"
            elif len(parts) >= 3:
                fstr, istr, vstr = parts[0], parts[1], ":".join(parts[2:])
            else:
                raise ValueError(f"FFM feature needs field:index[:value]: {f!r}")
            try:
                fi = int(fstr)
            except ValueError:
                fi = mhash(fstr, self.F) - 1
            try:
                ii = int(istr)
            except ValueError:
                ii = mhash(istr, self.dims - 1)
                self._names.setdefault(ii, istr)
            idx.append(ii)
            val.append(float(vstr))
            fld.append(fi % self.F)
        return (np.asarray(idx, np.int32), np.asarray(val, np.float32),
                np.asarray(fld, np.int32))

    def process(self, features, label) -> None:
        idx, val, fld = self._parse_row(features)
        self._buf_rows.append((idx, val, fld))
        self._buf_labels.append(self._convert_label(label))
        if len(self._buf_rows) >= int(self.opts.mini_batch):
            self._flush()

    def _flush_chunk(self, rows, labels) -> None:
        B = int(self.opts.mini_batch)
        L = self._pow2_len(max(1, max(len(r[0]) for r in rows)))
        idx = np.zeros((B, L), np.int32)
        val = np.zeros((B, L), np.float32)
        fld = np.zeros((B, L), np.int32)
        lab = np.zeros(B, np.float32)
        for b, (i, v, f) in enumerate(rows):
            idx[b, :len(i)] = i
            val[b, :len(v)] = v
            fld[b, :len(f)] = f
            lab[b] = labels[b]
            if self.layout == "joint":     # joint emission needs seen pairs
                self._pairs.update(zip(i.tolist(), f.tolist()))
        nv = len(rows)
        self._dispatch(self._preprocess_batch(
            SparseBatch(idx, val, lab, fld, n_valid=nv if nv < B else None)))

    def _score_batch(self, batch: SparseBatch) -> np.ndarray:
        p = self.params
        if self.layout == "parts":
            B0 = batch.batch_size
            if not batch.fieldmajor:
                batch = self._preprocess_batch(batch)   # forced; may raise
            out = np.asarray(self._fused_score_fm(
                p["w0"], p["T2"], jnp.asarray(batch.idx),
                None if batch.val is None else jnp.asarray(batch.val)))
            return out[:B0]            # drop kernel-grid padding rows
        if self.layout == "joint":
            if not batch.fieldmajor and self._step_fm is not None:
                # scoring fast path; unlike training, a row canonicalization
                # cannot handle (forced mode raises) just keeps the general
                # pairs scorer — prediction must accept any row
                try:
                    batch = self._preprocess_batch(batch)
                except ValueError:
                    pass
            if batch.fieldmajor:
                return np.asarray(self._fused_score_fm(
                    p["w0"], p["T"], jnp.asarray(batch.idx),
                    None if batch.val is None else jnp.asarray(batch.val)))
            return np.asarray(self._fused_score(
                p["w0"], p["T"], jnp.asarray(batch.idx),
                jnp.asarray(batch.val), jnp.asarray(batch.field)))
        return np.asarray(ffm_score(p["w0"], p["w"], p["V"],
                                    batch.idx, batch.val, batch.field))

    def _init_parser(self) -> None:
        # make_parser support: FFM's _parse_row hashes field names mod F
        self.F = int(self.opts.fields)

    def serving_tables(self):
        """Arena extraction (io.weight_arena): joint keeps the fused
        row-hashed table (V block + the linear-weight column, pad lanes
        dropped); dense flattens the field cube to the pair-flat [N*F, K]
        the general scorer gathers. The ``parts`` layout's kernel-grid
        geometry has no host-gather mapping — unsupported (the engine
        keeps the bundle path; docs/PERFORMANCE.md "when NOT to
        quantize")."""
        from ..io.weight_arena import ArenaUnsupported
        p = self.params
        cls = bool(self.classification)
        w0 = float(np.asarray(p["w0"], np.float32))
        if self.layout == "joint":
            T = np.asarray(p["T"].astype(jnp.float32))
            return ({"family": "ffm_joint", "F": self.F, "k": self.k,
                     "Mr": int(T.shape[0]), "w0": w0,
                     "classification": cls},
                    {"T": np.ascontiguousarray(
                        T[:, :self.F * self.k + 1])})
        if self.layout == "dense":
            V = np.asarray(p["V"].astype(jnp.float32))
            return ({"family": "ffm_dense", "F": self.F, "k": self.k,
                     "w0": w0, "classification": cls},
                    {"w": np.asarray(p["w"].astype(jnp.float32)),
                     "V2": np.ascontiguousarray(
                         V.reshape(-1, self.k))})
        raise ArenaUnsupported(
            f"-ffm_table {self.layout} has no weight-arena mapping")

    def _wants_fit_ds(self) -> bool:
        # emission needs observed pairs
        return self.layout in ("joint", "parts")

    def _note_batch(self, batch) -> None:
        """Streaming path (fit_stream): record observed (feature, field)
        pairs so joint-layout model emission keeps names/fields."""
        if self.layout not in ("joint", "parts") or batch.field is None:
            return
        idx = np.asarray(batch.idx)
        fld = np.asarray(batch.field)
        val = np.asarray(batch.val)
        live = val != 0
        packed = np.unique(idx[live].astype(np.int64) * self.F
                           + fld[live].astype(np.int64))
        ii, ff = np.divmod(packed, self.F)
        self._pairs.update(zip(ii.tolist(), ff.tolist()))

    def _observed_pairs(self):
        """Unique (feature_id, field) pairs seen in training as two sorted
        arrays (ii, ff), merged from the streaming path's tracked set and
        the columnar dataset — all vectorized (no per-pair Python)."""
        keys = []
        if self._pairs:
            arr = np.fromiter((i * self.F + f for i, f in self._pairs),
                              np.int64, len(self._pairs))
            keys.append(arr)
        ds = self._fit_ds
        if ds is not None and ds.fields is not None:
            keys.append(ds.indices.astype(np.int64) * self.F
                        + ds.fields.astype(np.int64))
        if not keys:
            return None
        uniq = np.unique(np.concatenate(keys))
        ii, ff = np.divmod(uniq, self.F)
        return ii.astype(np.int32), ff.astype(np.int32)

    def _rows_for(self, keys: np.ndarray, fields: np.ndarray = None
                  ) -> np.ndarray:
        """Host-side fused-table row ids for feature ids (joint layout) or
        (feature, own-field) pairs (parts layout)."""
        if self.layout == "parts":
            from ..ops.fm_pallas import parts_row_hash
            return np.asarray(parts_row_hash(
                jnp.asarray(keys, jnp.int32),
                jnp.asarray(fields, jnp.int32), self.MRF))
        return np.asarray(ffm_row_hash(jnp.asarray(keys, jnp.int32),
                                       self.Mr))

    def model_rows(self):
        """(feature, field, Wi, Vi[k]) rows — the FFMPredictionModel surface.

        Joint layout: rows are enumerated from the observed (feature, field)
        pairs; each feature's weight and per-field vectors are read from its
        hashed fused row. Colliding features intentionally report the same
        shared state (hashing-trick semantics). If no pairs were observed
        (e.g. a bundle-restored trainer that never saw data), falls back to
        row-keyed "vrow:<id>:<field>" rows."""
        yield ("0", -1, float(np.asarray(self.params["w0"])), None)
        if self.layout == "dense":
            w = np.asarray(self.params["w"].astype(jnp.float32))
            V = np.asarray(self.params["V"].astype(jnp.float32))
            touched = np.nonzero(np.abs(V).sum((1, 2)) > 0)[0]
            for i in touched:
                if i == 0:
                    continue
                name = self._names.get(int(i), str(int(i)))
                for f in range(self.F):
                    if np.abs(V[i, f]).sum() > 0:
                        yield (name, f, float(w[i]), V[i, f].tolist())
            return
        FK = self.F * self.k
        if self.layout == "parts":
            T = np.asarray(self.params["T2"].astype(jnp.float32)).reshape(
                self.F * self.MRF, self.Wp)
        else:
            T = np.asarray(self.params["T"].astype(jnp.float32))
        pairs = self._observed_pairs()
        if pairs is None:
            live = np.nonzero(np.abs(T[:, :FK]).sum(-1) > 0)[0]
            for r in live:
                for f in range(self.F):
                    vec = T[r, f * self.k:(f + 1) * self.k]
                    if np.abs(vec).sum() > 0:
                        yield (f"vrow:{int(r)}", f, float(T[r, FK]),
                               vec.tolist())
            return
        ii, ff = pairs
        rr = self._rows_for(ii, ff)
        for i, f, r in zip(ii.tolist(), ff.tolist(), rr.tolist()):
            if i == 0:
                continue
            name = self._names.get(i, str(i))
            yield (name, f, float(T[r, FK]),
                   T[r, f * self.k:(f + 1) * self.k].tolist())

    # -- sparse weight access for the mix client (joint layout) -------------
    def _weight_table(self):
        if self.layout in ("joint", "parts"):
            return None                # w lives inside T; use overrides
        return super()._weight_table()

    def _get_weights_at(self, keys: np.ndarray) -> np.ndarray:
        if self.layout == "parts":
            raise ValueError("MIX weight exchange is not supported with "
                             "-ffm_table parts; use -ffm_table joint")
        if self.layout != "joint":
            return super()._get_weights_at(keys)
        FK = self.F * self.k
        rr = jnp.asarray(self._rows_for(np.asarray(keys)))
        return np.asarray(self.params["T"][rr, FK], np.float32)

    def _set_weights_at(self, keys: np.ndarray, vals: np.ndarray) -> None:
        if self.layout == "parts":
            raise ValueError("MIX weight exchange is not supported with "
                             "-ffm_table parts; use -ffm_table joint")
        if self.layout != "joint":
            return super()._set_weights_at(keys, vals)
        FK = self.F * self.k
        rr = jnp.asarray(self._rows_for(np.asarray(keys)))
        T = self.params["T"]
        self.params["T"] = T.at[rr, FK].set(jnp.asarray(vals, T.dtype))

    def _finalized_weights(self) -> np.ndarray:
        if self.layout == "parts":
            FK = self.F * self.k
            Tl = self.params["T2"].reshape(self.F * self.MRF, self.Wp)
            return np.asarray(Tl[:, FK].astype(jnp.float32))
        if self.layout != "joint":
            return super()._finalized_weights()
        FK = self.F * self.k
        return np.asarray(self.params["T"][:, FK].astype(jnp.float32))

    def _load_weights(self, w: np.ndarray) -> None:
        if self.layout == "parts":
            FK = self.F * self.k
            T2 = self.params["T2"]
            Tl = T2.reshape(self.F * self.MRF, self.Wp)
            Tl = Tl.at[:, FK].set(jnp.asarray(w, T2.dtype))
            self.params["T2"] = Tl.reshape(T2.shape)
            return
        if self.layout != "joint":
            return super()._load_weights(w)
        FK = self.F * self.k
        T = self.params["T"]
        self.params["T"] = T.at[:, FK].set(jnp.asarray(w, T.dtype))


# --- standalone predict kernels (the UDAF/UDF reassembly path) -------------

def fm_predict(w0, w, V, idx, val) -> np.ndarray:
    """SQL: fm_predict — reference hivemall.fm.FMPredictGenericUDAF."""
    return np.asarray(fm_score(jnp.asarray(w0), jnp.asarray(w),
                               jnp.asarray(V), jnp.asarray(idx),
                               jnp.asarray(val)))


def ffm_predict(w0, w, V, idx, val, field) -> np.ndarray:
    """SQL: ffm_predict — reference hivemall.fm.FFMPredictUDF."""
    return np.asarray(ffm_score(jnp.asarray(w0), jnp.asarray(w),
                                jnp.asarray(V), jnp.asarray(idx),
                                jnp.asarray(val), jnp.asarray(field)))
