"""train_word2vec — SkipGram/CBOW with negative sampling (BASELINE config #4).

Reference (SURVEY.md §3.8): the late-incubator hivemall embedding package's
Word2VecUDTF: consume tokenized documents, build a vocabulary + unigram^0.75
negative-sampling table, and train SkipGram (default) or CBOW embeddings.

TPU shape: training pairs are generated host-side into fixed-shape arrays
(center[B], context[B], negatives[B, neg]); one jitted step does the
logistic pos/neg dot products and scatter-adds into the in/out embedding
tables — the whole O(B * neg * dim) update is a handful of fused einsums,
instead of the reference's per-pair scalar loops. Linear LR decay matches
word2vec.c / the reference.

Pair generation is fully vectorized (numpy, no per-token Python): dynamic
windows draw one width per position, then each window offset delta becomes
two array-slice selections (left/right context) over the whole document —
2*win vector ops per doc instead of O(tokens * window) scalar work. This
keeps the host side >=10M pairs/sec so text8-scale training is TPU-bound,
not input-bound.
"""

from __future__ import annotations

from collections import Counter
from functools import partial
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.options import OptionSpec

__all__ = ["Word2VecTrainer"]


class Word2VecTrainer:
    """SQL: train_word2vec(words[, options]) — UDTF over tokenized docs."""

    NAME = "train_word2vec"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = OptionSpec(cls.NAME)
        s.add("dim", "size", type=int, default=100, help="embedding dim")
        s.add("window", "win", type=int, default=5, help="context window")
        s.add("neg", "negative", type=int, default=5,
              help="negative samples per pair")
        s.add("iters", "iterations", type=int, default=1, help="epochs")
        s.add("min_count", type=int, default=5, help="vocab frequency floor")
        s.add("alpha", "lr", type=float, default=0.25,
              help="initial learning rate, linearly decayed. NOTE: applies "
                   "to the batch-MEAN pair loss, so it sits ~10x above "
                   "word2vec.c's per-pair 0.025 for equivalent pacing")
        s.add("sample", type=float, default=1e-4,
              help="frequent-word subsampling threshold (0 = off)")
        s.add("mini_batch", type=int, default=2048,
              help="pairs per step. NOTE: the loss is a batch MEAN, so "
                   "total per-epoch movement scales with alpha/mini_batch "
                   "— raise alpha when raising this")
        s.add("seed", type=int, default=11, help="rng seed")
        s.flag("cbow", help="CBOW instead of SkipGram")
        s.add("mesh", default=None,
              help="shard training over a device mesh, e.g. 'dp=2,tp=4' "
                   "(pair batches over dp, embedding tables over tp)")
        return s

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self._docs: List[List[str]] = []
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: List[str] = []
        self.in_emb: Optional[jnp.ndarray] = None
        self.out_emb: Optional[jnp.ndarray] = None
        self.mesh = None
        if self.opts.mesh:
            from ..parallel.mesh import make_mesh, parse_mesh_spec
            dp, tp = parse_mesh_spec(str(self.opts.mesh))
            if int(self.opts.mini_batch) % dp:
                raise ValueError(
                    f"-mini_batch {self.opts.mini_batch} must be divisible "
                    f"by the dp axis ({dp})")
            self.mesh = make_mesh(dp=dp, tp=tp)

    # -- UDTF lifecycle ------------------------------------------------------
    def process(self, words: Sequence[str]) -> None:
        self._docs.append([str(w) for w in words if w])

    def close(self) -> Iterator[Tuple[str, List[float]]]:
        self.train(self._docs)
        yield from self.model_rows()

    # -- training ------------------------------------------------------------
    def _build_vocab(self, docs: Sequence[Sequence[str]]) -> np.ndarray:
        counts = Counter(w for d in docs for w in d)
        kept = [(w, c) for w, c in counts.most_common()
                if c >= int(self.opts.min_count)]
        self.vocab = {w: i for i, (w, _) in enumerate(kept)}
        self.inv_vocab = [w for w, _ in kept]
        freqs = np.asarray([c for _, c in kept], np.float64)
        return freqs

    def _neg_table(self, freqs: np.ndarray, size: int = 1 << 20) -> np.ndarray:
        """Unigram^0.75 sampling table (word2vec.c style)."""
        p = freqs ** 0.75
        p /= p.sum()
        return np.repeat(np.arange(len(freqs)),
                         np.maximum(1, np.round(p * size).astype(np.int64))
                         ).astype(np.int32)

    def _make_step(self, cbow: bool, vocab_size: int, dim: int):
        neg = int(self.opts.neg)
        # Two update variants, chosen by table size (measured on v5e):
        #   dense  — autodiff over the whole (in, out) tables; the SGD
        #            update is two fused elementwise passes. Fastest while
        #            V*D stays a few MB (text8-class vocabularies).
        #   sparse — slab-level autodiff + scatter-add of touched rows
        #            only (the ops.fm.make_ffm_step_fused principle). At
        #            enwiki scale (V ~ 1M) the dense variant would move
        #            100s of MB of table per step for a few thousand
        #            touched rows.
        # Both variants draw NEGATIVES ON DEVICE from the staged unigram^.75
        # table (word2vec.c's table sampling, jax PRNG keyed by the step
        # counter) and rebuild the pair mask from the valid-count scalar:
        # per-step h2d drops from 4 arrays (~520 KB at B=16k) to the two
        # id arrays — the dispatch link is the e2e bottleneck here.
        if vocab_size * dim <= (1 << 23):
            return self._make_step_dense(cbow)

        seed = int(self.opts.seed)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(in_emb, out_emb, ntab, center, context, nvalid, t, lr):
            # SkipGram: v_in = in[center]; target = context
            # CBOW: v_in = mean(in[context window]) handled by caller passing
            #       the window in `center` as [B, 2w] with -1 padding
            B = context.shape[0]
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            negs = ntab[jax.random.randint(key, (B, neg), 0, ntab.shape[0])]
            row_mask = (jnp.arange(B) < nvalid).astype(jnp.float32)
            if cbow:
                cmask = (center >= 0).astype(jnp.float32)
                cids = jnp.maximum(center, 0)
                vin_slab = in_emb[cids]                      # [B, 2w, D]
            else:
                vin_slab = in_emb[center]                    # [B, D]
            pos_slab = out_emb[context]                      # [B, D]
            neg_slab = out_emb[negs]                         # [B, neg, D]

            def batch_loss(vin, op, on):
                if cbow:
                    v = (vin * cmask[..., None]).sum(1) / jnp.maximum(
                        cmask.sum(1, keepdims=True), 1.0)
                else:
                    v = vin
                pos = (v * op).sum(-1)
                negd = jnp.einsum("bd,bnd->bn", v, on)
                per_pair = (jax.nn.softplus(-pos)
                            + jax.nn.softplus(negd).sum(-1)) * row_mask
                # mean over valid pairs: per-word effective step stays O(lr)
                # even when one word recurs many times in a batch (the
                # batched analog of word2vec.c's sequential per-pair steps)
                return per_pair.sum() / jnp.maximum(row_mask.sum(), 1.0)

            loss, (gv, gp, gn) = jax.value_and_grad(
                batch_loss, argnums=(0, 1, 2))(vin_slab, pos_slab, neg_slab)
            D = in_emb.shape[1]
            if cbow:
                ie = in_emb.at[cids.reshape(-1)].add(
                    (-lr * gv).reshape(-1, D))
            else:
                ie = in_emb.at[center].add(-lr * gv)
            oe = out_emb.at[context].add(-lr * gp)
            oe = oe.at[negs.reshape(-1)].add((-lr * gn).reshape(-1, D))
            return ie, oe, loss

        return step

    def _make_step_dense(self, cbow: bool):
        neg = int(self.opts.neg)

        seed = int(self.opts.seed)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(in_emb, out_emb, ntab, center, context, nvalid, t, lr):
            B = context.shape[0]
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            negs = ntab[jax.random.randint(key, (B, neg), 0, ntab.shape[0])]
            row_mask = (jnp.arange(B) < nvalid).astype(jnp.float32)

            def batch_loss(tables):
                ie, oe = tables
                if cbow:
                    mask = (center >= 0).astype(jnp.float32)
                    v = (ie[jnp.maximum(center, 0)] *
                         mask[..., None]).sum(1) / jnp.maximum(
                             mask.sum(1, keepdims=True), 1.0)
                else:
                    v = ie[center]
                pos = (v * oe[context]).sum(-1)
                negd = jnp.einsum("bd,bnd->bn", v, oe[negs])
                per_pair = (jax.nn.softplus(-pos)
                            + jax.nn.softplus(negd).sum(-1)) * row_mask
                # mean over valid pairs: per-word effective step stays O(lr)
                # even when one word recurs many times in a batch (the
                # batched analog of word2vec.c's sequential per-pair steps)
                return per_pair.sum() / jnp.maximum(row_mask.sum(), 1.0)

            loss, grads = jax.value_and_grad(batch_loss)((in_emb, out_emb))
            return (in_emb - lr * grads[0], out_emb - lr * grads[1], loss)

        return step

    @staticmethod
    def _skipgram_pairs(d: np.ndarray, win: int, rng) -> Tuple[np.ndarray,
                                                               np.ndarray]:
        """Vectorized SkipGram (center, context) pairs for one doc.

        Dynamic windows as in word2vec.c: each position draws a width
        w in [1, win]; (pos, pos±delta) is a pair iff delta <= w[pos].
        2*win slice-selections replace the per-token Python loop."""
        n = len(d)
        if n < 2:
            return (np.zeros(0, np.int32),) * 2
        w = rng.integers(1, win + 1, n, dtype=np.uint8)
        cs: List[np.ndarray] = []
        xs: List[np.ndarray] = []
        for delta in range(1, win + 1):
            pos = np.flatnonzero(w >= delta)   # centers wide enough for delta
            right = pos[pos < n - delta]       # (pos, pos+delta)
            cs.append(d[right])
            xs.append(d[right + delta])
            left = pos[pos >= delta]           # (pos, pos-delta)
            cs.append(d[left])
            xs.append(d[left - delta])
        return np.concatenate(cs), np.concatenate(xs)

    @staticmethod
    def _cbow_windows(d: np.ndarray, win: int, rng) -> Tuple[np.ndarray,
                                                             np.ndarray]:
        """Vectorized CBOW windows: rows [n, 2*win] of context ids (-1 pad)
        plus the center target, dynamic widths per position."""
        n = len(d)
        if n < 2:
            return np.zeros((0, 2 * win), np.int32), np.zeros(0, np.int32)
        w = rng.integers(1, win + 1, n)
        ctx = np.full((n, 2 * win), -1, np.int32)
        for delta in range(1, win + 1):
            keep = w >= delta
            col_r, col_l = 2 * (delta - 1), 2 * (delta - 1) + 1
            # right neighbor pos+delta feeds center pos
            sel = keep[:n - delta]
            ctx[:n - delta, col_r] = np.where(sel, d[delta:], -1)
            # left neighbor pos-delta feeds center pos
            sel = keep[delta:]
            ctx[delta:, col_l] = np.where(sel, d[:n - delta], -1)
        has_ctx = (ctx >= 0).any(1)
        return ctx[has_ctx], d[has_ctx]

    def train(self, docs: Sequence[Sequence[str]]) -> "Word2VecTrainer":
        o = self.opts
        freqs = self._build_vocab(docs)
        V, D = len(self.vocab), int(o.dim)
        if V == 0:
            raise ValueError("empty vocabulary (check -min_count)")
        rng = np.random.default_rng(int(o.seed))
        key = jax.random.PRNGKey(int(o.seed))
        Vp = V
        if self.mesh is not None:     # pad vocab rows to the tp axis size
            tp = self.mesh.shape["tp"]
            Vp = -(-V // tp) * tp     # extra rows are never gathered
        self.in_emb = (jax.random.uniform(key, (Vp, D)) - 0.5) / D
        self.out_emb = jnp.zeros((Vp, D))
        table = jnp.asarray(self._neg_table(freqs))   # staged on device once
        if self.mesh is not None:
            # vocab rows over tp, negative table replicated, batches over dp
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(self.mesh, P("tp", None))
            self.in_emb = jax.device_put(self.in_emb, sh)
            self.out_emb = jax.device_put(self.out_emb, sh)
            table = jax.device_put(table, NamedSharding(self.mesh, P()))
        ids_docs =[np.asarray([self.vocab[w] for w in d if w in self.vocab],
                               np.int32) for d in docs]
        total = sum(len(d) for d in ids_docs)
        # frequent-word subsampling probabilities (word2vec.c formula)
        sample = float(o.sample)
        if sample > 0:
            f = freqs / max(1, total)
            keep_p = np.minimum(1.0, np.sqrt(sample / f) + sample / f)
        else:
            keep_p = np.ones(V)

        cbow = bool(o.cbow)
        step = self._make_step(cbow, V, D)
        win = int(o.window)
        B = int(o.mini_batch)
        neg = int(o.neg)
        alpha = float(o.alpha)
        epochs = int(o.iters)

        # pending vectorized pair chunks awaiting dispatch
        pend_c: List[np.ndarray] = []
        pend_x: List[np.ndarray] = []
        pending = 0

        nstep = 0

        def dispatch(c: np.ndarray, x: np.ndarray, progress: float) -> None:
            """One fixed-shape [B] (or [B, 2w]) step; short batches pad.
            Only the two id arrays cross host->device; negatives and the
            pair mask are built on device (see _make_step)."""
            nonlocal nstep
            nb = len(x)
            if nb == 0:
                return
            if nb < B:
                pad = B - nb
                c = np.concatenate(
                    [c, np.full((pad,) + c.shape[1:],
                                -1 if cbow else 0, np.int32)])
                x = np.concatenate([x, np.zeros(pad, np.int32)])
            lr = max(alpha * (1.0 - progress), alpha * 1e-4)
            nstep += 1
            cd, xd = jnp.asarray(c), jnp.asarray(x)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                cd = jax.device_put(cd, NamedSharding(
                    self.mesh, P("dp", *([None] * (cd.ndim - 1)))))
                xd = jax.device_put(xd, NamedSharding(self.mesh, P("dp")))
            self.in_emb, self.out_emb, _ = step(
                self.in_emb, self.out_emb, table, cd, xd, nb, nstep, lr)

        def drain(progress: float, final: bool = False) -> None:
            nonlocal pend_c, pend_x, pending
            if pending >= B or (final and pending):
                c = np.concatenate(pend_c)
                x = np.concatenate(pend_x)
                nfull = (len(x) // B) * B
                for s in range(0, nfull, B):
                    dispatch(c[s:s + B], x[s:s + B], progress)
                if final and nfull < len(x):
                    dispatch(c[nfull:], x[nfull:], progress)
                    pend_c, pend_x, pending = [], [], 0
                else:
                    pend_c = [c[nfull:]]
                    pend_x = [x[nfull:]]
                    pending = len(x) - nfull

        tokens_done = 0
        for ep in range(epochs):
            for d in ids_docs:
                if sample > 0 and len(d):
                    d = d[rng.random(len(d)) < keep_p[d]]
                if cbow:
                    c, x = self._cbow_windows(d, win, rng)
                else:
                    c, x = self._skipgram_pairs(d, win, rng)
                if len(x):
                    # shuffle within the doc chunk: the per-delta grouping
                    # above would otherwise feed same-offset runs
                    perm = rng.permutation(len(x))
                    pend_c.append(c[perm])
                    pend_x.append(x[perm])
                    pending += len(x)
                tokens_done += len(d)
                drain(tokens_done / max(1, total * epochs))
        drain(1.0, final=True)
        return self

    # -- output --------------------------------------------------------------
    def model_rows(self) -> Iterator[Tuple[str, List[float]]]:
        emb = np.asarray(self.in_emb)
        for w, i in self.vocab.items():
            yield (w, emb[i].tolist())

    def vectors(self) -> Dict[str, np.ndarray]:
        emb = np.asarray(self.in_emb)
        return {w: emb[i] for w, i in self.vocab.items()}

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.vectors()[a], self.vectors()[b]
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)
                                + 1e-12))
