"""train_word2vec — SkipGram/CBOW with negative sampling (BASELINE config #4).

Reference (SURVEY.md §3.8): the late-incubator hivemall embedding package's
Word2VecUDTF: consume tokenized documents, build a vocabulary + unigram^0.75
negative-sampling table, and train SkipGram (default) or CBOW embeddings.

TPU shape: training pairs are generated host-side into fixed-shape arrays
(center[B], context[B], negatives[B, neg]); one jitted step does the
logistic pos/neg dot products and scatter-adds into the in/out embedding
tables — the whole O(B * neg * dim) update is a handful of fused einsums,
instead of the reference's per-pair scalar loops. Linear LR decay matches
word2vec.c / the reference.

Pair generation is fully vectorized (numpy, no per-token Python): dynamic
windows draw one width per position, then each window offset delta becomes
two array-slice selections (left/right context) over the whole document —
2*win vector ops per doc instead of O(tokens * window) scalar work. This
keeps the host side >=10M pairs/sec so text8-scale training is TPU-bound,
not input-bound.
"""

from __future__ import annotations

from collections import Counter
from functools import lru_cache, partial
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.devprof import instrument_factory as _instrument
from ..utils.options import OptionSpec

__all__ = ["Word2VecTrainer"]


class Word2VecTrainer:
    """SQL: train_word2vec(words[, options]) — UDTF over tokenized docs."""

    NAME = "train_word2vec"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = OptionSpec(cls.NAME)
        s.add("dim", "size", type=int, default=100, help="embedding dim")
        s.add("window", "win", type=int, default=5, help="context window")
        s.add("neg", "negative", type=int, default=5,
              help="negative samples per pair")
        s.add("iters", "iterations", type=int, default=1, help="epochs")
        s.add("min_count", type=int, default=5, help="vocab frequency floor")
        s.add("alpha", "lr", type=float, default=0.025,
              help="initial learning rate, linearly decayed. With the "
                   "default -pacing pair this is word2vec.c's per-pair "
                   "step size (0.025 means 0.025)")
        s.add("pacing", default="pair",
              help="pair (default): per-pair-SUM loss — each pair moves "
                   "its rows by O(alpha), word2vec.c-compatible option "
                   "values | mean: round-2 batch-MEAN loss (alpha must "
                   "scale with mini_batch; kept for compatibility)")
        s.add("sample", type=float, default=1e-4,
              help="frequent-word subsampling threshold (0 = off)")
        s.add("neg_sharing", default="pair",
              help="pair (default): word2vec.c per-pair negative draws | "
                   "batch: ONE negative set shared by the whole minibatch "
                   "(candidate-sampling style). Sharing turns the "
                   "negative path into a [B,D]x[D,neg] MXU matmul and a "
                   "neg-row scatter instead of B*neg gather/scatter rows "
                   "— ~3x step throughput; raise -neg (e.g. 16-64) to "
                   "compensate the shared draw")
        s.add("mini_batch", type=int, default=16384,
              help="pairs per step. Under -pacing pair each pair "
                   "contributes its own O(alpha) step regardless of batch "
                   "size (hogwild-style minibatch of word2vec.c's "
                   "sequential updates), so bigger batches only reduce "
                   "dispatch overhead")
        s.add("seed", type=int, default=11, help="rng seed")
        s.add("pair_gen", default="auto",
              help="where SkipGram (center, context) pairs are generated: "
                   "host (vectorized numpy, pairs cross h2d — 4 bytes per "
                   "pair) | device (token stream crosses h2d ONCE — ~2 "
                   "bytes per token, pairs come from shifted views on "
                   "device; needs -neg_sharing batch, SkipGram, no -mesh "
                   "— rejected otherwise) | auto (device on accelerators "
                   "when those hold, else host)")
        s.add("window_policy", default="sample",
              help="device pair-gen window policy: sample (word2vec.c "
                   "dynamic windows — each position draws w in [1,win], "
                   "pairs beyond w masked) | weighted (every pair trains, "
                   "weighted (win-delta+1)/win — the EXPECTATION of "
                   "sample's draw; zero masked slots, lower variance)")
        s.flag("cbow", help="CBOW instead of SkipGram")
        s.add("mesh", default=None,
              help="shard training over a device mesh, e.g. 'dp=2,tp=4' "
                   "(pair batches over dp, embedding tables over tp)")
        return s

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self._docs: List[List[str]] = []
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: List[str] = []
        self.in_emb: Optional[jnp.ndarray] = None
        self.out_emb: Optional[jnp.ndarray] = None
        self.mesh = None
        if self.opts.mesh:
            from ..parallel.mesh import make_mesh, parse_mesh_spec
            dp, tp = parse_mesh_spec(str(self.opts.mesh))
            if int(self.opts.mini_batch) % dp:
                raise ValueError(
                    f"-mini_batch {self.opts.mini_batch} must be divisible "
                    f"by the dp axis ({dp})")
            self.mesh = make_mesh(dp=dp, tp=tp)

    # -- UDTF lifecycle ------------------------------------------------------
    def process(self, words: Sequence[str]) -> None:
        self._docs.append([str(w) for w in words if w])

    def close(self) -> Iterator[Tuple[str, List[float]]]:
        self.train(self._docs)
        yield from self.model_rows()

    # -- training ------------------------------------------------------------
    def _build_vocab(self, docs: Sequence[Sequence[str]]) -> np.ndarray:
        # vectorized: ONE np.unique pass over the corpus replaces the
        # Counter + two per-token dict walks (~1.2 s of the text8-scale
        # bench was host string work); per-doc id arrays are cached for
        # train() via the same inverse
        # host string arrays from Python token lists — no device sync
        parts = [np.asarray(d, dtype=np.str_) for d in docs if len(d)]  # graftcheck: disable=GC07
        flat = np.concatenate(parts) if parts else np.asarray([], np.str_)
        uniq, inverse, counts = np.unique(
            flat, return_inverse=True, return_counts=True)
        keep = counts >= int(self.opts.min_count)
        order = np.argsort(-counts[keep], kind="stable")
        kept_words = uniq[keep][order]
        kept_counts = counts[keep][order]
        remap = np.full(len(uniq), -1, np.int64)
        remap[np.nonzero(keep)[0][order]] = np.arange(order.size)
        ids_flat = remap[inverse]
        self.vocab = {w: i for i, w in enumerate(kept_words.tolist())}
        self.inv_vocab = kept_words.tolist()
        # cache per-doc id arrays (dropping out-of-vocab tokens)
        self._ids_docs_cache = []
        off = 0
        for d in docs:
            ids = ids_flat[off:off + len(d)]
            off += len(d)
            self._ids_docs_cache.append(
                ids[ids >= 0].astype(np.int32))
        return np.asarray(kept_counts, np.float64)

    def _neg_table(self, freqs: np.ndarray, size: int = 0) -> np.ndarray:
        """Unigram^0.75 sampling table (word2vec.c style). Sized ~16 slots
        per word (capped [2^16, 2^20]) and stored uint16 when the vocab
        fits — the table crosses h2d once per trainer and a fixed 2^20
        int32 table cost ~4 MB (~0.3 s of every e2e run on the relay) for
        no sampling-fidelity gain at text8-scale vocabularies."""
        V = len(freqs)
        if not size:
            size = max(1 << 16, min(1 << 20, 16 * V))
        p = freqs ** 0.75
        p /= p.sum()
        dt = np.uint16 if V < 65536 else np.int32
        return np.repeat(np.arange(len(freqs)),
                         np.maximum(1, np.round(p * size).astype(np.int64))
                         ).astype(dt)

    def _make_step(self, cbow: bool, vocab_size: int, dim: int):
        neg = int(self.opts.neg)
        pair_pacing = str(getattr(self.opts, "pacing", "pair")) == "pair"
        share_neg = str(getattr(self.opts, "neg_sharing",
                                "pair")) == "batch"
        # Two update variants, chosen by table size (measured on v5e):
        #   dense  — autodiff over the whole (in, out) tables; the SGD
        #            update is two fused elementwise passes. Fastest while
        #            V*D stays a few MB (text8-class vocabularies).
        #   sparse — slab-level autodiff + scatter-add of touched rows
        #            only (the ops.fm.make_ffm_step_fused principle). At
        #            enwiki scale (V ~ 1M) the dense variant would move
        #            100s of MB of table per step for a few thousand
        #            touched rows.
        # Both variants draw NEGATIVES ON DEVICE from the staged unigram^.75
        # table (word2vec.c's table sampling, jax PRNG keyed by the step
        # counter) and rebuild the pair mask from the valid-count scalar:
        # per-step h2d drops from 4 arrays (~520 KB at B=16k) to the two
        # id arrays — the dispatch link is the e2e bottleneck here.
        if vocab_size * dim <= (1 << 23) and not share_neg:
            # NOTE: with -neg_sharing batch the sparse slab step wins at
            # every vocab size (measured 5 ms vs 20 ms at V=16k, B=32k —
            # the dense autodiff materializes several [V,D] passes while
            # shared negatives already removed the sparse path's per-pair
            # neg rows)
            return self._make_step_dense(cbow)

        seed = int(self.opts.seed)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(in_emb, out_emb, ntab, center, context, nvalid, t, lr):
            # SkipGram: v_in = in[center]; target = context
            # CBOW: v_in = mean(in[context window]) handled by caller passing
            #       the window in `center` as [B, 2w] with -1 padding
            # ids may arrive uint16 (halved h2d bytes — the relay link is
            # the e2e bottleneck); widen on device
            center = center.astype(jnp.int32)
            context = context.astype(jnp.int32)
            B = context.shape[0]
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            nshape = (neg,) if share_neg else (B, neg)
            negs = ntab[jax.random.randint(key, nshape, 0,
                                           ntab.shape[0])].astype(
                jnp.int32)
            row_mask = (jnp.arange(B) < nvalid).astype(jnp.float32)
            if cbow:
                cmask = (center >= 0).astype(jnp.float32)
                cids = jnp.maximum(center, 0)
                vin_slab = in_emb[cids]                      # [B, 2w, D]
            else:
                vin_slab = in_emb[center]                    # [B, D]
            pos_slab = out_emb[context]                      # [B, D]
            neg_slab = out_emb[negs]            # [neg, D] or [B, neg, D]

            def batch_loss(vin, op, on):
                if cbow:
                    v = (vin * cmask[..., None]).sum(1) / jnp.maximum(
                        cmask.sum(1, keepdims=True), 1.0)
                else:
                    v = vin
                pos = (v * op).sum(-1)
                if share_neg:
                    negd = jnp.einsum("bd,nd->bn", v, on)    # MXU
                else:
                    negd = jnp.einsum("bd,bnd->bn", v, on)
                per_pair = (jax.nn.softplus(-pos)
                            + jax.nn.softplus(negd).sum(-1)) * row_mask
                if pair_pacing:
                    # per-pair SUM: every pair moves its rows by O(lr) —
                    # word2vec.c's pacing, batched hogwild-style
                    return per_pair.sum()
                # batch MEAN (round-2 semantics): effective per-pair step
                # is lr / n_valid
                return per_pair.sum() / jnp.maximum(row_mask.sum(), 1.0)

            loss, (gv, gp, gn) = jax.value_and_grad(
                batch_loss, argnums=(0, 1, 2))(vin_slab, pos_slab, neg_slab)
            D = in_emb.shape[1]
            if cbow:
                ie = in_emb.at[cids.reshape(-1)].add(
                    (-lr * gv).reshape(-1, D))
            else:
                ie = in_emb.at[center].add(-lr * gv)
            oe = out_emb.at[context].add(-lr * gp)
            oe = oe.at[negs.reshape(-1)].add((-lr * gn).reshape(-1, D))
            return ie, oe, loss

        return step

    def _make_step_dense(self, cbow: bool):
        neg = int(self.opts.neg)
        pair_pacing = str(getattr(self.opts, "pacing", "pair")) == "pair"
        share_neg = str(getattr(self.opts, "neg_sharing",
                                "pair")) == "batch"

        seed = int(self.opts.seed)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(in_emb, out_emb, ntab, center, context, nvalid, t, lr):
            center = center.astype(jnp.int32)
            context = context.astype(jnp.int32)
            B = context.shape[0]
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t)
            nshape = (neg,) if share_neg else (B, neg)
            negs = ntab[jax.random.randint(key, nshape, 0,
                                           ntab.shape[0])].astype(
                jnp.int32)
            row_mask = (jnp.arange(B) < nvalid).astype(jnp.float32)

            def batch_loss(tables):
                ie, oe = tables
                if cbow:
                    mask = (center >= 0).astype(jnp.float32)
                    v = (ie[jnp.maximum(center, 0)] *
                         mask[..., None]).sum(1) / jnp.maximum(
                             mask.sum(1, keepdims=True), 1.0)
                else:
                    v = ie[center]
                pos = (v * oe[context]).sum(-1)
                if share_neg:
                    negd = jnp.einsum("bd,nd->bn", v, oe[negs])
                else:
                    negd = jnp.einsum("bd,bnd->bn", v, oe[negs])
                per_pair = (jax.nn.softplus(-pos)
                            + jax.nn.softplus(negd).sum(-1)) * row_mask
                if pair_pacing:
                    # per-pair SUM — word2vec.c pacing (see _make_step)
                    return per_pair.sum()
                return per_pair.sum() / jnp.maximum(row_mask.sum(), 1.0)

            loss, grads = jax.value_and_grad(batch_loss)((in_emb, out_emb))
            return (in_emb - lr * grads[0], out_emb - lr * grads[1], loss)

        return step

    def _make_pairgen(self, Nc: int, win: int, sep_id: int, policy: str,
                      seed: int, wire_dt):
        # module-level lru_cache: a fresh jitted closure per TRAINER would
        # re-trace/compile on every instance (measured: recompilation cost
        # dominated the device-windowing e2e run — each bench repeat paid
        # seconds of compile for identical configs)
        return _pairgen_cached(Nc, win, sep_id, policy, seed,
                               np.dtype(wire_dt).name)

    def _make_chunk_trainer(self, W2: int, Bc: int, n_steps: int):
        return _chunk_trainer_cached(
            W2, Bc, n_steps, int(self.opts.neg),
            str(getattr(self.opts, "pacing", "pair")) == "pair",
            int(self.opts.seed))

    def _train_device_windowing(self, ids_docs, keep_p,
                                table) -> None:
        """SkipGram training with on-device pair windowing (-pair_gen
        device): the token stream crosses h2d once per epoch (~2
        bytes/token vs ~4 bytes/PAIR x ~5 pairs/token on the host path);
        per-chunk, one jitted pair-gen builds the center-major [.., 2*win]
        grid and the grid step consumes row-block device slices."""
        o = self.opts
        rng = np.random.default_rng(int(o.seed))
        win = int(o.window)
        W2 = 2 * win
        B = int(o.mini_batch)
        Bc = max(128, B // W2)          # centers per step (~B pair slots)
        alpha = float(o.alpha)
        epochs = int(o.iters)
        V = len(self.vocab)
        sep = V                         # out-of-vocab sentinel id
        wire_dt = np.uint16 if V < 65535 else np.int32
        policy = str(o.window_policy)
        if policy not in ("sample", "weighted"):
            raise ValueError(f"-window_policy must be sample|weighted, got "
                             f"{policy!r}")
        gen = None                      # built once the stream size is known
        runner = None
        nstep = 0
        for ep in range(epochs):
            parts = []
            for d in ids_docs:
                if float(o.sample) > 0 and len(d):
                    d = d[rng.random(len(d)) < keep_p[d]]
                if len(d):
                    parts.append(d)
                    parts.append(np.full(win, sep, np.int32))
            if not parts:
                continue
            stream = np.concatenate(parts).astype(wire_dt)
            n = len(stream)
            if gen is None:
                # chunk tokens: power-of-two sized to the corpus, capped at
                # 512k (pair grid ~5.2M slots) — ONE compile per corpus
                # scale instead of a fixed grid that buries small corpora
                # in masked slots
                CH = min(1 << 19, 1 << max(10, (n - 1).bit_length()))
                Nc = CH + 2 * win
                gen = self._make_pairgen(Nc, win, sep, policy,
                                         int(o.seed), wire_dt)
            epd = jnp.uint32(ep)
            for s0 in range(0, n, CH):
                # win-token halo each side; SEP-pad the stream edges
                lo, hi = s0 - win, s0 + CH + win
                chunk = np.full(Nc, sep, wire_dt)
                src_lo, src_hi = max(0, lo), min(n, hi)
                chunk[src_lo - lo:src_hi - lo] = stream[src_lo:src_hi]
                c_all, x_all, m_all, _ = gen(jnp.asarray(chunk),
                                             jnp.int32(s0), epd)
                R = c_all.shape[0]               # grid rows (= Nc centers)
                ck_tokens = min(CH, n - s0)
                n_steps = -(-R // Bc)
                if runner is None:
                    runner = self._make_chunk_trainer(W2, Bc, n_steps)
                pad = n_steps * Bc - R
                if pad:
                    c_all = jnp.pad(c_all, (0, pad))
                    x_all = jnp.pad(x_all, ((0, pad), (0, 0)))
                    m_all = jnp.pad(m_all, ((0, pad), (0, 0)))

                # word2vec.c decays alpha continuously per word; progress
                # is PER-EPOCH NORMALIZED ((ep + within-epoch)/epochs) so
                # subsampling's per-epoch stream-length jitter can't push
                # it past 1.0 (which would clamp the tail at lr_min) or
                # leave it short of the floor; within a chunk it
                # interpolates per STEP so a single-chunk corpus still
                # sweeps alpha -> ~0
                def lr_at(si: float) -> float:
                    prog = (ep + (s0 + ck_tokens * (si / n_steps)) / n) \
                        / epochs
                    return alpha * (1.0 - prog)

                lr0 = lr_at(0.0)
                dlr = (lr0 - lr_at(float(n_steps))) / max(1, n_steps)
                self.in_emb, self.out_emb = runner(
                    self.in_emb, self.out_emb, table, c_all, x_all, m_all,
                    jnp.int32(nstep), jnp.float32(lr0), jnp.float32(dlr),
                    jnp.float32(alpha * 1e-4))
                nstep += n_steps

    @staticmethod
    def _skipgram_pairs(d: np.ndarray, win: int, rng) -> Tuple[np.ndarray,
                                                               np.ndarray]:
        """Vectorized SkipGram (center, context) pairs for one doc.

        Dynamic windows as in word2vec.c: each position draws a width
        w in [1, win]; (pos, pos±delta) is a pair iff delta <= w[pos].
        2*win slice-selections replace the per-token Python loop."""
        n = len(d)
        if n < 2:
            return (np.zeros(0, np.int32),) * 2
        w = rng.integers(1, win + 1, n, dtype=np.uint8)
        cs: List[np.ndarray] = []
        xs: List[np.ndarray] = []
        for delta in range(1, win + 1):
            pos = np.flatnonzero(w >= delta)   # centers wide enough for delta
            right = pos[pos < n - delta]       # (pos, pos+delta)
            cs.append(d[right])
            xs.append(d[right + delta])
            left = pos[pos >= delta]           # (pos, pos-delta)
            cs.append(d[left])
            xs.append(d[left - delta])
        return np.concatenate(cs), np.concatenate(xs)

    @staticmethod
    def _cbow_windows(d: np.ndarray, win: int, rng) -> Tuple[np.ndarray,
                                                             np.ndarray]:
        """Vectorized CBOW windows: rows [n, 2*win] of context ids (-1 pad)
        plus the center target, dynamic widths per position."""
        n = len(d)
        if n < 2:
            return np.zeros((0, 2 * win), np.int32), np.zeros(0, np.int32)
        w = rng.integers(1, win + 1, n)
        ctx = np.full((n, 2 * win), -1, np.int32)
        for delta in range(1, win + 1):
            keep = w >= delta
            col_r, col_l = 2 * (delta - 1), 2 * (delta - 1) + 1
            # right neighbor pos+delta feeds center pos
            sel = keep[:n - delta]
            ctx[:n - delta, col_r] = np.where(sel, d[delta:], -1)
            # left neighbor pos-delta feeds center pos
            sel = keep[delta:]
            ctx[delta:, col_l] = np.where(sel, d[:n - delta], -1)
        has_ctx = (ctx >= 0).any(1)
        return ctx[has_ctx], d[has_ctx]

    def train(self, docs: Sequence[Sequence[str]]) -> "Word2VecTrainer":
        o = self.opts
        freqs = self._build_vocab(docs)
        V, D = len(self.vocab), int(o.dim)
        if V == 0:
            raise ValueError("empty vocabulary (check -min_count)")
        rng = np.random.default_rng(int(o.seed))
        key = jax.random.PRNGKey(int(o.seed))
        Vp = V
        if self.mesh is not None:     # pad vocab rows to the tp axis size
            tp = self.mesh.shape["tp"]
            Vp = -(-V // tp) * tp     # extra rows are never gathered
        self.in_emb = (jax.random.uniform(key, (Vp, D)) - 0.5) / D
        self.out_emb = jnp.zeros((Vp, D))
        table = jnp.asarray(self._neg_table(freqs))   # staged on device once
        if self.mesh is not None:
            # vocab rows over tp, negative table replicated, batches over dp
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(self.mesh, P("tp", None))
            self.in_emb = jax.device_put(self.in_emb, sh)
            self.out_emb = jax.device_put(self.out_emb, sh)
            table = jax.device_put(table, NamedSharding(self.mesh, P()))
        ids_docs = getattr(self, "_ids_docs_cache", None) or \
            [np.asarray([self.vocab[w] for w in d if w in self.vocab],  # graftcheck: disable=GC07
                               np.int32) for d in docs]  # host id arrays, no sync
        total = sum(len(d) for d in ids_docs)
        # frequent-word subsampling probabilities (word2vec.c formula)
        sample = float(o.sample)
        if sample > 0:
            f = freqs / max(1, total)
            keep_p = np.minimum(1.0, np.sqrt(sample / f) + sample / f)
        else:
            keep_p = np.ones(V)

        cbow = bool(o.cbow)
        pg = str(o.pair_gen)
        if pg not in ("auto", "host", "device"):
            raise ValueError(f"-pair_gen must be auto|host|device, got "
                             f"{pg!r}")
        share_neg = str(getattr(o, "neg_sharing", "pair")) == "batch"
        dev_ok = not cbow and self.mesh is None and share_neg
        if pg == "device" and not dev_ok:
            # never SILENTLY train with different semantics than asked: the
            # grid path needs batch-shared negatives (the per-center
            # negative term is the savings), SkipGram, and no mesh
            raise ValueError(
                "-pair_gen device requires -neg_sharing batch, SkipGram "
                "(no -cbow), and no -mesh; use -pair_gen auto to fall "
                "back automatically")
        if dev_ok and (pg == "device"
                       or (pg == "auto"
                           and jax.default_backend() != "cpu")):
            self._train_device_windowing(ids_docs, keep_p, table)
            return self

        step = self._make_step(cbow, V, D)
        win = int(o.window)
        B = int(o.mini_batch)
        neg = int(o.neg)
        alpha = float(o.alpha)
        epochs = int(o.iters)

        # pending vectorized pair chunks awaiting dispatch
        pend_c: List[np.ndarray] = []
        pend_x: List[np.ndarray] = []
        pending = 0

        nstep = 0

        wire_dt = np.uint16 if (not cbow and V < 65536) else np.int32
        K = 8               # steps shipped per h2d block (latency ~5 ms
                            # per transfer through the relay dominates; one
                            # [K*B] block transfer feeds K pipelined steps)

        def dispatch_block(c: np.ndarray, x: np.ndarray, progress: float
                           ) -> None:
            """Ship up to K steps' pair ids in ONE h2d each, then step on
            device-resident slices; short tails pad to B and mask."""
            nonlocal nstep
            n = len(x)
            if n == 0:
                return
            nfull = -(-n // B) * B
            if nfull != n:
                padn = nfull - n
                c = np.concatenate(
                    [c, np.full((padn,) + c.shape[1:],
                                -1 if cbow else 0, c.dtype)])
                x = np.concatenate([x, np.zeros(padn, x.dtype)])
            lr = max(alpha * (1.0 - progress), alpha * 1e-4)
            cd_all = jnp.asarray(c.astype(wire_dt, copy=False))
            xd_all = jnp.asarray(x.astype(wire_dt, copy=False))
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                cd_all = jax.device_put(cd_all, NamedSharding(
                    self.mesh, P(None, *([None] * (cd_all.ndim - 1)))))
                xd_all = jax.device_put(xd_all,
                                        NamedSharding(self.mesh, P(None)))
            for s0 in range(0, nfull, B):
                nb = min(B, n - s0)
                if nb <= 0:
                    break
                nstep += 1
                cd = cd_all[s0:s0 + B]
                xd = xd_all[s0:s0 + B]
                if self.mesh is not None:
                    from jax.sharding import NamedSharding, \
                        PartitionSpec as P
                    cd = jax.device_put(cd, NamedSharding(
                        self.mesh, P("dp", *([None] * (cd.ndim - 1)))))
                    xd = jax.device_put(xd,
                                        NamedSharding(self.mesh, P("dp")))
                self.in_emb, self.out_emb, _ = step(
                    self.in_emb, self.out_emb, table, cd, xd, nb, nstep,
                    lr)

        def drain(progress: float, final: bool = False) -> None:
            nonlocal pend_c, pend_x, pending
            if pending >= K * B or (final and pending):
                c = np.concatenate(pend_c)
                x = np.concatenate(pend_x)
                nfull = (len(x) // B) * B
                if final:
                    dispatch_block(c, x, progress)
                    pend_c, pend_x, pending = [], [], 0
                else:
                    dispatch_block(c[:nfull], x[:nfull], progress)
                    pend_c = [c[nfull:]]
                    pend_x = [x[nfull:]]
                    pending = len(x) - nfull

        tokens_done = 0
        for ep in range(epochs):
            for d in ids_docs:
                if sample > 0 and len(d):
                    d = d[rng.random(len(d)) < keep_p[d]]
                if cbow:
                    c, x = self._cbow_windows(d, win, rng)
                else:
                    c, x = self._skipgram_pairs(d, win, rng)
                if len(x):
                    if str(o.pacing) == "mean":
                        # mean pacing needs in-chunk shuffling: the
                        # per-delta grouping feeds same-offset runs that
                        # skew the batch mean. Pair pacing processes pairs
                        # in corpus order — word2vec.c's own order — and
                        # skips the ~1s host permutation+gather per 10M+
                        # pair chunk.
                        perm = rng.permutation(len(x))
                        c, x = c[perm], x[perm]
                    pend_c.append(c)
                    pend_x.append(x)
                    pending += len(x)
                tokens_done += len(d)
                drain(tokens_done / max(1, total * epochs))
        drain(1.0, final=True)
        return self

    # -- output --------------------------------------------------------------
    def model_rows(self) -> Iterator[Tuple[str, List[float]]]:
        emb = np.asarray(self.in_emb)
        for w, i in self.vocab.items():
            yield (w, emb[i].tolist())

    def vectors(self) -> Dict[str, np.ndarray]:
        emb = np.asarray(self.in_emb)
        return {w: emb[i] for w, i in self.vocab.items()}

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.vectors()[a], self.vectors()[b]
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)
                                + 1e-12))

    def serving_tables(self) -> Tuple[dict, Dict[str, np.ndarray]]:
        """Arena "factor" family (io.weight_arena): both the query table
        and the candidate table are the input embeddings — word2vec's
        retrieval shape is word→nearest-words over ONE vector space, so
        ``P is Q`` and cosine neighbor queries are the meaningful tier.
        Only the trained vocab rows export (the table may be padded to a
        tp mesh axis); the vocab itself rides in the header so the
        retrieval plane can translate ids back to words."""
        if self.in_emb is None:
            raise ValueError("serving_tables() before train(): "
                             "no embeddings yet")
        V = len(self.vocab)
        emb = np.asarray(self.in_emb, np.float32)[:V]
        meta = {"family": "factor", "k": int(emb.shape[1]), "mu": 0.0,
                "user_bias": False, "item_bias": False,
                "classification": False, "vocab": list(self.inv_vocab)}
        return meta, {"P": emb, "Q": emb}


@_instrument("word2vec", "pairgen")
@lru_cache(maxsize=64)
def _pairgen_cached(Nc: int, win: int, sep_id: int, policy: str, seed: int,
                    wire_name: str):
    """Jitted device-side SkipGram pair generator over a token chunk
    (cached per static config so trainer instances share one compile).

    The round-3 e2e wall was the h2d link moving PAIRS (~4 bytes/pair
    x ~5 pairs/token); here the TOKEN STREAM crosses once (~2
    bytes/token) and pairs come from 2*win shifted views (jnp.roll —
    no per-element index ops, the round-3 trap). Slot (i, j) of the
    [Nc, 2*win] grid is (T[i], T[i +/- delta]); validity/weight rides
    a per-slot mask consumed by the grid step (invalid slots train with
    weight 0 — masking beats device compaction, whose argsort/scatter
    would cost ~26 ns per pair, more than the step).

    policy='sample': word2vec.c dynamic windows — w[i] drawn in [1, win]
    by an integer hash of the global position (stateless, so chunks and
    epochs stay reproducible), pairs with delta > w[i] masked.
    policy='weighted': every pair trains with weight (win - delta + 1)/win
    — exactly the expectation of sample's draw, zero masked slots, lower
    gradient variance (documented delta). Chunks arrive with a win-token
    halo on both sides; centers in the halo are masked (their pairs belong
    to neighbour chunks)."""
    wire_dt = np.dtype(wire_name)

    @jax.jit
    def gen(T, offset, ep):
        Tw = T.astype(jnp.int32)
        i = jnp.arange(Nc, dtype=jnp.int32)
        if policy == "sample":
            h = (i + offset).astype(jnp.uint32)
            h = h * jnp.uint32(0x9E3779B1) + jnp.uint32(seed)
            h = h ^ (h >> 15)
            h = (h + ep.astype(jnp.uint32)) * jnp.uint32(0xC2B2AE35)
            h = h ^ (h >> 13)
            w = (1 + h % jnp.uint32(win)).astype(jnp.int32)
        ms, xs = [], []
        is_sep = Tw == sep_id
        center_ok = (~is_sep) & (i >= win) & (i < Nc - win)
        for delta in range(1, win + 1):
            for sgn in (1, -1):
                ctx = jnp.roll(Tw, -sgn * delta)
                ok = center_ok & (ctx != sep_id)
                if policy == "sample":
                    wt = (ok & (w >= delta)).astype(jnp.float32)
                else:
                    wt = ok.astype(jnp.float32) * ((win - delta + 1) / win)
                xs.append(ctx)
                ms.append(wt)
        x = jnp.stack(xs, 1).astype(wire_dt)      # [Nc, 2*win]
        m = jnp.stack(ms, 1)                      # [Nc, 2*win]
        return Tw.astype(wire_dt), x, m, m.sum()

    return gen


@_instrument("word2vec", "chunk_trainer")
@lru_cache(maxsize=64)
def _chunk_trainer_cached(W2: int, Bc: int, n_steps: int, neg: int,
                          pair_pacing: bool, seed: int):
    """The WHOLE chunk's step loop as one jitted lax.fori_loop (cached per
    static config — a fresh closure per trainer re-compiled every run).

    A per-step python loop cost ~2 ms of relay dispatch per slice/step
    (measured: it capped the device pair-gen path below the host path);
    here a chunk is ONE dispatch. Each iteration consumes a [Bc] center
    block of the center-major grid via dynamic_slice, draws that step's
    shared negatives from the staged table, and applies the grid-step
    update: the flat pair step pays (gather + scatter) on BOTH endpoints
    of every slot (~4 index ops/pair at ~26 ns, the measured per-row
    floor); the grid gathers/scatters each center ONCE per W2 slots and
    computes the shared-negative term — which depends only on the center
    vector — per CENTER, weighted by the row's total pair weight (equal
    to summing it per pair). Index ops per slot drop from ~4 to
    ~2 + 2/W2. lr decays linearly across the chunk (word2vec.c per-word
    decay)."""
    @partial(jax.jit, donate_argnums=(0, 1))
    def run(in_emb, out_emb, ntab, c_all, x_all, m_all, t0, lr0, dlr,
            lr_min):
        D = in_emb.shape[1]

        def body(si, carry):
            ie, oe = carry
            r0 = si * Bc
            centers = jax.lax.dynamic_slice(
                c_all, (r0,), (Bc,)).astype(jnp.int32)
            ctx = jax.lax.dynamic_slice(
                x_all, (r0, 0), (Bc, W2)).astype(jnp.int32)
            wts = jax.lax.dynamic_slice(m_all, (r0, 0), (Bc, W2))
            lr = jnp.maximum(lr0 - dlr * si.astype(jnp.float32), lr_min)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), t0 + si)
            negs = ntab[jax.random.randint(
                key, (neg,), 0, ntab.shape[0])].astype(jnp.int32)
            vin = ie[centers]                        # [Bc, D]
            pos_slab = oe[ctx.reshape(-1)].reshape(Bc, W2, D)
            neg_slab = oe[negs]                      # [neg, D]
            wrow = wts.sum(1)

            def batch_loss(v, po, on):
                posd = jnp.einsum("bd,bwd->bw", v, po)
                negd = jnp.einsum("bd,nd->bn", v, on)
                data = (jax.nn.softplus(-posd) * wts).sum() \
                    + (jax.nn.softplus(negd).sum(-1) * wrow).sum()
                if pair_pacing:
                    return data
                return data / jnp.maximum(wrow.sum(), 1.0)

            _, (gv, gp, gn) = jax.value_and_grad(
                batch_loss, argnums=(0, 1, 2))(vin, pos_slab, neg_slab)
            ie = ie.at[centers].add(-lr * gv)
            oe = oe.at[ctx.reshape(-1)].add((-lr * gp).reshape(-1, D))
            oe = oe.at[negs].add(-lr * gn)
            return (ie, oe)

        return jax.lax.fori_loop(0, n_steps, body, (in_emb, out_emb))

    return run
