"""train_word2vec — SkipGram/CBOW with negative sampling (BASELINE config #4).

Reference (SURVEY.md §3.8): the late-incubator hivemall embedding package's
Word2VecUDTF: consume tokenized documents, build a vocabulary + unigram^0.75
negative-sampling table, and train SkipGram (default) or CBOW embeddings.

TPU shape: training pairs are generated host-side into fixed-shape arrays
(center[B], context[B], negatives[B, neg]); one jitted step does the
logistic pos/neg dot products and scatter-adds into the in/out embedding
tables — the whole O(B * neg * dim) update is a handful of fused einsums,
instead of the reference's per-pair scalar loops. Linear LR decay matches
word2vec.c / the reference.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.options import OptionSpec

__all__ = ["Word2VecTrainer"]


class Word2VecTrainer:
    """SQL: train_word2vec(words[, options]) — UDTF over tokenized docs."""

    NAME = "train_word2vec"

    @classmethod
    def spec(cls) -> OptionSpec:
        s = OptionSpec(cls.NAME)
        s.add("dim", "size", type=int, default=100, help="embedding dim")
        s.add("window", "win", type=int, default=5, help="context window")
        s.add("neg", "negative", type=int, default=5,
              help="negative samples per pair")
        s.add("iters", "iterations", type=int, default=1, help="epochs")
        s.add("min_count", type=int, default=5, help="vocab frequency floor")
        s.add("alpha", "lr", type=float, default=0.25,
              help="initial learning rate, linearly decayed. NOTE: applies "
                   "to the batch-MEAN pair loss, so it sits ~10x above "
                   "word2vec.c's per-pair 0.025 for equivalent pacing")
        s.add("sample", type=float, default=1e-4,
              help="frequent-word subsampling threshold (0 = off)")
        s.add("mini_batch", type=int, default=2048, help="pairs per step")
        s.add("seed", type=int, default=11, help="rng seed")
        s.flag("cbow", help="CBOW instead of SkipGram")
        return s

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self._docs: List[List[str]] = []
        self.vocab: Dict[str, int] = {}
        self.inv_vocab: List[str] = []
        self.in_emb: Optional[jnp.ndarray] = None
        self.out_emb: Optional[jnp.ndarray] = None

    # -- UDTF lifecycle ------------------------------------------------------
    def process(self, words: Sequence[str]) -> None:
        self._docs.append([str(w) for w in words if w])

    def close(self) -> Iterator[Tuple[str, List[float]]]:
        self.train(self._docs)
        yield from self.model_rows()

    # -- training ------------------------------------------------------------
    def _build_vocab(self, docs: Sequence[Sequence[str]]) -> np.ndarray:
        counts = Counter(w for d in docs for w in d)
        kept = [(w, c) for w, c in counts.most_common()
                if c >= int(self.opts.min_count)]
        self.vocab = {w: i for i, (w, _) in enumerate(kept)}
        self.inv_vocab = [w for w, _ in kept]
        freqs = np.asarray([c for _, c in kept], np.float64)
        return freqs

    def _neg_table(self, freqs: np.ndarray, size: int = 1 << 20) -> np.ndarray:
        """Unigram^0.75 sampling table (word2vec.c style)."""
        p = freqs ** 0.75
        p /= p.sum()
        return np.repeat(np.arange(len(freqs)),
                         np.maximum(1, np.round(p * size).astype(np.int64))
                         ).astype(np.int32)

    def _make_step(self, cbow: bool):
        neg = int(self.opts.neg)

        @jax.jit
        def step(in_emb, out_emb, center, context, negs, row_mask, lr):
            # SkipGram: v_in = in[center]; target = context
            # CBOW: v_in = mean(in[context window]) handled by caller passing
            #       the window in `center` as [B, 2w] with -1 padding
            def batch_loss(tables):
                ie, oe = tables
                if cbow:
                    mask = (center >= 0).astype(jnp.float32)
                    v = (ie[jnp.maximum(center, 0)] *
                         mask[..., None]).sum(1) / jnp.maximum(
                             mask.sum(1, keepdims=True), 1.0)
                    tgt = context
                else:
                    v = ie[center]
                    tgt = context
                pos = (v * oe[tgt]).sum(-1)
                negd = jnp.einsum("bd,bnd->bn", v, oe[negs])
                per_pair = (jax.nn.softplus(-pos)
                            + jax.nn.softplus(negd).sum(-1)) * row_mask
                # mean over valid pairs: per-word effective step stays O(lr)
                # even when one word recurs many times in a batch (the
                # batched analog of word2vec.c's sequential per-pair steps)
                return per_pair.sum() / jnp.maximum(row_mask.sum(), 1.0)

            loss, grads = jax.value_and_grad(batch_loss)((in_emb, out_emb))
            return (in_emb - lr * grads[0], out_emb - lr * grads[1], loss)

        return step

    def train(self, docs: Sequence[Sequence[str]]) -> "Word2VecTrainer":
        o = self.opts
        freqs = self._build_vocab(docs)
        V, D = len(self.vocab), int(o.dim)
        if V == 0:
            raise ValueError("empty vocabulary (check -min_count)")
        rng = np.random.default_rng(int(o.seed))
        key = jax.random.PRNGKey(int(o.seed))
        self.in_emb = (jax.random.uniform(key, (V, D)) - 0.5) / D
        self.out_emb = jnp.zeros((V, D))
        table = self._neg_table(freqs)
        ids_docs = [np.asarray([self.vocab[w] for w in d if w in self.vocab],
                               np.int32) for d in docs]
        total = sum(len(d) for d in ids_docs)
        # frequent-word subsampling probabilities (word2vec.c formula)
        sample = float(o.sample)
        if sample > 0:
            f = freqs / max(1, total)
            keep_p = np.minimum(1.0, np.sqrt(sample / f) + sample / f)
        else:
            keep_p = np.ones(V)

        cbow = bool(o.cbow)
        step = self._make_step(cbow)
        win = int(o.window)
        B = int(o.mini_batch)
        neg = int(o.neg)
        alpha = float(o.alpha)
        epochs = int(o.iters)

        # host-side pair generation into fixed [B] / [B, 2w] batches
        centers: List = []
        contexts: List[int] = []

        def flush(progress: float):
            nonlocal centers, contexts
            if not centers:
                return 0.0
            n = len(centers)
            pad = B - n
            if cbow:
                c = np.full((B, 2 * win), -1, np.int32)
                for r, ctx in enumerate(centers):
                    c[r, :len(ctx)] = ctx
            else:
                c = np.zeros(B, np.int32)
                c[:n] = centers
            t = np.zeros(B, np.int32)
            t[:n] = contexts
            rm = np.zeros(B, np.float32)
            rm[:n] = 1.0
            negs = table[rng.integers(0, len(table), (B, neg))]
            lr = max(alpha * (1.0 - progress), alpha * 1e-4)
            self.in_emb, self.out_emb, loss = step(
                self.in_emb, self.out_emb, jnp.asarray(c), jnp.asarray(t),
                jnp.asarray(negs), jnp.asarray(rm), lr)
            centers, contexts = [], []
            return loss            # device array; don't block async dispatch

        seen = 0
        for ep in range(epochs):
            for d in ids_docs:
                if sample > 0 and len(d):
                    d = d[rng.random(len(d)) < keep_p[d]]
                for pos in range(len(d)):
                    w = 1 + int(rng.integers(0, win))   # dynamic window
                    lo, hi = max(0, pos - w), min(len(d), pos + w + 1)
                    ctx_ids = [d[p] for p in range(lo, hi) if p != pos]
                    if not ctx_ids:
                        continue
                    if cbow:
                        centers.append(ctx_ids)
                        contexts.append(int(d[pos]))
                        seen += 1
                        if len(centers) >= B:
                            flush(seen / (total * epochs + 1))
                    else:
                        for c_id in ctx_ids:
                            centers.append(int(d[pos]))
                            contexts.append(int(c_id))
                            seen += 1
                            if len(centers) >= B:
                                flush(seen / (total * epochs * 2 * win + 1))
        flush(1.0)
        return self

    # -- output --------------------------------------------------------------
    def model_rows(self) -> Iterator[Tuple[str, List[float]]]:
        emb = np.asarray(self.in_emb)
        for w, i in self.vocab.items():
            yield (w, emb[i].tolist())

    def vectors(self) -> Dict[str, np.ndarray]:
        emb = np.asarray(self.in_emb)
        return {w: emb[i] for w, i in self.vocab.items()}

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.vectors()[a], self.vectors()[b]
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)
                                + 1e-12))
