"""Tree-ensemble trainers — train_randomforest_* and the XGBoost-parity
gradient-boosting family (BASELINE config #5).

Reference (SURVEY.md §3.9): hivemall.smile.classification.
RandomForestClassifierUDTF / regression.RandomForestRegressionUDTF (buffer all
rows, build -trees bootstrap trees at close(), emit one row per tree:
serialized model + oob error), TreePredictUDF's StackMachine VM,
RandomForestEnsembleUDAF, GuessAttributesUDF, and the xgboost/ module's JNI
wrapper (train_xgboost_classifier / _regr / multiclass + predict UDTFs).

TPU rebuild: histogram kernels (ops.trees) replace both smile's exact scans
and native libxgboost; tree models serialize to base64 npz blobs (the analog
of the opcode script / booster blob) and predict via the vectorized gather
walk.
"""

from __future__ import annotations

import base64
import io
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.trees import (Tree, bin_raw, boost_loop_xgb, build_tree_classifier,
                         build_tree_regressor, colsample_mtry, predict_bins,
                         quantize_bins, use_pallas_default)
from ..utils.options import OptionSpec

__all__ = ["RandomForestClassifier", "RandomForestRegressor",
           "GradientBoosting", "XGBoostClassifier", "XGBoostRegressor",
           "XGBoostMulticlassClassifier", "StagedMatrix", "tree_predict",
           "tree_model_meta", "rf_ensemble",
           "guess_attribute_types", "serialize_tree", "deserialize_tree"]


# --- model blob codec (the opcode/booster-blob analog) ----------------------

def serialize_tree(tree: Tree, e: int, extra: Optional[Dict] = None) -> str:
    buf = io.BytesIO()
    np.savez_compressed(buf, feat=tree.feat[e], thr=tree.thr[e],
                        value=tree.value[e], edges=tree.edges,
                        **(extra or {}))
    return base64.b64encode(buf.getvalue()).decode("ascii")


def deserialize_tree(blob: str) -> Tuple[Tree, Dict]:
    z = np.load(io.BytesIO(base64.b64decode(blob)), allow_pickle=False)
    tree = Tree(z["feat"][None], z["thr"][None], z["value"][None], z["edges"])
    extra = {k: z[k] for k in z.files
             if k not in ("feat", "thr", "value", "edges")}
    return tree, extra


def _rf_spec(name: str) -> OptionSpec:
    s = OptionSpec(name)
    s.add("trees", "num_trees", type=int, default=50, help="ensemble size")
    s.add("vars", "num_vars", type=int, default=0,
          help="mtry: features tried per node (0 = sqrt(d) cls / d/3 regr)")
    s.add("depth", "max_depth", type=int, default=8, help="max tree depth")
    s.add("leafs", "max_leaf_nodes", type=int, default=0,
          help="accepted for reference compat (depth bounds the tree here)")
    s.add("mesh", default=None,
          help="ensemble parallelism over a device mesh, e.g. 'dp=4': "
               "bootstrap trees shard across devices (SURVEY §3.17), "
               "bins replicate; -trees must divide the dp axis")
    s.add("min_split", "min_samples_split", type=int, default=2,
          help="min rows to split a node")
    s.add("min_leaf", "min_samples_leaf", type=int, default=1,
          help="min rows per child")
    s.add("bins", type=int, default=64, help="histogram bins per feature")
    s.add("seed", type=int, default=31, help="rng seed")
    s.add("attrs", "attribute_types", default=None,
          help="comma list of Q (quantitative) / C (categorical) specs; "
               "C columns with cardinality <= -bins split NOMINALLY "
               "(one-hot membership columns — a threshold split tests "
               "set membership, not order); higher-cardinality C columns "
               "fall back to ordinal binning (documented delta)")
    s.add("bootstrap", default="exact",
          help="exact (reference parity: multinomial resample per tree, "
               "host-generated) | poisson (Poisson(1) streaming-bootstrap "
               "approximation, generated ON DEVICE — skips the [trees, n] "
               "weight transfer, the biggest h2d term of a 1M-row fit)")
    return s


class _ForestBase:
    SPEC_NAME = "train_randomforest"

    @classmethod
    def spec(cls) -> OptionSpec:
        return _rf_spec(cls.SPEC_NAME)

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self._X: List[Sequence[float]] = []
        self._y: List[float] = []
        self.tree: Optional[Tree] = None
        self.oob_errors: List[float] = []

    def process(self, features: Sequence[float], label) -> None:
        """Buffer one dense feature row (the reference buffers ALL rows and
        trains at close — SURVEY.md §3.9)."""
        self._X.append([float(v) for v in features])
        self._y.append(label)

    def fit(self, X, y) -> "_ForestBase":
        # X may be a raw [n, d] array or a StagedMatrix (pre-binned,
        # device-staged — quantize + h2d paid once across many fits)
        self._X = X if isinstance(X, StagedMatrix) else \
            list(np.asarray(X, np.float32))
        self._y = np.asarray(y)
        self._train()
        return self

    def close(self) -> Iterator[Tuple[int, str, float]]:
        """Emit (model_id, serialized model, oob_error) per tree."""
        self._train()
        for e in range(self.tree.feat.shape[0]):
            yield (e, serialize_tree(self.tree, e,
                                     self._blob_extra()),
                   float(self.oob_errors[e]))

    def _blob_extra(self) -> Dict:
        if getattr(self, "_expander", None) is not None:
            return self._expander.to_blob()
        return {}

    def _features_for_train(self):
        """(binsj, edges, n, d) with -attrs nominal expansion applied.
        C columns (cardinality <= -bins) become one-hot membership
        columns via CatExpander; the expander rides the model for
        predict-time expansion and is serialized into tree blobs."""
        o = self.opts
        self._expander = None
        attrs = getattr(o, "attrs", None)
        if attrs is not None:
            if isinstance(self._X, StagedMatrix):
                is_cat = _parse_attrs(attrs, self._X.shape[1])
                if any(is_cat):
                    raise ValueError(
                        "-attrs with C columns is applied at quantize "
                        "time; pass raw X, not a StagedMatrix")
                return _staged_or_quantize(self._X, int(o.bins))
            X = np.asarray(self._X, np.float32)
            is_cat = _parse_attrs(attrs, X.shape[1])
            if any(is_cat):
                exp = CatExpander(is_cat, X, int(o.bins))
                if exp.active:
                    self._expander = exp
                    X2 = exp.transform(X)
                    codes, edges = exp.quantize(X2, int(o.bins))
                    import jax.numpy as jnp
                    return (jnp.asarray(codes), edges,
                            X2.shape[0], X2.shape[1])
        return _staged_or_quantize(self._X, int(o.bins))

    def _predict_codes(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        if getattr(self, "_expander", None) is not None:
            X = self._expander.transform(X)
        return bin_raw(X, self.tree.edges)

    def _bootstrap(self, n: int, n_trees: int, rng):
        mode = str(self.opts.bootstrap)
        if mode == "poisson":
            # Poisson(1) bootstrap (the streaming-bootstrap approximation
            # of multinomial resampling — per-row counts i.i.d. Poisson(1)
            # instead of jointly summing to n): generated ON DEVICE, so
            # the [E, n] int8 weights never cross h2d (~16 MB / 1-3 s of
            # relay per 1M-row forest). Documented delta: per-tree total
            # weight is n +- sqrt(n), not exactly n.
            import jax
            import jax.numpy as jnp
            key = jax.random.PRNGKey(int(self.opts.seed) + 7)
            return jax.random.poisson(key, 1.0,
                                      (n_trees, n)).astype(jnp.int8)
        if mode != "exact":
            raise ValueError(f"-bootstrap must be exact|poisson, got "
                             f"{mode!r}")
        # counts are tiny ints; int8 keeps the h2d transfer 4x smaller
        # than f32, and bincount replaces np.add.at (~100 ms/tree at 1M)
        w = np.empty((n_trees, n), np.int8)
        for e in range(n_trees):
            picks = rng.integers(0, n, n)
            w[e] = np.bincount(picks, minlength=n).astype(np.int8)
        return w


class StagedMatrix:
    """Pre-binned, device-staged feature matrix — the xgboost-DMatrix
    analog for every tree family. quantize_bins + the bins h2d transfer
    are the dominant per-fit costs that do NOT depend on the model
    (measured at 1M x 28: ~0.7 s host quantize + ~28 MB over a 5-38 MB/s
    relay); staging pays them ONCE and every RandomForest*/XGBoost*/
    GradientBoosting fit() accepts the staged object in place of X."""

    def __init__(self, binsj, edges: np.ndarray, n_bins: int):
        self.binsj = binsj                    # device [n, d] uint8 codes
        self.edges = edges                    # [d, n_bins-1] f32 (host)
        self.n_bins = int(n_bins)
        self.shape = tuple(binsj.shape)

    @classmethod
    def stage(cls, X: np.ndarray, n_bins: int = 64) -> "StagedMatrix":
        import jax.numpy as jnp
        bins, edges = quantize_bins(np.asarray(X, np.float32), n_bins)
        return cls(jnp.asarray(bins), edges, n_bins)


def _staged_or_quantize(X, n_bins: int):
    """(binsj, edges, n, d) from a raw array / row-list or StagedMatrix."""
    if isinstance(X, StagedMatrix):
        if X.n_bins != n_bins:
            raise ValueError(
                f"StagedMatrix was staged with n_bins={X.n_bins} but the "
                f"trainer wants -bins {n_bins}; re-stage with the "
                f"trainer's bin count")
        return X.binsj, X.edges, X.shape[0], X.shape[1]
    import jax.numpy as jnp
    X = np.asarray(X, np.float32)
    bins, edges = quantize_bins(X, n_bins)
    return jnp.asarray(bins), edges, X.shape[0], X.shape[1]


def _parse_attrs(spec: str, d: int) -> List[bool]:
    """-attrs 'Q,C,...' -> per-column is-categorical flags."""
    parts = [p.strip().upper() for p in str(spec).split(",")]
    if len(parts) != d:
        raise ValueError(f"-attrs lists {len(parts)} columns but the data "
                         f"has {d}")
    bad = [p for p in parts if p not in ("Q", "C")]
    if bad:
        raise ValueError(f"-attrs entries must be Q or C, got {bad[0]!r}")
    return [p == "C" for p in parts]


class CatExpander:
    """-attrs C columns as NOMINAL features: each categorical column with
    cardinality <= n_bins expands into one 0/1 membership column per
    observed category, so a single threshold split IS a set-membership
    split (value == v goes right). Ordinal binning treats categories as
    ordered — a 'perfect' single-category split in the middle of the
    sort order is then unreachable at depth 1 (SURVEY.md §3.9 -attrs
    semantics; the round-4 ordinal approximation was a documented
    delta). Categorical columns with MORE distinct values than n_bins
    keep ordinal binning (documented fallback)."""

    def __init__(self, is_cat: List[bool], X: np.ndarray, n_bins: int):
        self.plan: List[Optional[np.ndarray]] = []
        for j, c in enumerate(is_cat):
            vals = None
            if c:
                u = np.unique(X[:, j])
                u = u[np.isfinite(u)]
                if 2 <= len(u) <= n_bins:
                    vals = u.astype(np.float32)
            self.plan.append(vals)

    @property
    def active(self) -> bool:
        return any(v is not None for v in self.plan)

    def transform(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        cols = []
        for j, vals in enumerate(self.plan):
            if vals is None:
                cols.append(X[:, j:j + 1])
            else:
                cols.append((X[:, j:j + 1] == vals[None, :]
                             ).astype(np.float32))
        return np.concatenate(cols, axis=1)

    def indicator_cols(self) -> np.ndarray:
        out = []
        k = 0
        for vals in self.plan:
            w = 1 if vals is None else len(vals)
            if vals is not None:
                out.extend(range(k, k + w))
            k += w
        return np.asarray(out, np.int64)

    def quantize(self, X2: np.ndarray, n_bins: int):
        """quantize_bins on the expanded matrix, with indicator columns
        coded EXACTLY (edge row [0.5, inf...]): quantile edges of a 0/1
        column degenerate when one side is rarer than 1/n_bins, which
        would silently remove the membership split."""
        codes, edges = quantize_bins(X2, n_bins)
        ind = self.indicator_cols()
        if len(ind):
            row = np.full(n_bins - 1, np.inf, np.float32)
            row[0] = 0.5
            edges[ind] = row
            codes[:, ind] = (X2[:, ind] > 0.5).astype(np.uint8)
        return codes, edges

    def to_blob(self) -> Dict[str, np.ndarray]:
        cols = [j for j, v in enumerate(self.plan) if v is not None]
        vals = ([np.zeros(0, np.float32)] +
                [self.plan[j] for j in cols])
        offs = np.cumsum([0] + [len(self.plan[j]) for j in cols])
        return {"cat_cols": np.asarray(cols, np.int64),
                "cat_vals": np.concatenate(vals).astype(np.float32),
                "cat_offs": offs.astype(np.int64),
                "cat_ncols": np.int64(len(self.plan))}

    @classmethod
    def from_blob(cls, extra: Dict) -> Optional["CatExpander"]:
        if "cat_cols" not in extra:
            return None
        self = cls.__new__(cls)
        ncols = int(extra["cat_ncols"])
        plan: List[Optional[np.ndarray]] = [None] * ncols
        offs = np.asarray(extra["cat_offs"])
        vals = np.asarray(extra["cat_vals"], np.float32)
        for i, j in enumerate(np.asarray(extra["cat_cols"])):
            plan[int(j)] = vals[offs[i]:offs[i + 1]]
        self.plan = plan
        return self


class RandomForestClassifier(_ForestBase):
    """SQL: train_randomforest_classifier — reference
    hivemall.smile.classification.RandomForestClassifierUDTF."""

    SPEC_NAME = "train_randomforest_classifier"

    def _train(self) -> None:
        o = self.opts
        labels = np.asarray(self._y).astype(np.int64)
        classes = np.unique(labels)
        self.classes_ = classes
        y = np.searchsorted(classes, labels)
        C = len(classes)
        # one h2d; build + OOB share it (or zero h2d with a StagedMatrix)
        binsj, edges, n, d = self._features_for_train()
        rng = np.random.default_rng(int(o.seed))
        E = int(o.trees)
        mtry = int(o["vars"]) or max(1, int(np.sqrt(d)))
        w = self._bootstrap(n, E, rng)
        import jax.numpy as jnp
        mesh = None
        if o.mesh:
            from ..parallel.mesh import make_mesh, parse_mesh_spec
            dp, tp = parse_mesh_spec(str(o.mesh))
            if tp != 1:
                raise ValueError("tree ensembles shard over dp only "
                                 f"(got tp={tp})")
            mesh = make_mesh(dp=dp)
        self.tree, node_dev, v_dev = build_tree_classifier(
            binsj, y, w, edges, C, depth=int(o.depth), n_bins=int(o.bins),
            mtry=mtry, min_split=float(o.min_split),
            min_leaf=float(o.min_leaf), seed=int(o.seed), n_trees=E,
            mesh=mesh, return_nodes=True)
        # out-of-bag error per tree, ON DEVICE, from the builder's OWN row
        # routing: the builder already walked every row to its final node
        # (weights don't affect routing), so OOB is one small-table class
        # lookup per (tree, row) instead of re-predicting the whole forest
        # — the level-sweep re-predict measured 0.9 s of the 2.4 s warm
        # 1M-row fit (experiments/probe_rf_warm.py). Only [E] floats d2h.
        import jax
        wj = jnp.asarray(w)
        yj = jnp.asarray(y)
        if node_dev is not None:
            pcls = jnp.argmax(v_dev, -1)                         # [E, Nn]
            pe = jax.vmap(lambda p, nd: p[nd])(pcls, node_dev)   # [E, n]
        else:
            # mesh path: the sharded builder doesn't carry node ids
            from hivemall_tpu.ops.trees import predict_bins_device
            pe = predict_bins_device(self.tree, binsj).argmax(-1)
        oob = wj == 0
        n_oob = jnp.maximum(oob.sum(1), 1)
        err = ((pe != yj[None, :]) & oob).sum(1) / n_oob
        err = jnp.where(oob.sum(1) == 0, 0.0, err)
        self.oob_errors = [float(v) for v in np.asarray(err)]

    def _blob_extra(self) -> Dict:
        extra = super()._blob_extra()
        extra["classes"] = self.classes_
        return extra

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        counts = predict_bins(self.tree, self._predict_codes(X))
        probs = counts / np.maximum(counts.sum(-1, keepdims=True), 1e-12)
        return probs.mean(0)

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.classes_[self.predict_proba(X).argmax(-1)]


class RandomForestRegressor(_ForestBase):
    """SQL: train_randomforest_regressor — reference
    hivemall.smile.regression.RandomForestRegressionUDTF."""

    SPEC_NAME = "train_randomforest_regressor"

    def _train(self) -> None:
        o = self.opts
        y = np.asarray(self._y, np.float32)
        binsj, edges, n, d = self._features_for_train()
        rng = np.random.default_rng(int(o.seed))
        E = int(o.trees)
        mtry = int(o["vars"]) or max(1, d // 3)
        w = self._bootstrap(n, E, rng)
        self.tree, node_dev, v_dev = build_tree_regressor(
            binsj, y, w, edges, depth=int(o.depth), n_bins=int(o.bins),
            mtry=mtry, min_split=float(o.min_split),
            min_leaf=float(o.min_leaf), seed=int(o.seed), n_trees=E,
            return_nodes=True)
        # per-tree OOB MSE ON DEVICE from the builder's own row routing
        # (see the classifier: no forest re-predict); only [E] floats d2h
        import jax
        import jax.numpy as jnp
        v0 = v_dev[..., 0]                               # [E, Nn] means
        preds = jax.vmap(lambda p, nd: p[nd])(v0, node_dev)
        wj = jnp.asarray(w)
        yj = jnp.asarray(y)
        oob = wj == 0
        n_oob = jnp.maximum(oob.sum(1), 1)
        mse = (((preds - yj[None, :]) ** 2) * oob).sum(1) / n_oob
        mse = jnp.where(oob.sum(1) == 0, 0.0, mse)
        self.oob_errors = [float(v) for v in np.asarray(mse)]

    def predict(self, X: np.ndarray) -> np.ndarray:
        vals = predict_bins(self.tree, self._predict_codes(X))[..., 0]
        return vals.mean(0)


# --- gradient boosting (xgboost-capability parity, SURVEY.md §3.9 callout) --

def _gb_spec(name: str) -> OptionSpec:
    s = OptionSpec(name)
    s.add("num_round", "iters", type=int, default=30, help="boosting rounds")
    s.add("eta", "shrinkage", type=float, default=0.3, help="learning rate")
    s.add("max_depth", "depth", type=int, default=6, help="tree depth")
    s.add("lambda", type=float, default=1.0, help="L2 on leaf weights")
    s.add("colsample_bytree", "colsample", type=float, default=1.0,
          help="feature subsample per split scan")
    s.add("subsample", type=float, default=1.0,
          help="row subsample per round")
    s.add("min_child_weight", type=float, default=1.0,
          help="min hessian per child")
    s.add("bins", type=int, default=64, help="histogram bins")
    s.add("seed", type=int, default=7, help="rng seed")
    s.add("objective", default=None, help="binary:logistic | reg:squarederror"
                                          " | multi:softmax")
    s.add("num_class", type=int, default=0, help="multiclass class count")
    return s


class GradientBoosting:
    """Histogram GBDT with XGBoost semantics (second-order gains, shrinkage,
    colsample) — the native-performance replacement for the libxgboost JNI
    wrapper (SURVEY.md §3.9: 'native-performance equivalent, not a Python
    stand-in'; training runs as jitted TPU kernels)."""

    NAME = "train_gradient_boosting"
    DEFAULT_OBJECTIVE = "binary:logistic"

    @classmethod
    def spec(cls) -> OptionSpec:
        return _gb_spec(cls.NAME)

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self.objective = self.opts.objective or self.DEFAULT_OBJECTIVE
        self._X: List = []
        self._y: List = []
        self.trees: List[Tree] = []
        self.base_score = 0.0

    # UDTF lifecycle (buffer-all then boost at close, like the XGBoostUDTF)
    def process(self, features: Sequence[float], label) -> None:
        self._X.append([float(v) for v in features])
        self._y.append(float(label))

    def close(self) -> Iterator[Tuple[int, str]]:
        if self._X:                  # refit only from buffered rows; a prior
            self.fit(np.asarray(self._X, np.float32), np.asarray(self._y))
        for r, tree in enumerate(self.trees):
            yield (r, serialize_tree(tree, 0,
                                     {"eta": np.float32(self.eta),
                                      "base": np.float32(self.base_score),
                                      "objective": np.frombuffer(
                                          self.objective.encode(), np.uint8)}))

    def fit(self, X: np.ndarray, y: np.ndarray) -> "GradientBoosting":
        # the WHOLE R-round boosting chain is one jitted lax.scan dispatch
        # (ops.trees.boost_loop_xgb): round 3's round-serial loop paid
        # several ~100 ms host-synced dispatches per round, which — not the
        # histogram math — bounded GBT at ~26k rows/s (VERDICT r3 weak #5)
        import jax
        import jax.numpy as jnp
        o = self.opts
        if self.objective == "multi:softmax":
            raise ValueError(
                "multi:softmax is the multiclass trainer's objective — use "
                "XGBoostMulticlassClassifier "
                "(train_multiclass_xgboost_classifier)")
        y = np.asarray(y, np.float32)
        if self.objective == "binary:logistic":
            y = (y > 0).astype(np.float32)
        self.eta = float(o.eta)
        binsj, edges, n, d = _staged_or_quantize(X, int(o.bins))
        mtry = colsample_mtry(float(o.colsample_bytree), d)
        loop = boost_loop_xgb(self.objective, int(o.num_round),
                              int(o.max_depth), int(o.bins), mtry,
                              float(o.min_child_weight), float(o["lambda"]),
                              self.eta, float(o.subsample),
                              use_pallas_default())
        packed, _ = loop(binsj, jnp.asarray(y),
                         self.base_score,
                         jax.random.PRNGKey(int(o.seed)))
        # the single np.asarray fetch IS the device sync (block_until_ready
        # does not synchronize through the relay)
        packed = np.asarray(packed)
        vs, fs, ts = (packed[..., :3], packed[..., 3].astype(np.int32),
                      packed[..., 4].astype(np.uint8))
        self.trees = [Tree(fs[r][None], ts[r][None], vs[r][None], edges)
                      for r in range(fs.shape[0])]
        return self

    def decision_function(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        out = np.full(X.shape[0], self.base_score, np.float32)
        for tree in self.trees:
            # output path: host accumulation over a SMALL round count —
            # the per-tree score fetch is the boosted-ensemble design
            # graftcheck: disable=GC07
            out += self.eta * predict_bins(          # graftcheck: disable=GC07
                tree, bin_raw(X, tree.edges))[0, :, 0]  # graftcheck: disable=GC07
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        m = self.decision_function(X)
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-m))
        return m


class XGBoostClassifier(GradientBoosting):
    """SQL: train_xgboost_classifier — reference hivemall.xgboost.XGBoostUDTF
    (binary logistic)."""
    NAME = "train_xgboost_classifier"
    DEFAULT_OBJECTIVE = "binary:logistic"


class XGBoostRegressor(GradientBoosting):
    """SQL: train_xgboost_regr — squared-error boosting."""
    NAME = "train_xgboost_regr"
    DEFAULT_OBJECTIVE = "reg:squarederror"


class XGBoostMulticlassClassifier(GradientBoosting):
    """SQL: train_multiclass_xgboost_classifier — softmax boosting, one tree
    per class per round."""
    NAME = "train_multiclass_xgboost_classifier"
    DEFAULT_OBJECTIVE = "multi:softmax"

    def fit(self, X: np.ndarray, y: np.ndarray):
        # one fused scan dispatch for all rounds x classes: each round
        # vmaps the builder over the per-class (g, h) stacks (one-vs-rest
        # softmax rounds, same structure as the reference XGBoostUDTF)
        import jax
        import jax.numpy as jnp
        o = self.opts
        labels = np.asarray(y).astype(np.int64)
        self.classes_ = np.unique(labels)
        yc = np.searchsorted(self.classes_, labels)
        C = len(self.classes_)
        self.eta = float(o.eta)
        binsj, edges, n, d = _staged_or_quantize(X, int(o.bins))
        mtry = colsample_mtry(float(o.colsample_bytree), d)
        loop = boost_loop_xgb("multi:softmax", int(o.num_round),
                              int(o.max_depth), int(o.bins), mtry,
                              float(o.min_child_weight), float(o["lambda"]),
                              self.eta, float(o.subsample),
                              use_pallas_default(), n_class=C)
        packed, _ = loop(binsj,
                         jnp.asarray(yc.astype(np.float32)), 0.0,
                         jax.random.PRNGKey(int(o.seed)))
        packed = np.asarray(packed)          # one fetch for all R x C trees
        vs, fs, ts = (packed[..., :3], packed[..., 3].astype(np.int32),
                      packed[..., 4].astype(np.uint8))
        self.trees = [[Tree(fs[r, c][None], ts[r, c][None], vs[r, c][None],
                            edges) for c in range(C)]
                      for r in range(fs.shape[0])]
        return self

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float32)
        C = len(self.classes_)
        margin = np.zeros((X.shape[0], C), np.float32)
        for round_trees in self.trees:
            for c, tree in enumerate(round_trees):
                # output path: per-tree host accumulation (see
                # decision_function) — bounded by rounds x classes
                # graftcheck: disable=GC07
                margin[:, c] += self.eta * predict_bins(  # graftcheck: disable=GC07
                    tree, bin_raw(X, tree.edges))[0, :, 0]  # graftcheck: disable=GC07
        e = np.exp(margin - margin.max(1, keepdims=True))
        return e / e.sum(1, keepdims=True)

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.classes_[self.predict_proba(X).argmax(-1)]

    def close(self) -> Iterator[Tuple[int, str]]:
        """Emit one row per (round, class) tree — the base close() expects a
        flat tree list and cannot serialize the per-class nesting."""
        if self._X:                  # direct fit() then close() serializes
            self.fit(np.asarray(self._X, np.float32), np.asarray(self._y))
        mid = 0
        for round_trees in self.trees:
            for c, tree in enumerate(round_trees):
                yield (mid, serialize_tree(
                    tree, 0,
                    {"eta": np.float32(self.eta),
                     "cls": np.int32(self.classes_[c]),
                     "objective": np.frombuffer(
                         self.objective.encode(), np.uint8)}))
                mid += 1


# --- SQL-side predict / ensemble / attr helpers ----------------------------

def tree_predict(model_blob: str, features: Sequence[float],
                 classification: bool = True):
    """SQL: tree_predict(model, features[, classification]) — reference
    hivemall.smile.tools.TreePredictUDF (StackMachine VM -> gather walk)."""
    tree, extra = deserialize_tree(model_blob)
    X = np.asarray([features], np.float32)
    exp = CatExpander.from_blob(extra)
    if exp is not None:
        X = exp.transform(X)
    out = predict_bins(tree, bin_raw(X, tree.edges))[0, 0]
    if "eta" in extra:               # boosting tree: raw leaf value
        if "cls" in extra:           # multiclass softmax: (class, leaf) so
            # the SQL pattern GROUP BY rowid, cls / sum(leaf) / argmax works
            return int(extra["cls"]), float(out[0])
        return float(out[0])
    if classification:
        cls = extra.get("classes")
        k = int(np.argmax(out))
        return int(cls[k]) if cls is not None else k
    return float(out[0])


def tree_model_meta(model_blob: str) -> Dict:
    """Scalar metadata of a serialized tree blob (eta, base, cls, objective)
    — what a scorer needs to assemble per-tree leaves into a prediction."""
    _, extra = deserialize_tree(model_blob)
    meta: Dict = {}
    for k in ("eta", "base", "cls"):
        if k in extra:
            meta[k] = extra[k].item() if hasattr(extra[k], "item") \
                else extra[k]
    if "objective" in extra:
        meta["objective"] = bytes(np.asarray(extra["objective"])
                                  .tobytes()).decode()
    return meta


def rf_ensemble(predictions: Sequence) -> Tuple[object, float, List[float]]:
    """SQL: rf_ensemble(yhat) UDAF — majority vote over per-tree predictions;
    returns (label, probability, per-class distribution). Reference:
    hivemall.smile.tools.RandomForestEnsembleUDAF."""
    preds = list(predictions)
    uniq = sorted(set(preds))
    counts = np.asarray([preds.count(u) for u in uniq], np.float64)
    probs = counts / counts.sum()
    k = int(np.argmax(counts))
    return uniq[k], float(probs[k]), probs.tolist()


def guess_attribute_types(*values) -> str:
    """SQL: guess_attribute_types(col1, ...) — emit 'Q,C,...' spec.
    Reference: hivemall.smile.tools.GuessAttributesUDF."""
    out = []
    for v in values:
        out.append("Q" if isinstance(v, (int, float))
                   and not isinstance(v, bool) else "C")
    return ",".join(out)
