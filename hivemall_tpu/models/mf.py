"""Matrix factorization — train_mf_sgd / train_mf_adagrad / train_bprmf
(BASELINE config #3).

Reference (SURVEY.md §3.7): hivemall.mf.OnlineMatrixFactorizationUDTF (base:
streaming (user, item, rating) SGD over rank-k P/Q tables with biases and
global mean -mu), MatrixFactorizationSGDUDTF / MatrixFactorizationAdaGradUDTF,
BPRMatrixFactorizationUDTF (implicit feedback (u, pos, neg) ranking), and the
MFPredictUDF / BPRMFPredictUDF scorers.

TPU shape: P[U,K], Q[I,K], b_u[U], b_i[I] dense tables in HBM; one jitted
value_and_grad step per (user, item, rating) minibatch; within-batch duplicate
ids accumulate via scatter-add (gradient accumulation of the reference's
sequential per-row updates).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.options import OptionSpec, Parsed

__all__ = ["MFTrainer", "MFAdaGradTrainer", "BPRMFTrainer", "mf_predict",
           "bprmf_predict"]


def _mf_spec(name: str) -> OptionSpec:
    s = OptionSpec(name)
    s.add("factors", "factor", type=int, default=10, help="rank k")
    s.add("mu", "mean_rating", type=float, default=0.0, help="global mean")
    s.add("eta0", "eta", type=float, default=0.01, help="learning rate")
    s.add("lambda", type=float, default=0.03, help="L2 regularization")
    s.add("iters", "iterations", type=int, default=1, help="epochs")
    s.add("mini_batch", type=int, default=1024, help="minibatch size")
    s.add("users", "max_users", type=int, default=1 << 20,
          help="user table size")
    s.add("items", "max_items", type=int, default=1 << 20,
          help="item table size")
    s.add("sigma", type=float, default=0.1, help="factor init stddev")
    s.add("seed", type=int, default=31, help="init seed")
    s.flag("disable_bias", help="drop user/item bias terms")
    s.flag("halffloat", help="bf16 factor tables")
    s.add("mesh", default=None,
          help="shard training over a device mesh, e.g. 'dp=2,tp=4' "
               "(batch over dp, P/Q/bias tables over tp) or 'auto'")
    return s


class MFTrainer:
    """SQL: train_mf_sgd — reference hivemall.mf.MatrixFactorizationSGDUDTF."""

    NAME = "train_mf_sgd"
    ADAGRAD = False

    @classmethod
    def spec(cls) -> OptionSpec:
        return _mf_spec(cls.NAME)

    def __init__(self, options: str = ""):
        self.opts: Parsed = self.spec().parse(options)
        o = self.opts
        self.k = int(o.factors)
        # bracket access: "items" would hit dict.items on the Parsed namespace
        self.U, self.I = int(o["users"]), int(o["items"])
        dtype = jnp.bfloat16 if o.halffloat else jnp.float32
        key = jax.random.PRNGKey(int(o.seed))
        k1, k2 = jax.random.split(key)
        sig = float(o.sigma)
        self.params = {
            "P": (jax.random.normal(k1, (self.U, self.k)) * sig).astype(dtype),
            "Q": (jax.random.normal(k2, (self.I, self.k)) * sig).astype(dtype),
            "bu": jnp.zeros(self.U, jnp.float32),
            "bi": jnp.zeros(self.I, jnp.float32),
        }
        self.gg = ({k: jnp.zeros(v.shape, jnp.float32)
                    for k, v in self.params.items()} if self.ADAGRAD else None)
        self.mesh = None
        if o.mesh:
            self._apply_mesh(str(o.mesh))
        self._step = self._make_step()
        self._t = 0
        self._buf: List[Tuple[int, int, float]] = []
        self._all: List[Tuple[int, int, float]] = []
        # device-side loss accumulation: fetching the loss value every step
        # would put one host round-trip on each dispatch (the step itself is
        # async); fold into the host float sparingly instead
        self._loss_pending = jnp.zeros(())
        self._loss_host = 0.0
        self.n_seen = 0

    # -- mesh sharding (SURVEY.md §3.17): batch over dp, tables over tp ------
    def _apply_mesh(self, spec: str) -> None:
        """GSPMD-shard the MF state: P/Q factor tables and biases split
        their id axis over 'tp' (feature-dim sharding), minibatches split
        rows over 'dp' (XLA inserts the gradient psum). The same jitted
        step runs unchanged — mirrors LearnerBase._apply_mesh."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import make_mesh, parse_mesh_spec
        dp, tp = parse_mesh_spec(spec)
        if int(self.opts.mini_batch) % dp:
            raise ValueError(
                f"-mini_batch {self.opts.mini_batch} must be divisible by "
                f"the dp axis ({dp})")
        self.mesh = make_mesh(dp=dp, tp=tp)

        def shard(v):
            spec_ = P(*(["tp"] + [None] * (v.ndim - 1)))
            return jax.device_put(v, NamedSharding(self.mesh, spec_))
        self.params = {k: shard(v) for k, v in self.params.items()}
        if self.gg is not None:
            self.gg = {k: shard(v) for k, v in self.gg.items()}

    def _shard_inputs(self, arrays):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return tuple(jax.device_put(a, NamedSharding(self.mesh, P("dp")))
                     for a in arrays)

    def _make_step(self):
        o = self.opts
        lam = float(o["lambda"])
        eta0 = float(o.eta0)
        mu = float(o.mu)
        use_bias = not o.disable_bias
        adagrad = self.ADAGRAD

        @jax.jit
        def step(params, gg, t, u, i, r, mask):
            def batch_loss(p):
                pu = p["P"][u].astype(jnp.float32)        # [B, K]
                qi = p["Q"][i].astype(jnp.float32)
                pred = mu + (pu * qi).sum(-1)
                if use_bias:
                    pred = pred + p["bu"][u] + p["bi"][i]
                err = (r - pred) * mask
                reg = lam * ((pu * pu).sum() + (qi * qi).sum()
                             + ((p["bu"][u] ** 2).sum()
                                + (p["bi"][i] ** 2).sum() if use_bias else 0.0))
                return 0.5 * (err * err).sum() + reg

            loss, grads = jax.value_and_grad(batch_loss)(params)
            new_p, new_gg = {}, {}
            for k in params:
                g = grads[k].astype(jnp.float32)
                if adagrad:
                    g2 = gg[k] + g * g
                    upd = eta0 * g / (jnp.sqrt(g2) + 1e-6)
                    new_gg[k] = g2
                else:
                    upd = eta0 * g
                new_p[k] = (params[k].astype(jnp.float32) - upd
                            ).astype(params[k].dtype)
            return new_p, (new_gg if adagrad else gg), loss

        return step

    # -- UDTF lifecycle ------------------------------------------------------
    def process(self, user: int, item: int, rating: float) -> None:
        self._buf.append((int(user), int(item), float(rating)))
        if len(self._buf) >= int(self.opts.mini_batch):
            self._flush()

    # -- full-state checkpointing (io.checkpoint bundles, SURVEY.md §6) ------
    # Bundles capture model + optimizer state and counters; the -iters
    # replay buffer is NOT serialized (matching the reference, where task
    # retry replays the input split rather than restoring scratch).
    def _checkpoint_arrays(self):
        tree = {"params": self.params}
        if self.gg is not None:
            tree["gg"] = self.gg
        return tree

    def _restore_arrays(self, tree) -> None:
        self.params = tree["params"]
        if "gg" in tree:
            self.gg = tree["gg"]

    def _checkpoint_scalars(self):
        return {"cum_loss": self.cum_loss, "n_seen": self.n_seen}

    def _restore_scalars(self, scalars) -> None:
        self._loss_host = float(scalars["cum_loss"])
        self._loss_pending = jnp.zeros(())
        self.n_seen = int(scalars["n_seen"])

    def save_bundle(self, path: str) -> None:
        from ..io.checkpoint import save_bundle
        self._flush()                  # buffered rows train before we snapshot
        save_bundle(self, path)

    def load_bundle(self, path: str) -> None:
        from ..io.checkpoint import load_bundle
        load_bundle(self, path)

    def _flush(self) -> None:
        if not self._buf:
            return
        chunk = self._buf
        self._buf = []
        if int(self.opts.iters) > 1:
            self._all.extend(chunk)
        self._dispatch(chunk)

    def _dispatch(self, chunk: List[Tuple[int, int, float]]) -> None:
        B = int(self.opts.mini_batch)
        u = np.zeros(B, np.int32)
        i = np.zeros(B, np.int32)
        r = np.zeros(B, np.float32)
        m = np.zeros(B, np.float32)
        n = len(chunk)
        u[:n] = [c[0] for c in chunk]
        i[:n] = [c[1] for c in chunk]
        r[:n] = [c[2] for c in chunk]
        m[:n] = 1.0
        if self.mesh is not None:
            u, i, r, m = self._shard_inputs((u, i, r, m))
        self.params, self.gg, loss = self._step(
            self.params, self.gg, float(self._t), u, i, r, m)
        self._post_step(loss, n)

    def _post_step(self, loss, n: int) -> None:
        self._t += 1
        self._loss_pending = self._loss_pending + loss
        if self._t % 256 == 0:
            self._fold_loss()
        self.n_seen += n

    def _fold_loss(self) -> None:
        self._loss_host += float(self._loss_pending)
        self._loss_pending = jnp.zeros(())

    @property
    def cum_loss(self) -> float:
        self._fold_loss()
        return self._loss_host

    def close(self) -> Iterator[Tuple]:
        self._flush()
        iters = int(self.opts.iters)
        if iters > 1 and self._all:
            rng = np.random.default_rng(42)
            bs = int(self.opts.mini_batch)
            for ep in range(1, iters):
                order = rng.permutation(len(self._all))
                for s in range(0, len(order), bs):
                    self._dispatch([self._all[j] for j in order[s:s + bs]])
        yield from self.model_rows()

    # third fit column dtype: ratings (f32) for explicit MF, the negative
    # ITEM ID (i32) for BPR — lets the columnar fast path below serve both
    _COL3_DTYPE = np.float32

    def fit(self, users: np.ndarray, items: np.ndarray, ratings: np.ndarray,
            *, epochs: Optional[int] = None, shuffle: bool = True
            ) -> "MFTrainer":
        epochs = int(self.opts.iters) if epochs is None else epochs
        bs = int(self.opts.mini_batch)
        n = len(users)
        rng = np.random.default_rng(42)
        if self.mesh is not None or n < bs:
            # sharded placement (and tiny inputs) keep the row path
            for ep in range(epochs):
                order = rng.permutation(n) if shuffle else np.arange(n)
                for s in range(0, n, bs):
                    take = order[s:s + bs]
                    self._dispatch(list(zip(users[take], items[take],
                                            ratings[take])))
            return self
        # columnar fast path: the row path built THREE 65k-element python
        # lists per step and re-crossed h2d every batch (measured: it held
        # train_mf at ~750k ex/s while the step alone sustains multiples).
        # Stage each epoch's permuted columns on device ONCE and feed the
        # step device slices; the short tail reuses the row path.
        # Callers may pass DEVICE arrays (jnp) to skip the h2d entirely
        # across repeated fits — shuffling then permutes on device.
        dev_in = not isinstance(users, np.ndarray) and hasattr(
            users, "devices")
        if dev_in:
            u = jnp.asarray(users, jnp.int32)
            i = jnp.asarray(items, jnp.int32)
            r = jnp.asarray(ratings, self._COL3_DTYPE)
        else:
            u = np.ascontiguousarray(users, np.int32)
            i = np.ascontiguousarray(items, np.int32)
            r = np.ascontiguousarray(ratings, self._COL3_DTYPE)
        md = jnp.ones(bs, jnp.float32)
        ud = id_ = rd = None              # staged once unless shuffling
        nb = n - n % bs
        for ep in range(epochs):
            if shuffle:
                order = rng.permutation(n)
                if dev_in:
                    oj = jnp.asarray(order.astype(np.int32))
                    uo = io_ = ro = None        # device-side permute
                    ud, id_, rd = u[oj], i[oj], r[oj]
                else:
                    uo, io_, ro = u[order], i[order], r[order]
                    ud, id_, rd = (jnp.asarray(uo), jnp.asarray(io_),
                                   jnp.asarray(ro))
            else:
                uo, io_, ro = u, i, r
                if ud is None:            # identical columns: ONE h2d
                    ud, id_, rd = (jnp.asarray(u), jnp.asarray(i),
                                   jnp.asarray(r))
            for s in range(0, nb, bs):
                self.params, self.gg, loss = self._step(
                    self.params, self.gg, float(self._t),
                    ud[s:s + bs], id_[s:s + bs], rd[s:s + bs], md)
                self._post_step(loss, bs)
            if nb < n:
                if uo is None or not isinstance(uo, np.ndarray):
                    # device input: fetch ONLY the tail rows for the row
                    # path, not the whole permuted columns — a bounded
                    # once-per-epoch remainder fetch, not per step
                    # graftcheck: disable=GC07
                    tails = (np.asarray(ud[nb:]), np.asarray(id_[nb:]),
                             np.asarray(rd[nb:]))  # graftcheck: disable=GC07
                else:
                    tails = (uo[nb:], io_[nb:], ro[nb:])
                self._dispatch(list(zip(*tails)))
        return self

    # -- scoring / emission --------------------------------------------------
    def predict(self, users, items) -> np.ndarray:
        p = self.params
        u = np.asarray(users, np.int32)
        i = np.asarray(items, np.int32)
        pu = np.asarray(p["P"].astype(jnp.float32))[u]
        qi = np.asarray(p["Q"].astype(jnp.float32))[i]
        out = float(self.opts.mu) + (pu * qi).sum(-1)
        if not self.opts.disable_bias:
            out = out + np.asarray(p["bu"])[u] + np.asarray(p["bi"])[i]
        return out.astype(np.float32)

    # -- weight-arena publishing (io.weight_arena "factor" family) -----------
    def serving_tables(self) -> Tuple[dict, Dict[str, np.ndarray]]:
        """Arena serving surface: the finalized f32 factor tables and the
        score recipe ``mu + P[u].Q[i] (+ bu[u] + bi[i])``, consumed by the
        retrieval plane (serve/retrieve.py) rather than the SparseBatch
        margin kernels — factor scoring gathers TWO embedding rows per
        pair instead of one weight row per feature."""
        self._flush()                  # buffered rows train before export
        p = self.params
        use_bias = not self.opts.disable_bias
        meta = {"family": "factor", "k": self.k,
                "mu": float(self.opts.mu),
                "user_bias": use_bias, "item_bias": use_bias,
                "classification": False}
        tables = {"P": np.asarray(p["P"].astype(jnp.float32)),
                  "Q": np.asarray(p["Q"].astype(jnp.float32))}
        if use_bias:
            tables["bu"] = np.asarray(p["bu"], np.float32)
            tables["bi"] = np.asarray(p["bi"], np.float32)
        return meta, tables

    def model_rows(self) -> Iterator[Tuple]:
        """(idx, Pu|None, Qi|None, bu, bi) rows, users then items, only
        touched ids (nonzero factors)."""
        P = np.asarray(self.params["P"].astype(jnp.float32))
        Q = np.asarray(self.params["Q"].astype(jnp.float32))
        bu = np.asarray(self.params["bu"])
        bi = np.asarray(self.params["bi"])
        for uid in np.nonzero(np.abs(P).sum(-1) > 0)[0]:
            yield (int(uid), P[uid].tolist(), None, float(bu[uid]), None)
        for iid in np.nonzero(np.abs(Q).sum(-1) > 0)[0]:
            yield (int(iid), None, Q[iid].tolist(), None, float(bi[iid]))


class MFAdaGradTrainer(MFTrainer):
    """SQL: train_mf_adagrad — reference hivemall.mf.MatrixFactorizationAdaGradUDTF."""
    NAME = "train_mf_adagrad"
    ADAGRAD = True


class BPRMFTrainer(MFTrainer):
    """SQL: train_bprmf — reference hivemall.mf.BPRMatrixFactorizationUDTF.

    Implicit feedback: rows are (user, pos_item, neg_item); loss is
    -log sigmoid(x_upos - x_uneg) with x_ui = p_u.q_i + b_i (item bias only).
    """
    NAME = "train_bprmf"
    ADAGRAD = False
    _COL3_DTYPE = np.int32       # third fit column = negative item id

    def _make_step(self):
        o = self.opts
        lam = float(o["lambda"])
        eta0 = float(o.eta0)

        @jax.jit
        def step(params, gg, t, u, i, j, mask):
            def batch_loss(p):
                pu = p["P"][u].astype(jnp.float32)
                qi = p["Q"][i].astype(jnp.float32)
                qj = p["Q"][j].astype(jnp.float32)
                x = ((pu * (qi - qj)).sum(-1)
                     + p["bi"][i] - p["bi"][j])
                nll = jax.nn.softplus(-x) * mask
                reg = lam * ((pu * pu).sum() + (qi * qi).sum()
                             + (qj * qj).sum()
                             + (p["bi"][i] ** 2).sum()
                             + (p["bi"][j] ** 2).sum())
                return nll.sum() + reg

            loss, grads = jax.value_and_grad(batch_loss)(params)
            new_p = {k: (params[k].astype(jnp.float32)
                         - eta0 * grads[k].astype(jnp.float32)
                         ).astype(params[k].dtype) for k in params}
            return new_p, gg, loss

        return step

    def process(self, user: int, pos_item: int, neg_item: int) -> None:
        # third slot carries the negative item id (int), not a rating
        super().process(user, pos_item, float(neg_item))

    def _dispatch(self, chunk) -> None:
        B = int(self.opts.mini_batch)
        u = np.zeros(B, np.int32)
        i = np.zeros(B, np.int32)
        j = np.zeros(B, np.int32)
        m = np.zeros(B, np.float32)
        n = len(chunk)
        u[:n] = [c[0] for c in chunk]
        i[:n] = [c[1] for c in chunk]
        j[:n] = [int(c[2]) for c in chunk]
        m[:n] = 1.0
        if self.mesh is not None:
            u, i, j, m = self._shard_inputs((u, i, j, m))
        self.params, self.gg, loss = self._step(
            self.params, self.gg, float(self._t), u, i, j, m)
        self._post_step(loss, n)

    def predict(self, users, items) -> np.ndarray:
        p = self.params
        u = np.asarray(users, np.int32)
        i = np.asarray(items, np.int32)
        pu = np.asarray(p["P"].astype(jnp.float32))[u]
        qi = np.asarray(p["Q"].astype(jnp.float32))[i]
        return ((pu * qi).sum(-1) + np.asarray(p["bi"])[i]).astype(np.float32)

    def serving_tables(self) -> Tuple[dict, Dict[str, np.ndarray]]:
        """BPR's score has no global mean and no user bias — the pairwise
        ranking loss cancels both; only the item bias survives."""
        self._flush()
        p = self.params
        meta = {"family": "factor", "k": self.k, "mu": 0.0,
                "user_bias": False, "item_bias": True,
                "classification": False}
        tables = {"P": np.asarray(p["P"].astype(jnp.float32)),
                  "Q": np.asarray(p["Q"].astype(jnp.float32)),
                  "bi": np.asarray(p["bi"], np.float32)}
        return meta, tables


# --- predict UDFs (join-side reassembly, SURVEY.md §3.7 row 5) -------------

def mf_predict(pu: Optional[List[float]], qi: Optional[List[float]],
               bu: Optional[float] = None, bi: Optional[float] = None,
               mu: float = 0.0) -> float:
    """SQL: mf_predict(Pu, Qi, Bu, Bi, mu) — reference hivemall.mf.MFPredictUDF.
    Missing user/item rows fall back to the known parts (cold start)."""
    out = float(mu)
    if bu is not None:
        out += float(bu)
    if bi is not None:
        out += float(bi)
    if pu is not None and qi is not None:
        out += float(np.dot(np.asarray(pu, np.float64),
                            np.asarray(qi, np.float64)))
    return out


def bprmf_predict(pu: Optional[List[float]], qi: Optional[List[float]],
                  bi: Optional[float] = None) -> float:
    """SQL: bprmf_predict — reference hivemall.mf.BPRMFPredictUDF."""
    out = 0.0 if bi is None else float(bi)
    if pu is not None and qi is not None:
        out += float(np.dot(np.asarray(pu, np.float64),
                            np.asarray(qi, np.float64)))
    return out
