"""Multiclass online linear trainers (SURVEY.md §3.4).

Reference: hivemall.classifier.multiclass.{MulticlassPerceptronUDTF,
MulticlassPassiveAggressiveUDTF (+PA1/PA2), MulticlassConfidenceWeightedUDTF,
MulticlassAROWClassifierUDTF, MulticlassSoftConfidenceWeightedUDTF (+scw2)}.
Same row shape as the binary family but the label is a class (int|string) and
model rows are (label, feature, weight[, covar]).

Update scheme (Crammer's multiclass PA / CW): score every class, find the
true class and the highest-scoring wrong class; the closed-form step uses the
margin DIFFERENCE and pushes the true row up / the rival row down. Per-batch
deltas aggregate by scatter-add as in the binary family (minibatch=1 ==
reference semantics).

W is a [C_max, N] table; class labels map to rows on first sight, so the jit
shape stays static while the label set grows dynamically.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.sparse import SparseBatch, pow2_len, split_feature
from ..utils.options import OptionSpec
from .classifier import _cw_beta, _online_spec, _phi_of

__all__ = ["MulticlassPerceptronTrainer", "MulticlassPATrainer",
           "MulticlassPA1Trainer", "MulticlassPA2Trainer",
           "MulticlassCWTrainer", "MulticlassAROWTrainer",
           "MulticlassSCWTrainer", "MulticlassSCW2Trainer"]


def _mc_spec(name: str) -> OptionSpec:
    s = _online_spec(name)
    s.add("classes", "max_classes", type=int, default=64,
          help="class-table capacity (rows allocated in W)")
    return s


class _MulticlassBase:
    NAME = "train_multiclass"
    HAS_COVAR = False

    @classmethod
    def spec(cls) -> OptionSpec:
        return _mc_spec(cls.NAME)

    def __init__(self, options: str = ""):
        self.opts = self.spec().parse(options)
        self.dims = int(self.opts.dims)
        self.C = int(self.opts.classes)
        self.W = jnp.zeros((self.C, self.dims), jnp.float32)
        self.sigma = jnp.ones((self.C, self.dims), jnp.float32) \
            if self.HAS_COVAR else None
        self._labels: Dict[object, int] = {}
        self._names: Dict[int, str] = {}
        self._buf: List[Tuple[np.ndarray, np.ndarray, int]] = []
        mode = str(getattr(self.opts, "batch_mode", "aggregate"))
        if mode not in ("aggregate", "sequential"):
            raise ValueError(f"-batch_mode must be aggregate|sequential, "
                             f"got {mode!r}")
        from .base import shared_step
        self._step = shared_step(
            self, mode, self._make_step_sequential if mode == "sequential"
            else self._make_step)
        self._t = 0

    # -- full-state checkpointing (io.checkpoint bundles, SURVEY.md §6) ------
    def _checkpoint_arrays(self):
        tree = {"W": self.W}
        if self.sigma is not None:
            tree["sigma"] = self.sigma
        return tree

    def _restore_arrays(self, tree) -> None:
        self.W = tree["W"]
        if "sigma" in tree:
            self.sigma = tree["sigma"]

    def _checkpoint_scalars(self):
        # class labels are json keys; keep their original type tag so int
        # labels don't come back as strings
        return {"labels": [[type(k).__name__, str(k), v]
                           for k, v in self._labels.items()]}

    def _restore_scalars(self, scalars) -> None:
        for tname, key, row in scalars.get("labels", []):
            if tname.startswith("bool"):   # bool first: bool < int in Python
                # (startswith: numpy scalars stringify as 'bool_')
                self._labels[key == "True"] = int(row)
            elif "int" in tname:
                self._labels[int(key)] = int(row)
            elif "float" in tname:
                self._labels[float(key)] = int(row)
            else:
                self._labels[key] = int(row)

    def save_bundle(self, path: str) -> None:
        from ..io.checkpoint import save_bundle
        self._flush()
        save_bundle(self, path)

    def load_bundle(self, path: str) -> None:
        from ..io.checkpoint import load_bundle
        load_bundle(self, path)

    # -- label/row handling --------------------------------------------------
    def _label_id(self, label) -> int:
        if label not in self._labels:
            if len(self._labels) >= self.C:
                raise ValueError(f"more than -classes {self.C} labels seen")
            self._labels[label] = len(self._labels)
        return self._labels[label]

    def _parse_row(self, features) -> Tuple[np.ndarray, np.ndarray]:
        from ..utils.hashing import mhash
        idx: List[int] = []
        val: List[float] = []
        for f in features:
            if f in (None, ""):
                continue
            name, v = split_feature(f)
            try:
                i = int(name)
            except ValueError:
                i = mhash(name, self.dims - 1)
                self._names.setdefault(i, name)
            idx.append(i)
            val.append(float(v))
        return np.asarray(idx, np.int32), np.asarray(val, np.float32)

    def process(self, features, label) -> None:
        idx, val = self._parse_row(features)
        y = self._label_id(label)
        self._buf.append((idx, val, y))
        if len(self._buf) >= int(self.opts.mini_batch):
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        chunk = self._buf
        self._buf = []
        B = int(self.opts.mini_batch)
        Lp = pow2_len(max(1, max(len(r[0]) for r in chunk)))
        idx = np.zeros((B, Lp), np.int32)
        val = np.zeros((B, Lp), np.float32)
        y = np.zeros(B, np.int32)
        mask = np.zeros(B, np.float32)
        for b, (i, v, yy) in enumerate(chunk):
            idx[b, :len(i)] = i
            val[b, :len(v)] = v
            y[b] = yy
            mask[b] = 1.0
        self.W, self.sigma = self._step(self.W, self.sigma, idx, val, y, mask)
        self._t += 1

    def close(self) -> Iterator[Tuple]:
        self._flush()
        yield from self.model_rows()

    # -- the jitted aggregated step -----------------------------------------
    # subclass hook: (margin_diff, v, C-opts) -> (alpha, beta)
    def _rates(self):
        raise NotImplementedError

    def _make_step(self):
        rates = self._rates()
        has_covar = self.HAS_COVAR

        @jax.jit
        def step(W, sigma, idx, val, y, mask):
            scores = jnp.einsum("cbl,bl->bc",
                                W[:, idx], val)            # [B, C]
            B, C = scores.shape
            true_s = jnp.take_along_axis(scores, y[:, None], 1)[:, 0]
            penal = scores.at[jnp.arange(B), y].set(-jnp.inf)
            rival = jnp.argmax(penal, axis=1)               # best wrong class
            rival_s = jnp.take_along_axis(scores, rival[:, None], 1)[:, 0]
            m = true_s - rival_s                            # margin difference
            # diagonal covar: v = sum over both rows' sigma * x^2
            if has_covar:
                st = sigma[y, :][jnp.arange(B)[:, None], idx]
                sr = sigma[rival, :][jnp.arange(B)[:, None], idx]
                v = ((st + sr) * val * val).sum(-1)
            else:
                st = sr = jnp.ones_like(val)
                v = 2.0 * (val * val).sum(-1)
            alpha, beta = rates(m, v)
            alpha = alpha * mask
            beta = beta * mask
            # scatter into the true and rival class rows
            flat_t = y[:, None] * W.shape[1] + idx          # [B, L]
            flat_r = rival[:, None] * W.shape[1] + idx
            Wf = W.reshape(-1)
            Wf = Wf.at[flat_t.ravel()].add(
                (alpha[:, None] * st * val).ravel())
            Wf = Wf.at[flat_r.ravel()].add(
                (-alpha[:, None] * sr * val).ravel())
            W2 = Wf.reshape(W.shape)
            if has_covar:
                Sf = sigma.reshape(-1)
                Sf = Sf.at[flat_t.ravel()].add(
                    -(beta[:, None] * (st * val) ** 2).ravel())
                Sf = Sf.at[flat_r.ravel()].add(
                    -(beta[:, None] * (sr * val) ** 2).ravel())
                sigma2 = jnp.maximum(Sf.reshape(sigma.shape), 1e-8)
            else:
                sigma2 = sigma
            return W2, sigma2

        return step

    def _make_step_sequential(self):
        """Reference-exact row-by-row multiclass updates at slab rate.

        The round-3 slab scan (models/classifier.py): gather G=64 rows'
        per-class entries once ([C, G, L]), run the exact per-row loop on
        the in-register slab — rival selection and margins read the
        PREVIOUS rows' updates through an idx-match propagation mask, so
        each row sees exactly the values true row-by-row dispatch would —
        and scatter the final values back once per slab. Round 2's scan
        carried the whole [C, dims] tables through every row."""
        rates = self._rates()
        has_covar = self.HAS_COVAR
        G = 64

        @jax.jit
        def step(W, sigma, idx, val, y, mask):
            B, L = idx.shape
            pad = (-B) % G
            if pad:
                idx = jnp.pad(idx, ((0, pad), (0, 0)))
                val = jnp.pad(val, ((0, pad), (0, 0)))
                y = jnp.pad(y, (0, pad))
                mask = jnp.pad(mask, (0, pad))
            nS = (B + pad) // G
            sig0 = sigma if has_covar else jnp.zeros((1, 1), jnp.float32)

            def slab(carry, rows):
                cW, cS = carry
                sidx, sval, sy, smsk = rows          # [G, L], ..., [G]
                Ws = cW[:, sidx]                     # [C, G, L]
                Ss = cS[:, sidx] if has_covar else jnp.ones_like(Ws)

                def body(j, st_):
                    Ws, Ss = st_
                    rval, ry, msk = sval[j], sy[j], smsk[j]
                    scores = (Ws[:, j] * rval).sum(-1)       # [C]
                    true_s = scores[ry]
                    penal = scores.at[ry].set(-jnp.inf)
                    rival = jnp.argmax(penal)
                    m = true_s - scores[rival]
                    if has_covar:
                        st = Ss[ry, j]
                        sr = Ss[rival, j]
                        v = ((st + sr) * rval * rval).sum()
                    else:
                        st = sr = jnp.ones_like(rval)
                        v = 2.0 * (rval * rval).sum()
                    alpha, beta = rates(m, v)
                    alpha = alpha * msk
                    beta = beta * msk
                    match = sidx[:, :, None] == sidx[j][None, None, :]
                    dwt = (jnp.where(match, (alpha * st * rval)[None, None],
                                     0.0)).sum(-1)           # [G, L]
                    dwr = (jnp.where(match, (alpha * sr * rval)[None, None],
                                     0.0)).sum(-1)
                    Ws = Ws.at[ry].add(dwt)
                    Ws = Ws.at[rival].add(-dwr)
                    if has_covar:
                        stn = jnp.maximum(st - beta * (st * rval) ** 2,
                                          1e-8)
                        srn = jnp.maximum(sr - beta * (sr * rval) ** 2,
                                          1e-8)
                        dst = jnp.where(msk > 0, stn - st, 0.0)
                        dsr = jnp.where(msk > 0, srn - sr, 0.0)
                        Ss = Ss.at[ry].add(
                            jnp.where(match, dst[None, None], 0.0).sum(-1))
                        Ss = Ss.at[rival].add(
                            jnp.where(match, dsr[None, None], 0.0).sum(-1))
                    return Ws, Ss

                Ws, Ss = jax.lax.fori_loop(0, G, body, (Ws, Ss))
                cW = cW.at[:, sidx].set(Ws)
                if has_covar:
                    cS = cS.at[:, sidx].set(Ss)
                return (cW, cS), None

            (W2, sig), _ = jax.lax.scan(
                slab, (W, sig0),
                (idx.reshape(nS, G, L), val.reshape(nS, G, L),
                 y.reshape(nS, G), mask.reshape(nS, G)))
            return W2, (sig if has_covar else sigma)

        return step

    # -- scoring / emission --------------------------------------------------
    def classify(self, features) -> object:
        idx, val = self._parse_row(features)
        W = np.asarray(self.W)
        scores = (W[:, idx] * val).sum(-1)
        inv = {v: k for k, v in self._labels.items()}
        k = int(np.argmax(scores[:len(self._labels)]))
        return inv.get(k)

    def model_rows(self) -> Iterator[Tuple]:
        W = np.asarray(self.W)
        inv = {v: k for k, v in self._labels.items()}
        sig = None if self.sigma is None else np.asarray(self.sigma)
        for c in range(len(self._labels)):
            nz = np.nonzero(W[c])[0]
            for i in nz:
                name = self._names.get(int(i), str(int(i)))
                if sig is None:
                    yield (inv[c], name, float(W[c, i]))
                else:
                    yield (inv[c], name, float(W[c, i]), float(sig[c, i]))


class MulticlassPerceptronTrainer(_MulticlassBase):
    """SQL: train_multiclass_perceptron."""
    NAME = "train_multiclass_perceptron"

    def _rates(self):
        def rates(m, v):
            return (m <= 0).astype(jnp.float32), jnp.zeros_like(m)
        return rates


class MulticlassPATrainer(_MulticlassBase):
    """SQL: train_multiclass_pa — tau = hinge(1 - m) / v."""
    NAME = "train_multiclass_pa"

    def _tau_factory(self):
        # scalars-only closure (see classifier.PassiveAggressiveTrainer)
        return lambda loss, v: loss / jnp.maximum(v, 1e-12)

    def _rates(self):
        tau_fn = self._tau_factory()

        def rates(m, v):
            loss = jnp.maximum(0.0, 1.0 - m)
            return jnp.where(loss > 0, tau_fn(loss, v), 0.0), \
                jnp.zeros_like(m)
        return rates


class MulticlassPA1Trainer(MulticlassPATrainer):
    NAME = "train_multiclass_pa1"

    def _tau_factory(self):
        c = float(self.opts.c)
        return lambda loss, v: jnp.minimum(
            c, loss / jnp.maximum(v, 1e-12))


class MulticlassPA2Trainer(MulticlassPATrainer):
    NAME = "train_multiclass_pa2"

    def _tau_factory(self):
        c = float(self.opts.c)
        return lambda loss, v: loss / (v + 1.0 / (2.0 * c))


class MulticlassCWTrainer(_MulticlassBase):
    """SQL: train_multiclass_cw."""
    NAME = "train_multiclass_cw"
    HAS_COVAR = True

    def _rates(self):
        phi = _phi_of(self.opts)
        zeta = 1.0 + phi * phi
        psi = 1.0 + phi * phi / 2.0

        def rates(m, v):
            alpha = jnp.maximum(0.0, (-m * psi + jnp.sqrt(
                m * m * phi ** 4 / 4.0 + v * phi * phi * zeta))
                / jnp.maximum(v * zeta, 1e-12))
            return alpha, _cw_beta(alpha, v, phi)
        return rates


class MulticlassAROWTrainer(_MulticlassBase):
    """SQL: train_multiclass_arow."""
    NAME = "train_multiclass_arow"
    HAS_COVAR = True

    def _rates(self):
        r = float(self.opts.r)

        def rates(m, v):
            beta = 1.0 / (v + r)
            alpha = jnp.maximum(0.0, 1.0 - m) * beta
            upd = (m < 1.0).astype(jnp.float32)
            return alpha * upd, beta * upd
        return rates


class MulticlassSCWTrainer(MulticlassCWTrainer):
    """SQL: train_multiclass_scw — SCW-I cap at C."""
    NAME = "train_multiclass_scw"

    def _rates(self):
        base = super()._rates()
        C = float(self.opts.c)

        def rates(m, v):
            alpha, beta = base(m, v)
            alpha = jnp.minimum(alpha, C)
            return alpha, beta
        return rates


class MulticlassSCW2Trainer(_MulticlassBase):
    """SQL: train_multiclass_scw2 — SCW-II."""
    NAME = "train_multiclass_scw2"
    HAS_COVAR = True

    def _rates(self):
        phi = _phi_of(self.opts)
        C = float(self.opts.c)

        def rates(m, v):
            n = v + 1.0 / (2.0 * C)
            gamma = phi * jnp.sqrt(
                phi * phi * m * m * v * v + 4.0 * n * v * (n + v * phi * phi))
            alpha = jnp.maximum(0.0, (-(2.0 * m * n + phi * phi * m * v)
                                      + gamma)
                                / (2.0 * (n * n + n * v * phi * phi) + 1e-12))
            return alpha, _cw_beta(alpha, v, phi)
        return rates
