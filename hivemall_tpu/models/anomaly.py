"""Anomaly / change-point detection — changefinder and sst (SURVEY.md §3.11).

Reference: hivemall.anomaly.{ChangeFinderUDF,ChangeFinder1D,ChangeFinder2D,
SDAR1D,SDAR2D,SingularSpectrumTransformUDF}.

changefinder: two-stage sequentially-discounted AR (SDAR). Stage 1 scores
each point by -log p(x_t | AR model); smoothed scores feed a second SDAR
whose score is the change-point score. The recurrence is inherently
sequential, so the UDF form is a streaming host-side update (tiny O(k^2)
state — exactly the reference's shape); `changefinder_batch` wraps a whole
series at once.

sst: singular-spectrum transformation — past/future Hankel matrices at each
t; score = 1 - overlap of principal left subspaces. The batched form stacks
every offset's Hankel matrix and runs one vmapped SVD on TPU.
"""

from __future__ import annotations

from collections import deque
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.options import OptionSpec

__all__ = ["SDAR1D", "ChangeFinder", "changefinder", "sst"]


class SDAR1D:
    """Sequentially discounted AR(k) estimator (reference SDAR1D):
    discounted mean/autocovariances + Yule-Walker solve; score is the
    negative log likelihood of x_t under the one-step prediction."""

    def __init__(self, r: float = 0.02, k: int = 3):
        self.r = r
        self.k = k
        self.mu = 0.0
        self.sigma = 1.0
        self.c = np.zeros(k + 1)
        self.hist = deque(maxlen=k)
        self.n = 0

    def update(self, x: float) -> float:
        r, k = self.r, self.k
        self.n += 1
        self.mu = (1 - r) * self.mu + r * x
        xc = x - self.mu
        hist = list(self.hist)
        for j in range(min(len(hist), k + 1)):
            lagged = hist[-1 - j] - self.mu if j < len(hist) else 0.0
            self.c[j] = (1 - r) * self.c[j] + r * xc * (
                xc if j == 0 else lagged)
        if len(hist) >= 1:
            m = min(k, len(hist))
            # Yule-Walker: Toeplitz(c[0..m-1]) a = c[1..m]
            T = np.empty((m, m))
            for i in range(m):
                for j in range(m):
                    T[i, j] = self.c[abs(i - j)]
            try:
                a = np.linalg.solve(T + 1e-6 * np.eye(m), self.c[1:m + 1])
            except np.linalg.LinAlgError:
                a = np.zeros(m)
            pred = self.mu + sum(a[j] * (hist[-1 - j] - self.mu)
                                 for j in range(m))
        else:
            pred = self.mu
        err = x - pred
        self.sigma = (1 - r) * self.sigma + r * err * err
        self.hist.append(x)
        sig = max(self.sigma, 1e-12)
        return 0.5 * (np.log(2 * np.pi * sig) + err * err / sig)


class ChangeFinder:
    """Two-stage ChangeFinder over a scalar stream (UDF-per-row semantics).

    update(x) -> (outlier_score, change_score)."""

    def __init__(self, r: float = 0.02, k: int = 3, T1: int = 7, T2: int = 7):
        self.stage1 = SDAR1D(r, k)
        self.stage2 = SDAR1D(r, k)
        self.w1 = deque(maxlen=T1)
        self.w2 = deque(maxlen=T2)

    def update(self, x: float) -> Tuple[float, float]:
        s1 = self.stage1.update(float(x))
        self.w1.append(s1)
        y = float(np.mean(self.w1))
        s2 = self.stage2.update(y)
        self.w2.append(s2)
        return s1, float(np.mean(self.w2))


CHANGEFINDER_SPEC = (OptionSpec("changefinder")
                     .add("r", "forget", type=float, default=0.02,
                          help="discounting rate")
                     .add("k", "order", type=float, default=3,
                          help="AR order")
                     .add("T1", "smooth1", type=int, default=7)
                     .add("T2", "smooth2", type=int, default=7)
                     .add("outlier_threshold", type=float, default=0.0)
                     .add("changepoint_threshold", type=float, default=0.0))


def changefinder(series: Sequence[float], options: str = ""
                 ) -> List[Tuple[float, float]]:
    """SQL: changefinder(x[, options]) — batch over a series, emitting
    (outlier_score, changepoint_score) per element."""
    ns = CHANGEFINDER_SPEC.parse(options)
    cf = ChangeFinder(float(ns.r), int(ns.k), int(ns.T1), int(ns.T2))
    return [cf.update(float(x)) for x in series]


SST_SPEC = (OptionSpec("sst")
            .add("w", "window", type=int, default=30,
                 help="Hankel window size")
            .add("n", "n_past", type=int, default=0,
                 help="past columns (default w)")
            .add("m", "n_current", type=int, default=0,
                 help="future columns (default w)")
            .add("g", "gap", type=int, default=0,
                 help="gap between past and future (default w/4)")
            .add("r", "components", type=int, default=3,
                 help="principal components compared")
            .add("threshold", type=float, default=0.0))


def sst(series: Sequence[float], options: str = "") -> List[float]:
    """SQL: sst(x[, options]) — singular-spectrum-transform change score
    per element (0 until enough history). Batched: every offset's past and
    future Hankel matrices are SVD'd in one vmapped call."""
    import jax
    import jax.numpy as jnp

    ns = SST_SPEC.parse(options)
    x = np.asarray(list(series), np.float32)
    w = int(ns.w)
    n = int(ns.n) or w
    m = int(ns.m) or w
    g = int(ns.g) or max(1, w // 4)
    r = int(ns.r)
    T = len(x)
    start = w + n - 1          # first t with a full past matrix
    need = start + g + m       # and a full future matrix
    if T <= need:
        return [0.0] * T

    def hankel(t0, cols):
        # columns j: x[t0 + j - w + 1 : t0 + j + 1]
        return jnp.stack([jax.lax.dynamic_slice(xj, (t0 + j - w + 1,), (w,))
                          for j in range(cols)], axis=1)

    xj = jnp.asarray(x)

    @jax.jit
    def score_at(t):
        past = hankel(t - n + 1 - 1, n)       # ends at t-1... columns upto t
        fut = hankel(t + g - 1, m)
        up, _, _ = jnp.linalg.svd(past, full_matrices=False)
        uf, _, _ = jnp.linalg.svd(fut, full_matrices=False)
        s = jnp.linalg.svd(up[:, :r].T @ uf[:, :r], compute_uv=False)
        return 1.0 - s[0]

    ts = np.arange(start, T - g - m)
    scores = np.zeros(T, np.float32)
    if len(ts):
        vals = jax.vmap(score_at)(jnp.asarray(ts))
        scores[ts] = np.asarray(vals)
    return scores.tolist()
