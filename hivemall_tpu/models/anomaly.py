"""Anomaly / change-point detection — changefinder and sst (SURVEY.md §3.11).

Reference: hivemall.anomaly.{ChangeFinderUDF,ChangeFinder1D,ChangeFinder2D,
SDAR1D,SDAR2D,SingularSpectrumTransformUDF}.

changefinder: two-stage sequentially-discounted AR (SDAR). Stage 1 scores
each point by -log p(x_t | AR model); smoothed scores feed a second SDAR
whose score is the change-point score. The reference accepts a double OR
vector stream (ChangeFinder2D/SDAR2D for the vector case).

Two forms, same math:
  - streaming classes (SDAR1D/SDAR2D, ChangeFinder/ChangeFinder2D): the
    UDF-per-row form, tiny O(k^2 d^2) host state — and the oracles the
    batched path is tested against.
  - the batched TPU path (`changefinder`): the SDAR recurrence LOOKS
    sequential, but its state splits into (a) discounted moments (mu, the
    lag covariances, sigma) — affine EMAs s_t = a_t s_{t-1} + b_t whose
    coefficients never depend on the AR solves, and (b) the Yule-Walker
    solve + prediction, which reads only the moments at t. So the whole
    series runs as three lax.associative_scan EMA passes + ONE batched
    (vmapped) Yule-Walker solve + elementwise scoring per stage — no
    per-step linear algebra, no Python loop, one device dispatch. The
    round-4 per-row Python loop ran 16k points/s; this path is bounded by
    a few passes over [T, (k+1)d^2] arrays.

sst: singular-spectrum transformation — past/future Hankel matrices at each
t; score = 1 - overlap of principal left subspaces. Two batched score
functions, mirroring the reference's svd/power-iteration pair: `-scorefunc
svd` stacks every offset's Hankel and runs one vmapped SVD; `-scorefunc
ika` runs subspace iteration on the [w, w] Hankel Grams — batched matmuls
only, ~100x faster on TPU at the same detections.
"""

from __future__ import annotations

from collections import deque
from functools import lru_cache, partial
from typing import List, Sequence, Tuple

import numpy as np

from ..obs.devprof import instrument_factory as _instrument

from ..utils.options import OptionSpec

__all__ = ["SDAR1D", "SDAR2D", "ChangeFinder", "ChangeFinder2D",
           "changefinder", "sst"]


class SDAR1D:
    """Sequentially discounted AR(k) estimator (reference SDAR1D):
    discounted mean/autocovariances + Yule-Walker solve; score is the
    negative log likelihood of x_t under the one-step prediction."""

    def __init__(self, r: float = 0.02, k: int = 3):
        self.r = r
        self.k = k
        self.mu = 0.0
        self.sigma = 1.0
        self.c = np.zeros(k + 1)
        self.hist = deque(maxlen=k)
        self.n = 0

    def update(self, x: float) -> float:
        r, k = self.r, self.k
        self.n += 1
        self.mu = (1 - r) * self.mu + r * x
        xc = x - self.mu
        hist = list(self.hist)
        for j in range(min(len(hist), k + 1)):
            lagged = hist[-1 - j] - self.mu if j < len(hist) else 0.0
            self.c[j] = (1 - r) * self.c[j] + r * xc * (
                xc if j == 0 else lagged)
        if len(hist) >= 1:
            m = min(k, len(hist))
            # Yule-Walker: Toeplitz(c[0..m-1]) a = c[1..m]
            T = np.empty((m, m))
            for i in range(m):
                for j in range(m):
                    T[i, j] = self.c[abs(i - j)]
            try:
                # per-diagonal relative ridge (floored at the absolute
                # 1e-6), matching the batched path: right after warmup the
                # system is a rank-1 outer product, and against moments
                # ~1e13 (|x| ~ 5e6 series) an absolute 1e-6 is nothing —
                # the near-singular solve returns garbage ~1e16 that the
                # batch path (relatively ridged) never produces
                rg = 1e-6 * np.maximum(np.abs(np.diag(T)), 1.0)
                a = np.linalg.solve(T + np.diag(rg), self.c[1:m + 1])
            except np.linalg.LinAlgError:
                a = np.zeros(m)
            pred = self.mu + sum(a[j] * (hist[-1 - j] - self.mu)
                                 for j in range(m))
        else:
            pred = self.mu
        err = x - pred
        self.sigma = (1 - r) * self.sigma + r * err * err
        self.hist.append(x)
        sig = max(self.sigma, 1e-12)
        return 0.5 * (np.log(2 * np.pi * sig) + err * err / sig)


class SDAR2D:
    """Vector-stream SDAR(k) (reference SDAR2D): the same discounted
    moments with [d, d] lag-covariance blocks, a block-Toeplitz
    Yule-Walker solve for the AR matrices, and a multivariate Gaussian
    NLL score (logdet + Mahalanobis). Mirrors SDAR1D's warmup exactly
    (moment update only for lags the history covers; system size grows
    min(k, len(hist)))."""

    def __init__(self, r: float = 0.02, k: int = 3, d: int = 2):
        self.r = r
        self.k = k
        self.d = d
        self.mu = np.zeros(d)
        self.sigma = np.eye(d)
        self.c = np.zeros((k + 1, d, d))
        self.hist = deque(maxlen=k)
        self.n = 0

    def update(self, x: np.ndarray) -> float:
        r, k, d = self.r, self.k, self.d
        x = np.asarray(x, np.float64).reshape(d)
        self.n += 1
        self.mu = (1 - r) * self.mu + r * x
        xc = x - self.mu
        hist = list(self.hist)
        for j in range(min(len(hist), k + 1)):
            lag = (xc if j == 0 else hist[-1 - j] - self.mu)
            self.c[j] = (1 - r) * self.c[j] + r * np.outer(xc, lag)
        m = min(k, len(hist))
        if m >= 1:
            # block-Toeplitz G[i,j] = c[|i-j|] (transposed below diag so
            # the block matrix is symmetric), solve G S = R with R block
            # i = c[i+1]^T; S block j = A_j^T
            G = np.empty((m * d, m * d))
            R = np.empty((m * d, d))
            for i in range(m):
                R[i * d:(i + 1) * d] = self.c[i + 1].T
                for j in range(m):
                    blk = self.c[abs(i - j)]
                    G[i * d:(i + 1) * d, j * d:(j + 1) * d] = (
                        blk if i <= j else blk.T)
            try:
                # per-diagonal relative ridge (same rationale as SDAR1D's
                # and the batched path's _sdar_scores ridge)
                rg = 1e-6 * np.maximum(np.abs(np.diag(G)), 1.0)
                S = np.linalg.solve(G + np.diag(rg), R)
            except np.linalg.LinAlgError:
                S = np.zeros((m * d, d))
            pred = self.mu.copy()
            for j in range(m):
                pred += S[j * d:(j + 1) * d].T @ (hist[-1 - j] - self.mu)
        else:
            pred = self.mu
        err = x - pred
        self.sigma = (1 - r) * self.sigma + r * np.outer(err, err)
        self.hist.append(x)
        # relative per-diagonal ridge, mirroring the batch path's sigma
        # ridge (1e-9 * max(diag, 1)) so the two stay score-equivalent at
        # any channel magnitude
        sig = self.sigma + np.diag(
            1e-9 * np.maximum(np.abs(np.diag(self.sigma)), 1.0))
        sign, logdet = np.linalg.slogdet(sig)
        maha = float(err @ np.linalg.solve(sig, err))
        return 0.5 * (d * np.log(2 * np.pi) + logdet + maha)


class ChangeFinder:
    """Two-stage ChangeFinder over a scalar stream (UDF-per-row semantics).

    update(x) -> (outlier_score, change_score)."""

    def __init__(self, r: float = 0.02, k: int = 3, T1: int = 7, T2: int = 7):
        self.stage1 = SDAR1D(r, k)
        self.stage2 = SDAR1D(r, k)
        self.w1 = deque(maxlen=T1)
        self.w2 = deque(maxlen=T2)

    def update(self, x: float) -> Tuple[float, float]:
        s1 = self.stage1.update(float(x))
        self.w1.append(s1)
        y = float(np.mean(self.w1))
        s2 = self.stage2.update(y)
        self.w2.append(s2)
        return s1, float(np.mean(self.w2))


class ChangeFinder2D:
    """Two-stage ChangeFinder over a vector stream (reference
    ChangeFinder2D): stage 1 is a vector SDAR2D, its smoothed NLL feeds a
    scalar stage-2 SDAR exactly like the 1D form."""

    def __init__(self, d: int, r: float = 0.02, k: int = 3,
                 T1: int = 7, T2: int = 7):
        self.stage1 = SDAR2D(r, k, d)
        self.stage2 = SDAR1D(r, k)
        self.w1 = deque(maxlen=T1)
        self.w2 = deque(maxlen=T2)

    def update(self, x) -> Tuple[float, float]:
        s1 = self.stage1.update(np.asarray(x, np.float64))
        self.w1.append(s1)
        y = float(np.mean(self.w1))
        s2 = self.stage2.update(y)
        self.w2.append(s2)
        return s1, float(np.mean(self.w2))


# --- batched TPU path --------------------------------------------------


def _ema_scan(a, b):
    """s_t = a_t * s_{t-1} + b_t with s_{-1} = 0, via associative affine
    composition (numerically stable for any per-step a_t pattern — the
    warmup steps SKIP moment updates, i.e. a_t = 1, b_t = 0)."""
    import jax

    def comp(lo, hi):
        return (hi[0] * lo[0], hi[0] * lo[1] + hi[1])

    return jax.lax.associative_scan(comp, (a, b), axis=0)[1]


def _solve_small(G, R, pd: bool = False, with_logdet: bool = False):
    """Batched solve of symmetric [T, n, n] systems by closed form for
    n <= 3 — pure elementwise VPU work. jnp.linalg.solve's batched LU
    measured 64.2 ms vs 8.9 ms at [65536, 3, 3] on v5e (7.2x), and the
    default 1D changefinder pays TWO such solves per run. n > 3 (the 2D
    stream's kd = 6 Yule-Walker) falls back to the LAPACK-style path.

    Numerical design: each system is Jacobi-equilibrated by
    D = diag(1/sqrt(|G_ii|)) — solve (D G D) y = D R, x = D y — then
    solved by an UNROLLED LDL^T factorization. Equilibration respects
    heterogeneous channel scales (a [1e12, 1e-6] diagonal becomes a
    correlation-like matrix instead of drowning the small channel) and
    keeps products inside f32 range (covariance entries ~1e13 overflowed
    an explicit 3x3 det). LDL rather than Cramer/adjugate because the
    SEQUENTIAL pivots are each individually f32-representable: a smooth
    series (ChangeFinder's stage-2 input) makes the YW matrix
    near-all-ones, whose true ridge-induced det ~1e-12 is far below the
    ~1e-7 cancellation noise of an explicit cofactor product — Cramer +
    a det floor returned coefficients ~1e5 off there, while LDL's pivots
    carry only per-factor rounding (the same reason LAPACK works in f32).

    pd=False (default): pivots keep their sign, floored at |1e-7| — the
    SDAR discounted-moment Toeplitz is INDEFINITE in general (its c[j]
    are cross-moments, not true autocovariances; a measured t=4 stage-2
    system had det(correlation) = -0.0037 with a legitimate -0.018 third
    pivot that a positive clamp turned into garbage x1e5). pd=True: the
    caller asserts PD (ridged sigma from an outer-product EMA + PD
    init), so a non-positive pivot is pure f32 cancellation noise and
    clamps POSITIVE.

    with_logdet=True (requires pd=True, n <= 3): also return
    log det(G) = sum_i log d_i + 2 sum_i log s_i computed from the SAME
    floored pivots the solve used — the caller's Gaussian NLL then pairs
    a Mahalanobis term and a logdet that assume one determinant, by
    construction rather than by parallel code.

    Known limit (documented, not defended): unpivoted LDL on an
    INDEFINITE system whose leading 2x2 block is near-singular while the
    full matrix is well-conditioned (c0 ~= c1 with c2 << c0) floors d2
    and computes x2 as a difference of ~1/1e-7-scaled terms — ~O(1)
    relative error for that system where pivoted LU is exact. Scores
    stay finite (SDAR absorbs one bad prediction into sigma), the
    pattern needs an autocorrelation shape smooth/noisy streams don't
    produce, and per-system pivoting would forfeit the closed form."""
    import jax.numpy as jnp

    n = G.shape[-1]
    if n == 1:
        # same contract as n >= 2: equilibrate, floor the (single) pivot
        # at 1e-7 — a zero 1x1 system must return a finite solve and a
        # finite logdet, not inf — and keep logdet from the SAME floored
        # pivot the solve used
        if with_logdet:
            assert pd, "with_logdet requires a PD system (log of pivots)"
        g = G[..., 0, 0]
        s2 = jnp.maximum(jnp.abs(g), 1e-30)       # Jacobi scale squared
        gn = g / s2                               # equilibrated pivot, ±1|0
        if pd:
            d1 = jnp.maximum(gn, 1e-7)
        else:
            d1 = jnp.where(jnp.abs(gn) < 1e-7,
                           jnp.where(gn < 0, -1e-7, 1e-7), gn)
        x = R / (d1 * s2)[..., None, None]
        if with_logdet:
            return x, jnp.log(d1) + jnp.log(s2)
        return x
    if n > 3:
        # LAPACK-style path on the RAW system (pivoting handles scale)
        assert not with_logdet
        return jnp.linalg.solve(G, R)
    s = jnp.sqrt(jnp.maximum(
        jnp.abs(jnp.diagonal(G, axis1=-2, axis2=-1)), 1e-30))   # [..., n]
    G = G / (s[..., :, None] * s[..., None, :])
    R = R / s[..., :, None]

    if pd:
        def _piv(dd):
            return jnp.maximum(dd, 1e-7)
    else:
        def _piv(dd):
            return jnp.where(jnp.abs(dd) < 1e-7,
                             jnp.where(dd < 0, -1e-7, 1e-7), dd)

    def _with_ld(x, pivots):
        if not with_logdet:
            return x
        assert pd, "with_logdet requires a PD system (log of pivots)"
        ld = 2.0 * jnp.log(s).sum(-1)
        for dd in pivots:
            ld = ld + jnp.log(dd)
        return x, ld

    if n == 2:
        d1 = _piv(G[..., 0, 0])
        l21 = G[..., 1, 0] / d1
        d2 = _piv(G[..., 1, 1] - l21 * l21 * d1)
        z1 = R[..., 0, :]
        z2 = R[..., 1, :] - l21[..., None] * z1
        x2 = z2 / d2[..., None]
        x1 = z1 / d1[..., None] - l21[..., None] * x2
        return _with_ld(jnp.stack([x1, x2], axis=-2) / s[..., :, None],
                        (d1, d2))

    d1 = _piv(G[..., 0, 0])
    l21 = G[..., 1, 0] / d1
    l31 = G[..., 2, 0] / d1
    d2 = _piv(G[..., 1, 1] - l21 * l21 * d1)
    l32 = (G[..., 2, 1] - l31 * l21 * d1) / d2
    d3 = _piv(G[..., 2, 2] - l31 * l31 * d1 - l32 * l32 * d2)
    z1 = R[..., 0, :]
    z2 = R[..., 1, :] - l21[..., None] * z1
    z3 = R[..., 2, :] - l31[..., None] * z1 - l32[..., None] * z2
    x3 = z3 / d3[..., None]
    x2 = z2 / d2[..., None] - l32[..., None] * x3
    x1 = (z1 / d1[..., None] - l21[..., None] * x2
          - l31[..., None] * x3)
    return _with_ld(jnp.stack([x1, x2, x3], axis=-2) / s[..., :, None],
                    (d1, d2, d3))


def _sdar_scores(x, r: float, k: int):
    """Batched SDAR over x [T, d] -> NLL scores [T] (matches the
    streaming oracles' semantics step for step).

    The per-step Yule-Walker system embeds warmup as a block-diagonal
    identity: blocks >= m_t = min(t, k) become I rows with zero rhs, so
    their coefficients solve to exactly 0 — the same AR order growth the
    oracle gets from its m x m system."""
    import jax.numpy as jnp

    T, d = x.shape
    t_idx = jnp.arange(T)

    # discounted mean (always updated)
    mu = _ema_scan(jnp.full((T, 1), 1.0 - r), r * x)             # [T, d]
    xc = x - mu

    # lagged values x_{t-1-j} and their centered forms (zeros before
    # start); j runs 0..k because c[k]'s update reads one lag further
    # back than the prediction does
    lags = jnp.stack([
        jnp.concatenate([jnp.zeros((j + 1, d), x.dtype), x[:T - j - 1]])
        for j in range(k + 1)], axis=1)                        # [T, k+1, d]
    lagc = lags - mu[:, None, :]

    # discounted lag covariances: c[0] <- xc xc^T and c[j] <- xc
    # (x_{t-1-j} - mu)^T for j>=1 — the oracle's hist[-1-j], i.e. c[j]
    # pairs the current residual with lag j+1, NOT the textbook lag j.
    # update mask: j < min(t, k)  (the oracle skips lags history can't
    # cover — skipped lags keep their previous value WITHOUT decay)
    pair = jnp.concatenate([xc[:, None, :], lagc[:, 1:]], axis=1)  # [T,k+1,d]
    terms = r * xc[:, None, :, None] * pair[:, :, None, :]       # [T,k+1,d,d]
    jm = jnp.arange(k + 1)
    upd = (jm[None, :] < jnp.minimum(t_idx, k)[:, None]).astype(x.dtype)
    a_c = jnp.where(upd[..., None, None] > 0, 1.0 - r, 1.0)
    b_c = terms * upd[..., None, None]
    c = _ema_scan(a_c, b_c)                                      # [T,k+1,d,d]

    # batched block-Toeplitz Yule-Walker with warmup embedding
    m_t = jnp.minimum(t_idx, k)                                  # [T]
    ii = jnp.arange(k)
    absd = jnp.abs(ii[:, None] - ii[None, :])                    # [k, k]
    blk = c[:, absd]                                             # [T,k,k,d,d]
    blk = jnp.where((ii[:, None] <= ii[None, :])[None, :, :, None, None],
                    blk, jnp.swapaxes(blk, -1, -2))
    act = (ii[None, :] < m_t[:, None])                           # [T, k]
    act2 = act[:, :, None] & act[:, None, :]
    eye_blk = jnp.broadcast_to(
        jnp.eye(k)[:, :, None, None] * jnp.eye(d)[None, None],
        (T, k, k, d, d))
    blk = jnp.where(act2[..., None, None], blk, eye_blk)
    G = blk.transpose(0, 1, 3, 2, 4).reshape(T, k * d, k * d)
    # ridge relative PER DIAGONAL ENTRY (floored at the oracle's absolute
    # 1e-6 so O(1)-magnitude channels match it bit-for-tolerance): right
    # after warmup the active block is a rank-1 outer product, and against
    # covariances ~1e13 (|x| ~ 5e6 series) an absolute 1e-6 is below f32
    # cancellation noise — the CPU LU's second pivot cancels to exactly 0
    # and the solve returns inf (the TPU lowering happened to survive).
    # Per-entry (not global-max) keeps a small-scale channel's ridge at
    # the absolute 1e-6 instead of drowning its variance.
    gd = jnp.abs(jnp.diagonal(G, axis1=-2, axis2=-1))            # [T, kd]
    G = G + jnp.eye(k * d) * (1e-6 * jnp.maximum(gd, 1.0))[:, :, None]
    R = jnp.where(act[..., None, None],
                  jnp.swapaxes(c[:, 1:], -1, -2),
                  0.0).reshape(T, k * d, d)
    S = _solve_small(G, R)                                       # [T, kd, d]

    # pred_t = mu_t + sum_j A_j (x_{t-1-j} - mu_t),  A_j^T = S block j
    Sb = S.reshape(T, k, d, d)
    pred = mu + jnp.einsum("tjde,tjd->te", Sb, lagc[:, :k])
    err = x - pred

    # discounted residual covariance, init I (EMA from s_{-1}=I: fold the
    # init into step 0's b)
    ee = r * err[:, :, None] * err[:, None, :]
    b0 = ee.at[0].add((1.0 - r) * jnp.eye(d))
    sigma = _ema_scan(jnp.full((T, 1, 1), 1.0 - r), b0)          # [T, d, d]

    if d == 1:
        sig = jnp.maximum(sigma[:, 0, 0], 1e-12)
        e = err[:, 0]
        return 0.5 * (jnp.log(2 * jnp.pi * sig) + e * e / sig)
    # per-diagonal relative ridge (same rationale as the YW system's)
    sd = jnp.abs(jnp.diagonal(sigma, axis1=-2, axis2=-1))        # [T, d]
    sig = sigma + jnp.eye(d) * (1e-9 * jnp.maximum(sd, 1.0))[:, :, None]
    if d <= 3:
        # one LDL factorization serves both halves of the NLL: the
        # Mahalanobis solve and the logdet come from the SAME equilibrated
        # floored pivots, so they assume one determinant by construction
        sol, logdet = _solve_small(sig, err[..., None], pd=True,
                                   with_logdet=True)
    else:
        _, logdet = jnp.linalg.slogdet(sig)
        sol = jnp.linalg.solve(sig, err[..., None])
    maha = jnp.einsum("td,td->t", err, sol[..., 0])
    return 0.5 * (d * jnp.log(2 * jnp.pi) + logdet + maha)


def _rolling_mean(s, w: int):
    """Mean over the last min(t+1, w) values (the oracle's deque mean)."""
    import jax.numpy as jnp

    T = s.shape[0]
    cs = jnp.cumsum(s)
    shifted = jnp.concatenate([jnp.zeros((w,), s.dtype), cs[:T - w]]) \
        if T > w else jnp.zeros((T,), s.dtype)
    cnt = jnp.minimum(jnp.arange(T) + 1, w).astype(s.dtype)
    return (cs - shifted[:T]) / cnt


@_instrument("changefinder", "run")
@lru_cache(maxsize=32)
def _changefinder_jit(r: float, k: int, T1: int, T2: int, d: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x):
        # full padded outputs; the caller slices host-side so one compile
        # per (bucket, d) serves every series length in the bucket. The
        # two score streams come back STACKED — one device->host fetch
        # (the relay pays ~80-200 ms latency PER FETCH regardless of size)
        s1 = _sdar_scores(x, r, k)
        y = _rolling_mean(s1, T1)
        s2 = _sdar_scores(y[:, None], r, k)
        cp = _rolling_mean(s2, T2)
        return jnp.stack([s1, cp])

    return run


def _bucket(n: int) -> int:
    b = 256
    while b < n:
        b <<= 1
    return b


CHANGEFINDER_SPEC = (OptionSpec("changefinder")
                     .add("r", "forget", type=float, default=0.02,
                          help="discounting rate")
                     .add("k", "order", type=float, default=3,
                          help="AR order")
                     .add("T1", "smooth1", type=int, default=7)
                     .add("T2", "smooth2", type=int, default=7)
                     .add("outlier_threshold", type=float, default=0.0)
                     .add("changepoint_threshold", type=float, default=0.0))


def changefinder(series, options: str = "") -> List[Tuple[float, float]]:
    """SQL: changefinder(x[, options]) — batch over a series of doubles OR
    of array<double> rows (the reference's ChangeFinder1D / ChangeFinder2D
    dispatch), emitting (outlier_score, changepoint_score) per element.
    Runs the fully batched scan path: one device dispatch per series."""
    import jax.numpy as jnp

    ns = CHANGEFINDER_SPEC.parse(options)
    x = np.asarray(series, np.float32)
    if x.ndim == 1:
        x = x[:, None]
    T, d = x.shape
    if T == 0:
        return []
    pad = _bucket(T)
    # memory guard: the batched path holds O(bucket * (k*d)^2) f32 for
    # the Yule-Walker systems (plus the [T, k, k, d, d] block build) —
    # fine for the scalar/small-d streams it was built for, but a wide
    # vector stream would allocate gigabytes. Route those through the
    # O(k^2 d^2)-memory streaming oracle instead (identical math).
    k = int(ns.k)
    batch_bytes = pad * ((k * d) ** 2 * 3 + (k + 1) * d * d * 4) * 4
    if batch_bytes > (256 << 20):
        if d == 1:
            cf = ChangeFinder(float(ns.r), k, int(ns.T1), int(ns.T2))
            return [cf.update(float(v[0])) for v in x]
        cf2 = ChangeFinder2D(d, float(ns.r), k, int(ns.T1), int(ns.T2))
        return [cf2.update(v) for v in x]
    xp = np.zeros((pad, d), np.float32)
    xp[:T] = x
    run = _changefinder_jit(float(ns.r), int(ns.k), int(ns.T1),
                            int(ns.T2), d)
    packed = np.asarray(run(jnp.asarray(xp)), np.float64)
    s1, cp = packed[0, :T], packed[1, :T]
    return list(zip(s1.tolist(), cp.tolist()))


SST_SPEC = (OptionSpec("sst")
            .add("w", "window", type=int, default=30,
                 help="Hankel window size")
            .add("n", "n_past", type=int, default=0,
                 help="past columns (default w)")
            .add("m", "n_current", type=int, default=0,
                 help="future columns (default w)")
            .add("g", "gap", type=int, default=0,
                 help="gap between past and future (default w/4)")
            .add("r", "components", type=int, default=3,
                 help="principal components compared")
            .add("scorefunc", type=str, default="svd",
                 choices=("svd", "ika"),
                 help="svd (exact, reference default) | ika "
                      "(power/subspace iteration on the Hankel Grams — "
                      "the reference's fast score function; batched "
                      "matmuls only, ~100x on TPU)")
            .add("threshold", type=float, default=0.0))


def _mgs(Z):
    """Batched modified Gram-Schmidt over the (small, static) last axis:
    Z [..., w, r] -> orthonormal columns. Unrolled per column — pure
    elementwise/matmul work, no LAPACK."""
    import jax.numpy as jnp

    r = Z.shape[-1]
    cols = []
    for j in range(r):
        v = Z[..., j]
        for q in cols:
            v = v - jnp.sum(q * v, axis=-1, keepdims=True) * q
        v = v / jnp.maximum(
            jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-20)
        cols.append(v)
    return jnp.stack(cols, axis=-1)


def _sst_ika_scores(H_p, H_f, r: int, iters: int = 20):
    """Power/subspace-iteration SST score per offset (reference
    'ika'-style score function, SURVEY.md:265 'Hankel matrix SVD/power
    iteration'): top-r left subspaces of past/future Hankels via
    subspace iteration on the [w, w] Grams, then 1 - sigma_max of
    Up^T Uf by power iteration on the tiny [r, r] overlap. Everything
    is a batched matmul — no per-offset LAPACK calls.

    iters=20: on flat-spectrum (noise) regions the eigengap is tiny and
    12 iterations left the true-change score ~0.2 under the SVD's,
    losing the argmax to a noise point; 20 matches SVD's peak on the
    measured hard case and 32 adds nothing."""
    import jax.numpy as jnp

    def topr(H):
        A = jnp.einsum("twn,tvn->twv", H, H)          # [K, w, w] Gram
        Q = _mgs(A[..., :, :r])                        # data-aligned init
        for _ in range(iters):
            Q = _mgs(jnp.einsum("twv,tvr->twr", A, Q))
        return Q

    Up = topr(H_p)
    Uf = topr(H_f)
    M = jnp.einsum("twr,tws->trs", Up, Uf)             # [K, r, r]
    B = jnp.einsum("tsr,tsq->trq", M, M)               # M^T M
    v = jnp.ones(B.shape[:-1], B.dtype) / (r ** 0.5)   # [K, r]
    for _ in range(10):
        v = jnp.einsum("trq,tq->tr", B, v)
        v = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True),
                            1e-20)
    smax2 = jnp.einsum("tr,trq,tq->t", v, B, v)
    return jnp.clip(1.0 - jnp.sqrt(jnp.maximum(smax2, 0.0)), 0.0, 1.0)


@_instrument("sst", "ika")
@lru_cache(maxsize=32)
def _sst_ika_jit(w: int, n: int, m: int, g: int, r: int, Tpad: int):
    """Module-cached jitted ika runner for one (geometry, bucket) — the
    same one-compile-per-config discipline as _changefinder_jit.

    Offsets are CONSECUTIVE, so every Hankel entry is a static shift of
    the series: H[k][i, j] = x[base + k + j + i]. The [Tpad-w+1, w]
    sliding-window view builds from w static slices and each Hankel
    column j is a static K-row slice of it — zero gathers (a [K, w, n]
    advanced-index gather lowered to ~2.2M scalar loads and ran 100x
    slower than the matmuls it fed)."""
    import jax
    import jax.numpy as jnp

    start = w + n - 1
    K = Tpad - g - m - start
    base_p = start - n - w + 1                     # = 0
    # future column j covers x[t+g-w+1+j : t+g+1+j] — the FIRST future
    # window ends at t+g, the first post-gap point (without the +1 it
    # ended at t+g-1, scoring a window that never looked past the gap);
    # the svd scorer below builds the same window, pinned by
    # test_sst_ika_matches_svd_detection's argmax tolerance of 1
    base_f = start + g - w + 1

    @jax.jit
    def run(xj):
        W = jnp.stack([xj[s:s + (Tpad - w + 1)] for s in range(w)],
                      axis=1)                      # W[p] = x[p:p+w]
        H_p = jnp.stack([W[base_p + j:base_p + j + K]
                         for j in range(n)], axis=2)   # [K, w, n]
        H_f = jnp.stack([W[base_f + j:base_f + j + K]
                         for j in range(m)], axis=2)   # [K, w, m]
        return _sst_ika_scores(H_p, H_f, r)

    return run


def sst(series: Sequence[float], options: str = "") -> List[float]:
    """SQL: sst(x[, options]) — singular-spectrum-transform change score
    per element (0 until enough history). Batched: every offset's past
    and future Hankel matrices process in one dispatch. `-scorefunc svd`
    (default, reference default) runs the exact vmapped SVD; `-scorefunc
    ika` runs the reference's power-iteration score function as pure
    batched matmuls (~100x on TPU — SVD lowers to per-matrix iterative
    LAPACK-style loops there)."""
    import jax
    import jax.numpy as jnp

    ns = SST_SPEC.parse(options)
    x = np.asarray(list(series), np.float32)
    w = int(ns.w)
    n = int(ns.n) or w
    m = int(ns.m) or w
    g = int(ns.g) or max(1, w // 4)
    r = int(ns.r)
    scorefunc = str(ns.scorefunc).lower()
    T = len(x)
    start = w + n - 1          # first t with a full past matrix
    need = start + g + m       # and a full future matrix
    if T <= need:
        return [0.0] * T

    ts = np.arange(start, T - g - m)
    scores = np.zeros(T, np.float32)

    if scorefunc == "ika":
        # pad to a bucket so one compile serves every series length in
        # the bucket (the jitted runner is module-cached — a per-call
        # closure re-traced each call, ~5 s of the 5.6 s wall), then
        # slice the valid offsets; padded offsets read only zeros
        Tpad = _bucket(T)
        xp = np.zeros(Tpad, np.float32)
        xp[:T] = x
        run = _sst_ika_jit(w, n, m, g, r, Tpad)
        scores[ts] = np.asarray(run(jnp.asarray(xp)))[:len(ts)]
        return scores.tolist()

    xj = jnp.asarray(x)

    def hankel(t0, cols):
        # columns j: x[t0 + j - w + 1 : t0 + j + 1]
        return jnp.stack([jax.lax.dynamic_slice(xj, (t0 + j - w + 1,), (w,))
                          for j in range(cols)], axis=1)

    @jax.jit
    def score_at(t):
        past = hankel(t - n + 1 - 1, n)       # ends at t-1... columns upto t
        fut = hankel(t + g, m)                # first column ends at t+g (the
        # first post-gap point) — the same window the ika path's base_f
        # builds, so the two score functions disagree only by iteration
        # convergence, never by alignment
        up, _, _ = jnp.linalg.svd(past, full_matrices=False)
        uf, _, _ = jnp.linalg.svd(fut, full_matrices=False)
        s = jnp.linalg.svd(up[:, :r].T @ uf[:, :r], compute_uv=False)
        return 1.0 - s[0]

    vals = jax.vmap(score_at)(jnp.asarray(ts))
    scores[ts] = np.asarray(vals)
    return scores.tolist()
