"""Disk-backed multi-epoch replay for the process() lifecycle.

Reference: hivemall's NioStatefulSegment (SURVEY.md §3.20): UDTF trainers
buffer every processed row and, when ``-iters > 1``, replay the stream for
further epochs; beyond a memory budget the buffer spills to local disk
segments and epochs stream them back.

TPU-side analog: rows accumulate in RAM as (idx, val) arrays; once the
running byte budget (``HIVEMALL_TPU_REPLAY_BUDGET_MB``, default 512) is
exceeded, the buffered block compacts into a CSR .npz segment file in a
temp directory. Epoch replay shuffles segment order and row order within
each segment (loading one segment at a time, so resident memory stays one
segment regardless of corpus size); when nothing spilled, the caller keeps
the exact in-RAM global-permutation behavior of earlier rounds.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, List, Tuple

import numpy as np

__all__ = ["RowSegmentStore", "skip_batches"]


def skip_batches(batches, n: int):
    """Advance a batch stream past its first ``n`` items — the resume-side
    half of the checkpoint stream-position contract (SURVEY.md §6):
    autosaved bundles record how many source batches were dispatched, and
    a resumed ``fit_stream(..., resume=True)`` re-opens the SAME
    deterministic stream (same shard order, same shuffle seed) and skips
    that prefix, so training continues on exactly the batches the crashed
    run never saw. Raises ValueError if the stream ends inside the skip —
    that means the caller re-opened a different (shorter) stream than the
    checkpoint was cut from."""
    it = iter(batches)
    for i in range(int(n)):
        try:
            next(it)
        except StopIteration:
            raise ValueError(
                f"stream exhausted at batch {i} while skipping to the "
                f"checkpointed position {n} — resumed stream does not "
                f"match the one the checkpoint was written against") from None
    return it


def _default_budget() -> int:
    mb = float(os.environ.get("HIVEMALL_TPU_REPLAY_BUDGET_MB", "512"))
    return int(mb * (1 << 20))


class RowSegmentStore:
    """Append-only store of (idx, val, label) rows with disk spill."""

    def __init__(self, budget_bytes: int | None = None):
        self.budget = _default_budget() if budget_bytes is None \
            else int(budget_bytes)
        self.ram_rows: List[Tuple[np.ndarray, np.ndarray]] = []
        self.ram_labels: List[float] = []
        self._ram_bytes = 0
        self._segments: List[str] = []
        self._tmpdir: str | None = None
        self.n_rows = 0

    @property
    def spilled(self) -> bool:
        return bool(self._segments)

    def append(self, rows, labels) -> None:
        # a row is an arity-k tuple of parallel arrays (linear trainers:
        # (idx, val); FFM: (idx, val, field); ...)
        for r in rows:
            self._ram_bytes += sum(np.asarray(a).nbytes for a in r) + 64
        self.ram_rows.extend(rows)
        self.ram_labels.extend(labels)
        self.n_rows += len(rows)
        if self._ram_bytes > self.budget:
            self._spill()

    def _spill(self) -> None:
        if not self.ram_rows:
            return
        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(prefix="hivemall_tpu_replay_")
        lens = np.fromiter((len(r[0]) for r in self.ram_rows), np.int64,
                           len(self.ram_rows))
        indptr = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=indptr[1:])
        arity = len(self.ram_rows[0])
        payload = {"indptr": indptr,
                   "lab": np.asarray(self.ram_labels, np.float32)}
        for k in range(arity):
            payload[f"a{k}"] = np.concatenate(
                [np.asarray(r[k]) for r in self.ram_rows])
        path = os.path.join(self._tmpdir,
                            f"seg{len(self._segments):05d}.npz")
        np.savez(path, **payload)
        self._segments.append(path)
        self.ram_rows, self.ram_labels, self._ram_bytes = [], [], 0

    def _load(self, path: str):
        z = np.load(path)
        indptr, lab = z["indptr"], z["lab"]
        comps = [z[k] for k in sorted(
            (f for f in z.files if f.startswith("a")),
            key=lambda f: int(f[1:]))]
        rows = [tuple(c[indptr[i]:indptr[i + 1]] for c in comps)
                for i in range(len(lab))]
        return rows, lab.tolist()

    def epoch_rows(self, rng) -> Iterator[Tuple[list, list]]:
        """One epoch: yields (rows, labels) blocks, one per segment (plus
        the RAM tail), segment order and within-segment row order
        shuffled. Resident memory = one segment."""
        units: List[int | str] = list(self._segments)
        if self.ram_rows:
            units.append("ram")
        order = rng.permutation(len(units))
        for u in order:
            unit = units[int(u)]
            if unit == "ram":
                rows, labels = self.ram_rows, self.ram_labels
            else:
                rows, labels = self._load(unit)
            perm = rng.permutation(len(rows))
            yield ([rows[i] for i in perm], [labels[i] for i in perm])

    def cleanup(self) -> None:
        if self._tmpdir is not None:
            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None
        self._segments = []
        self.ram_rows, self.ram_labels = [], []
        self._ram_bytes = 0
        self.n_rows = 0
