"""Warehouse-scale offline scoring: the HivemallOps batch path.

Reference analog (SURVEY.md §4.6 Spark HivemallOps [B], §3.15
``each_top_k``): Hivemall's other half is offline — score an entire
warehouse table overnight, not one request at a time. This module is that
path as a library call plus the ``hivemall_tpu predict --input <parquet
dir>`` CLI plumbing:

- **Input** is a directory of Parquet shards (the PR 6 out-of-core
  layout) or a single LIBSVM/Parquet file. Shards decode through the
  SAME :class:`~.shard_cache.ShardDecodeCache` the training stream uses
  (same parse-config key), so a table that was ever trained on scores
  warm with zero Parquet read + parse cost.
- **Model source** defaults to the promotion pointer
  (:func:`~.checkpoint.read_promoted`): nightly jobs score with exactly
  the serving model. The resolved bundle is pinned
  (:func:`~.checkpoint.hold_bundle`) for the whole run so checkpoint
  retention can never GC it mid-job.
- **Backends**: ``kernel`` scores through the jitted shape-bucketed
  kernels (:func:`~.sparse.score_batches` — bit-identical to the offline
  ``predict_proba`` path); ``arena`` scores through the PR 15 mmap'd
  numpy/int8 twins (:mod:`~.weight_arena`) — no device at all, the
  pure-CPU scoring-fleet shape (docs/RELIABILITY.md). ``auto`` probes
  both on a sample of the first shard and picks the measured-fastest,
  per host (docs/PERFORMANCE.md "Bulk scoring").
- **Fan-out** mirrors ``-ingest_pool``: shards are scored by a pool of
  worker processes (spawn — JAX is fork-unsafe once initialized), each
  building its scorer once and streaming its shards; ``workers=1`` runs
  inline. Memory is bounded by (workers × one shard), never the table.
- **One pass** writes scored Parquet (one output shard per input shard,
  same basenames so sorted order is row order), folds the evaluation
  UDAFs (logloss/AUC/rmse via :mod:`~..frame.evaluation` — AUC exact up
  to a row cap, histogram-merged beyond), and optionally composes with
  ``frame.tools.each_top_k`` through the streaming
  :class:`~..frame.tools.TopKAccumulator` for the canonical "score then
  top-k per user" job.

Progress is a live ``bulk`` obs-registry section (stub parity with
``obs.registry.BULK_STUB``) plus ``bulk`` events on the metrics stream;
``hivemall_tpu obs`` renders a progress line from either.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs.flight import FS, get_flight
from .sparse import SparseDataset, score_batches

__all__ = ["bulk_predict", "BulkProgress", "resolve_model_bundle",
           "AUC_EXACT_CAP"]

#: rows of (label, score) retained for EXACT AUC before degrading to the
#: histogram merge (still via the same rank statistic, binned) — bounds
#: master-side memory on billion-row tables
AUC_EXACT_CAP = 8 << 20
_AUC_BINS = 4096
_PROBE_ROWS = 4096


# --------------------------------------------------------------------------
# model resolution

def resolve_model_bundle(algo: str, *, bundle: Optional[str] = None,
                         checkpoint_dir: Optional[str] = None
                         ) -> Tuple[str, str]:
    """``(bundle_path, source)`` for a bulk job: an explicit bundle wins;
    else the checkpoint dir's PROMOTED pointer (the serving model — the
    default nightly-job contract), falling back to the newest step bundle
    when nothing was ever promoted."""
    from ..catalog import lookup
    from .checkpoint import newest_bundle, promoted_bundle
    if bundle:
        return bundle, "explicit"
    if not checkpoint_dir:
        raise ValueError("bulk predict needs --bundle or --checkpoint-dir")
    name = lookup(algo).resolve().NAME
    hit = promoted_bundle(checkpoint_dir, name)
    if hit is not None:
        return hit[1], "promoted"
    hit = newest_bundle(checkpoint_dir, name)
    if hit is not None:
        return hit[1], "newest"
    raise FileNotFoundError(
        f"no {name} bundles under {checkpoint_dir}")


# --------------------------------------------------------------------------
# per-process scorer state (workers build this once, then stream shards)

_state_lock = threading.Lock()
_states: Dict[str, "_BackendState"] = {}


def _trainer_scores(trainer, ds: SparseDataset,
                    batch_size: Optional[int]) -> np.ndarray:
    """Output-space scores through the trainer's OWN offline path when no
    batch size is forced — ``predict_proba``/``decision_function`` choose
    their own bucket sizes, and riding them is what makes the kernel
    backend bit-identical to offline scoring by construction."""
    if batch_size:
        return np.asarray(trainer.score_dataset(ds, batch_size), np.float32)
    classification = getattr(trainer, "classification",
                             getattr(trainer, "CLASSIFICATION", False))
    if classification and hasattr(trainer, "predict_proba"):
        return np.asarray(trainer.predict_proba(ds), np.float32)
    if not classification and hasattr(trainer, "decision_function"):
        return np.asarray(trainer.decision_function(ds), np.float32)
    return np.asarray(trainer.score_dataset(ds), np.float32)


class _BackendState:
    """One process's scorer: jitted trainer (``kernel``) or mmap'd arena
    tier (``arena``), plus the shard decode cache. Built lazily per
    worker process, reused across that worker's shards."""

    def __init__(self, cfg: Dict[str, Any]):
        from ..catalog import lookup
        self.cfg = cfg
        self.backend = cfg["backend"]
        self.precision = cfg["precision"]
        self.batch_size = cfg.get("batch_size") or None
        self._cls = lookup(cfg["algo"]).resolve()
        self.trainer = None
        self.arena = None
        self._arena_fn = None
        if self.backend == "kernel":
            t = self._cls(cfg["options"] or "")
            t.load_bundle(cfg["bundle"])
            self.trainer = t
        else:
            from .weight_arena import try_open_arena
            a = try_open_arena(cfg["bundle"], trainer_name=self._cls.NAME,
                               precision=self.precision)
            if a is None:
                # the master publishes before fanning out; a worker can
                # only get here when the sidecar was deleted mid-run
                raise FileNotFoundError(
                    f"no usable arena sidecar for {cfg['bundle']}")
            self.arena = a
            self._arena_fn = a.scorer(self.precision)
        self.cache = None
        if cfg.get("cache_dir"):
            from .shard_cache import ShardDecodeCache
            self.cache = ShardDecodeCache(cfg["cache_dir"], cfg["parse_kw"])

    def decode(self, kind: str, path: str) -> SparseDataset:
        if kind == "libsvm":
            from .libsvm import read_libsvm
            kw = self.cfg["parse_kw"]
            if kw.get("ffm"):
                return read_libsvm(path, ffm=True,
                                   num_fields=kw["num_fields"],
                                   dims=kw.get("dims"))
            return read_libsvm(path)
        if self.cache is not None:
            ds = self.cache.load(path)
            if ds is not None:
                return ds
        import pyarrow.parquet as pq
        from .arrow import table_to_dataset
        ds = table_to_dataset(pq.read_table(path), **self.cfg["parse_kw"])
        if self.cache is not None:
            self.cache.store(path, ds)
        return ds

    def score(self, ds: SparseDataset) -> np.ndarray:
        if self.backend == "kernel":
            return _trainer_scores(self.trainer, ds, self.batch_size)
        bs = int(self.batch_size or 1024)
        out = np.empty(len(ds), np.float32)
        for s, b in score_batches(ds, bs):
            nv = b.n_valid or b.batch_size
            # output path: the per-batch score fetch IS the product
            # graftcheck: disable=GC07
            out[s:s + nv] = np.asarray(self._arena_fn(b), np.float32)[:nv]
        return out

    def release(self) -> None:
        if self.arena is not None:
            self.arena.release()
            self.arena = None
        self.trainer = None
        self._arena_fn = None


def _get_state(cfg: Dict[str, Any]) -> _BackendState:
    key = cfg["digest"]
    with _state_lock:
        st = _states.get(key)
        if st is None:
            st = _BackendState(cfg)
            _states[key] = st
        return st


def _release_states() -> None:
    """Drop every cached scorer state in THIS process — the inline/thread
    pools run workers in the master, and a cached arena mmap outliving
    the job would fail the leak census that gates the bulk smoke."""
    with _state_lock:
        states = list(_states.values())
        _states.clear()
    for st in states:
        st.release()


def _score_shard_task(cfg: Dict[str, Any], kind: str, path: str,
                      index: int) -> Dict[str, Any]:
    """Score ONE shard: decode (through the shared cache), score through
    the configured backend, write the scored output shard, and return the
    master's aggregation payload (labels+scores ride back for the exact
    evaluation UDAFs; top-k returns only the per-group k best — a row
    outside its shard's per-group k best can never rank globally)."""
    t0 = time.perf_counter()
    # shard lifecycle to the flight ring: a pool worker SIGKILLed (OOM)
    # mid-shard leaves a start with no done — the post-mortem names the
    # exact shard that killed it. Workers inherit $HIVEMALL_TPU_FLIGHT
    # through the spawn env; unset, this is one attribute check.
    fl = get_flight()
    if fl.enabled:
        fl.record("bulk.shard.start",
                  f"i={index}{FS}file={os.path.basename(path)[:48]}")
    st = _get_state(cfg)
    ds = st.decode(kind, path)
    t1 = time.perf_counter()
    scores = st.score(ds)
    t2 = time.perf_counter()
    if fl.enabled:
        fl.record("bulk.shard.done",
                  f"i={index}{FS}rows={len(ds)}{FS}"
                  f"d={(t1 - t0) * 1e3:.1f}{FS}s={(t2 - t1) * 1e3:.1f}")

    out_path = None
    group = None
    if cfg.get("group_col"):
        import pyarrow.parquet as pq
        if kind != "parquet":
            raise ValueError("--group-col needs Parquet input")
        group = pq.read_table(path, columns=[cfg["group_col"]]) \
            .column(cfg["group_col"]).to_numpy(zero_copy_only=False)
    if cfg.get("output_dir"):
        import pyarrow as pa
        import pyarrow.parquet as pq
        name = os.path.basename(path) if kind == "parquet" \
            else f"scores-{index:05d}.parquet"
        if not name.endswith((".parquet", ".pq")):
            name += ".parquet"
        cols = {"label": pa.array(ds.labels, pa.float32()),
                "score": pa.array(scores, pa.float32())}
        if group is not None:
            cols[cfg["group_col"]] = pa.array(group)
        out_path = os.path.join(cfg["output_dir"], name)
        pq.write_table(pa.table(cols), out_path)

    topk = None
    if cfg.get("top_k") and group is not None:
        from ..frame.tools import TopKAccumulator
        acc = TopKAccumulator(cfg["top_k"])
        acc.add_many(group.tolist(), scores,
                     [f"{index}:{r}" for r in range(len(scores))])
        # per-shard survivors only — (group, score, rowref), unranked;
        # the master re-accumulates globally and ranks via each_top_k
        topk = [(g, s, v) for g, _rank, s, v in acc.result()]

    return {"index": index, "rows": int(len(ds)),
            "decode_seconds": t1 - t0, "score_seconds": t2 - t1,
            "busy_seconds": time.perf_counter() - t0,
            "out_path": out_path, "topk": topk,
            "labels": np.asarray(ds.labels, np.float32),
            "scores": scores}


def _group_components(files: List[str], group_col: str) -> List[List[int]]:
    """Union-find shards into group-closed components: two shards land
    in one component iff they share >=1 ``group_col`` value (directly or
    transitively). Fused per-group top-k then routes ONE pooled task per
    component, so no group's candidate set is ever split across workers
    — each worker returns final per-group k-bests and the master merge
    degenerates to concatenation of disjoint group sets. Reads only the
    group column of each shard. Components come back as ascending shard
    indices, ordered by first member."""
    import pyarrow.parquet as pq
    parent = list(range(len(files)))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    owner: Dict[Any, int] = {}
    for i, f in enumerate(files):
        vals = pq.read_table(f, columns=[group_col]) \
            .column(group_col).to_numpy(zero_copy_only=False)
        for v in np.unique(vals).tolist():
            j = owner.setdefault(v, i)
            if j != i:
                ra, rb = find(i), find(j)
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)
    comps: Dict[int, List[int]] = {}
    for i in range(len(files)):
        comps.setdefault(find(i), []).append(i)
    return [sorted(m) for _, m in
            sorted(comps.items(), key=lambda kv: min(kv[1]))]


def _score_component_task(cfg: Dict[str, Any], kind: str,
                          paths: List[str],
                          indices: List[int]) -> List[Dict[str, Any]]:
    """Score one group-closed shard component in a single worker: each
    member shard scores exactly as `_score_shard_task` would, but the
    members' per-group top-k survivors merge HERE (ascending shard
    order, preserving the arrival-order tie semantics the master merge
    would have applied) and ride back once on the first member."""
    from ..frame.tools import TopKAccumulator
    results = []
    acc = None
    for path, idx in zip(paths, indices):
        res = _score_shard_task(cfg, kind, path, idx)
        if res["topk"] is not None:
            if acc is None:
                acc = TopKAccumulator(cfg["top_k"])
            for g, s, v in res["topk"]:
                acc.add(g, s, v)
            res["topk"] = None
        results.append(res)
    if acc is not None:
        results[0]["topk"] = [(g, s, v)
                              for g, _rank, s, v in acc.result()]
    return results


# --------------------------------------------------------------------------
# streaming evaluation UDAFs

class _EvalAccum:
    """Exactly-decomposable logloss/rmse sums + AUC that is EXACT (the
    frame/evaluation rank statistic over retained rows) up to
    ``AUC_EXACT_CAP`` rows and a binned midrank merge beyond it."""

    def __init__(self, classification: bool):
        self.classification = classification
        self.n = 0
        self._ll_sum = 0.0
        self._se_sum = 0.0
        self._rows: Optional[List[Tuple[np.ndarray, np.ndarray]]] = []
        self._pos_hist = np.zeros(_AUC_BINS, np.int64)
        self._neg_hist = np.zeros(_AUC_BINS, np.int64)

    def add(self, labels: np.ndarray, scores: np.ndarray) -> None:
        n = len(labels)
        if n == 0:
            return
        from ..frame.evaluation import logloss
        self.n += n
        if self.classification:
            self._ll_sum += float(logloss(labels, scores)) * n
            if self._rows is not None and self.n <= AUC_EXACT_CAP:
                self._rows.append((labels, scores))
            else:
                if self._rows is not None:       # degrade: bin the backlog
                    for lab, sc in self._rows:
                        self._bin(lab, sc)
                    self._rows = None
                self._bin(labels, scores)
        else:
            d = np.asarray(labels, np.float64) - np.asarray(scores,
                                                            np.float64)
            self._se_sum += float(np.dot(d, d))

    def _bin(self, labels: np.ndarray, scores: np.ndarray) -> None:
        b = np.clip((np.asarray(scores, np.float64) * _AUC_BINS).astype(
            np.int64), 0, _AUC_BINS - 1)
        pos = np.asarray(labels) > 0
        self._pos_hist += np.bincount(b[pos], minlength=_AUC_BINS)
        self._neg_hist += np.bincount(b[~pos], minlength=_AUC_BINS)

    def result(self) -> Dict[str, Any]:
        if self.n == 0:
            return {}
        if not self.classification:
            return {"rmse": round(float(np.sqrt(self._se_sum / self.n)), 6)}
        out: Dict[str, Any] = {"logloss": round(self._ll_sum / self.n, 6)}
        if self._rows is not None:
            from ..frame.evaluation import auc
            labels = np.concatenate([r[0] for r in self._rows])
            scores = np.concatenate([r[1] for r in self._rows])
            out["auc"] = round(float(auc(labels, scores)), 6)
            out["auc_method"] = "exact"
            return out
        P, N = int(self._pos_hist.sum()), int(self._neg_hist.sum())
        if P and N:
            # binned midrank: negatives strictly below each bin count
            # fully, same-bin negatives count half (ties at bin width)
            neg_below = np.concatenate(
                [[0], np.cumsum(self._neg_hist)[:-1]])
            wins = float((self._pos_hist * neg_below).sum()) \
                + 0.5 * float((self._pos_hist * self._neg_hist).sum())
            out["auc"] = round(wins / (P * N), 6)
            out["auc_method"] = "histogram"
        return out


# --------------------------------------------------------------------------
# live obs section

class BulkProgress:
    """The ``bulk`` obs-registry section of a running job — key-for-key
    the shape of ``obs.registry.BULK_STUB`` (GC05 stub parity)."""

    def __init__(self):
        self.active = False
        self.input = None
        self.output = None
        self.backend = None
        self.precision = None
        self.workers = 0
        self.shards_total = 0
        self.shards_done = 0
        self.rows_scored = 0
        self.busy_seconds = 0.0
        self.model_step = None
        self.bundle = None
        self._t0 = time.monotonic()
        self._elapsed = 0.0

    def elapsed(self) -> float:
        return time.monotonic() - self._t0 if self.active else self._elapsed

    def finish(self) -> None:
        self._elapsed = time.monotonic() - self._t0
        self.active = False

    def obs_section(self) -> dict:
        el = self.elapsed()
        util = self.busy_seconds / (el * self.workers) \
            if el > 0 and self.workers else 0.0
        return {"active": self.active, "input": self.input,
                "output": self.output, "backend": self.backend,
                "precision": self.precision, "workers": self.workers,
                "shards_total": self.shards_total,
                "shards_done": self.shards_done,
                "rows_scored": self.rows_scored,
                "rows_per_sec": round(self.rows_scored / el, 1)
                if el > 0 else 0.0,
                "worker_utilization": round(min(util, 1.0), 4),
                "elapsed_seconds": round(el, 3),
                "model_step": self.model_step, "bundle": self.bundle}


def _register_progress(prog: BulkProgress) -> None:
    from ..obs.registry import BULK_STUB, registry
    ref = weakref.ref(prog)

    def _obs() -> dict:
        p = ref()
        return p.obs_section() if p is not None else dict(BULK_STUB)

    registry.register("bulk", _obs)


# --------------------------------------------------------------------------
# backend probe

def _probe_backends(cfg: Dict[str, Any], kind: str,
                    first_shard: str) -> Tuple[str, Dict[str, Any]]:
    """Measure kernel vs arena rows/s on a sample of the first shard and
    pick the faster — the per-host heuristic of docs/PERFORMANCE.md
    "Bulk scoring". Probe states are built and released HERE (master);
    workers rebuild only the winning backend."""
    info: Dict[str, Any] = {"rows": 0}
    sample = None
    best, best_rate = "kernel", -1.0
    try:
        for backend in ("kernel", "arena"):
            c = dict(cfg, backend=backend,
                     digest=f"probe:{backend}:{cfg['digest']}")
            try:
                if backend == "arena":
                    # first bulk run against a bundle may predate any
                    # arena sidecar — publish one so the race is real
                    # (persists for every later nightly run); trainer
                    # families without arena support degrade to kernel
                    from ..catalog import lookup
                    from .weight_arena import ArenaUnsupported
                    try:
                        _ensure_arena_published(
                            lookup(cfg["algo"]).resolve(), c)
                    except ArenaUnsupported:
                        continue
                st = _BackendState(c)
            except (FileNotFoundError, ValueError, KeyError, OSError):
                continue
            try:
                if sample is None:
                    ds = st.decode(kind, first_shard)
                    sample = ds.take(np.arange(min(len(ds), _PROBE_ROWS)))
                    info["rows"] = int(len(sample))
                if len(sample) == 0:
                    continue
                st.score(sample)                       # warm (compiles)
                rate = 0.0
                for _ in range(2):                     # best of 2
                    t0 = time.perf_counter()
                    st.score(sample)
                    dt = time.perf_counter() - t0
                    rate = max(rate, len(sample) / max(dt, 1e-9))
            finally:
                st.release()
            info[f"{backend}_rows_per_sec"] = round(rate, 1)
            if rate > best_rate:
                best, best_rate = backend, rate
    finally:
        sample = None
    info["chosen"] = best
    return best, info


# --------------------------------------------------------------------------
# the bulk job

def bulk_predict(algo: str, input_path: str,
                 output_dir: Optional[str] = None, *,
                 options: str = "",
                 bundle: Optional[str] = None,
                 checkpoint_dir: Optional[str] = None,
                 backend: str = "auto", precision: str = "f32",
                 workers: int = 1, pool: str = "process",
                 batch_size: Optional[int] = None,
                 cache_dir: Optional[str] = None,
                 top_k: int = 0, group_col: Optional[str] = None,
                 feature_col: str = "features",
                 label_col: str = "label") -> Dict[str, Any]:
    """Score a Parquet shard directory (or single Parquet/LIBSVM file)
    through the bulk path; returns the job summary dict. See the module
    docstring for the full contract."""
    from ..catalog import lookup
    from .checkpoint import bundle_step, hold_bundle

    if precision != "f32" and backend == "kernel":
        raise ValueError(
            f"backend=kernel scores f32 only (got precision={precision}); "
            f"quantized tiers score through the arena twins")
    cls = lookup(algo).resolve()
    parser = cls.make_parser(options or "")
    # make_parser skips __init__, so option-driven instance flags (FM's
    # -classification) aren't set — fold the parsed option in explicitly
    classification = getattr(parser, "classification",
                             getattr(parser, "CLASSIFICATION", False))
    o = getattr(parser, "opts", None)
    if o is not None and o.get("classification"):
        classification = True
    parse_kw: Dict[str, Any] = dict(
        feature_col=feature_col, label_col=label_col,
        dims=getattr(parser, "dims", None))
    if getattr(parser, "F", None) is not None and cls.NAME == "train_ffm":
        parse_kw.update(ffm=True, num_fields=parser.F)

    bundle_path, source = resolve_model_bundle(
        algo, bundle=bundle, checkpoint_dir=checkpoint_dir)

    if os.path.isdir(input_path) \
            or input_path.endswith((".parquet", ".pq")):
        from .arrow import _parquet_files
        kind, files = "parquet", _parquet_files(input_path)
    else:
        kind, files = "libsvm", [input_path]
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)

    workers = max(1, int(workers))
    if workers == 1:
        pool = "inline"
    cfg: Dict[str, Any] = {
        "algo": algo, "options": options or "", "bundle": bundle_path,
        "backend": backend, "precision": precision,
        "batch_size": int(batch_size) if batch_size else None,
        "cache_dir": cache_dir, "parse_kw": parse_kw,
        "output_dir": output_dir, "top_k": int(top_k),
        "group_col": group_col,
    }
    cfg["digest"] = json.dumps(
        {k: v for k, v in cfg.items() if k != "digest"},
        sort_keys=True, default=str)

    prog = BulkProgress()
    prog.active = True
    prog.input = input_path
    prog.output = output_dir
    prog.precision = precision
    prog.workers = workers
    prog.shards_total = len(files)
    prog.bundle = bundle_path
    prog.model_step = bundle_step(bundle_path)
    _register_progress(prog)

    from ..utils.metrics import get_stream
    stream = get_stream()

    with hold_bundle(bundle_path):      # retention must not GC it mid-run
        probe_info = None
        if backend == "auto" and precision == "f32":
            backend, probe_info = _probe_backends(cfg, kind, files[0])
        elif backend == "auto":
            backend = "arena"           # quantized tiers are arena-only
        cfg["backend"] = backend
        cfg["digest"] = json.dumps(
            {k: v for k, v in cfg.items() if k != "digest"},
            sort_keys=True, default=str)
        prog.backend = backend

        if backend == "arena":
            _ensure_arena_published(cls, cfg)
        if stream.enabled:
            stream.emit("bulk", phase="start", **prog.obs_section())
        fl = get_flight()
        if fl.enabled:
            fl.record("bulk.start",
                      f"shards={len(files)}{FS}workers={workers}{FS}"
                      f"backend={backend}{FS}pool={pool}")

        ev = _EvalAccum(classification)
        topk_by_shard: Dict[int, list] = {}
        scored_files: List[Optional[str]] = [None] * len(files)
        busy = 0.0

        # group-aware shard routing (ROADMAP item 5 follow-up): with a
        # fused per-group top-k, shards sharing group values union into
        # one pooled task so no group's candidates split across workers
        components = None
        if top_k and group_col and kind == "parquet" and len(files) > 1:
            components = _group_components(files, group_col)
            if fl.enabled:
                fl.record("bulk.route",
                          f"components={len(components)}{FS}"
                          f"largest={max(len(c) for c in components)}")

        def _fold(res: Dict[str, Any]) -> None:
            nonlocal busy
            ev.add(res.pop("labels"), res.pop("scores"))
            if res["topk"] is not None:
                topk_by_shard[res["index"]] = res["topk"]
            scored_files[res["index"]] = res["out_path"]
            busy += res["busy_seconds"]
            prog.shards_done += 1
            prog.rows_scored += res["rows"]
            prog.busy_seconds = busy
            if stream.enabled:
                stream.emit("bulk", phase="shard", **prog.obs_section())

        try:
            if pool == "inline":
                if components is None:
                    for i, f in enumerate(files):
                        _fold(_score_shard_task(cfg, kind, f, i))
                else:
                    for comp in components:
                        for res in _score_component_task(
                                cfg, kind, [files[i] for i in comp], comp):
                            _fold(res)
            else:
                import concurrent.futures as cf
                if pool == "process":
                    import multiprocessing as mp
                    ex = cf.ProcessPoolExecutor(
                        max_workers=workers,
                        mp_context=mp.get_context("spawn"))
                else:
                    ex = cf.ThreadPoolExecutor(
                        max_workers=workers, thread_name_prefix="bulk")
                try:
                    if components is None:
                        futs = [ex.submit(_score_shard_task, cfg, kind,
                                          f, i)
                                for i, f in enumerate(files)]
                        for fut in cf.as_completed(futs):
                            _fold(fut.result())
                    else:
                        futs = [ex.submit(_score_component_task, cfg,
                                          kind, [files[i] for i in comp],
                                          comp)
                                for comp in components]
                        for fut in cf.as_completed(futs):
                            for res in fut.result():
                                _fold(res)
                finally:
                    ex.shutdown(wait=True)
        finally:
            # inline/thread pools cache scorer state (and arena mmaps)
            # in THIS process — release on every exit path (GC12)
            if pool != "process":
                _release_states()
            prog.finish()

        topk_file = None
        topk_rows = 0
        if top_k and group_col:
            from ..frame.tools import TopKAccumulator
            acc = TopKAccumulator(top_k)
            for i in sorted(topk_by_shard):     # shard order = arrival
                for g, s, ref in topk_by_shard[i]:
                    acc.add(g, s, ref)
            rows = list(acc.result())
            topk_rows = len(rows)
            if output_dir:
                topk_file = os.path.join(output_dir, "topk.tsv")
                tmp = topk_file + ".tmp"
                with open(tmp, "w") as fh:
                    for g, rank, s, ref in rows:
                        fh.write(f"{g}\t{rank}\t{s:.6g}\t{ref}\n")
                os.replace(tmp, topk_file)

    section = prog.obs_section()
    # keep the finished job's section live after prog is collected (the
    # CLI snapshots AFTER return) — same keys, so stub parity holds
    from ..obs.registry import registry
    registry.register("bulk", lambda s=dict(section): dict(s))
    if stream.enabled:
        stream.emit("bulk", phase="done", **section)
    if fl.enabled:
        fl.record("bulk.done",
                  f"rows={prog.rows_scored}{FS}shards={len(files)}")
    result: Dict[str, Any] = {
        "rows": prog.rows_scored, "shards": len(files),
        "backend": backend, "precision": precision,
        "workers": workers, "pool": pool,
        "bundle": bundle_path, "bundle_source": source,
        "model_step": prog.model_step,
        "elapsed_seconds": section["elapsed_seconds"],
        "rows_per_sec": section["rows_per_sec"],
        "worker_utilization": section["worker_utilization"],
        "metrics": ev.result(),
        "output": output_dir,
        "scored_files": [p for p in scored_files if p],
    }
    if probe_info is not None:
        result["probe"] = probe_info
    if top_k and group_col:
        result["topk_file"] = topk_file
        result["topk_rows"] = topk_rows
        if components is not None:
            result["group_components"] = len(components)
    return result


def _ensure_arena_published(cls, cfg: Dict[str, Any]) -> None:
    """Publish the arena sidecar ONCE in the master before fan-out (N
    workers racing publish_arena would each pay the bundle load)."""
    from .weight_arena import open_arena, publish_arena, try_open_arena
    a = try_open_arena(cfg["bundle"], trainer_name=cls.NAME,
                       precision=cfg["precision"])
    if a is not None:
        a.release()
        return
    t = cls(cfg["options"])
    t.load_bundle(cfg["bundle"])
    open_arena(publish_arena(cfg["bundle"], t)).release()


# --------------------------------------------------------------------------
# smoke: python -m hivemall_tpu.io.bulk --smoke  (run_tests.sh, tsan +
# leaktrack enabled there)

def _synth(n: int, dims: int, max_len: int, seed: int) -> SparseDataset:
    rng = np.random.default_rng(seed)
    lens = rng.integers(1, max_len + 1, n)
    indptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    idx = rng.integers(1, dims - 1, int(indptr[-1])).astype(np.int32)
    val = rng.standard_normal(int(indptr[-1])).astype(np.float32)
    w = rng.standard_normal(dims).astype(np.float32)
    margins = np.asarray([w[idx[s:e]] @ val[s:e]
                          for s, e in zip(indptr[:-1], indptr[1:])])
    labels = np.where(margins > 0, 1.0, -1.0).astype(np.float32)
    return SparseDataset(idx, indptr, val, labels)


def _write_empty_shard(path: str) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq
    off = np.zeros(1, np.int32)
    pq.write_table(pa.table({
        "indices": pa.ListArray.from_arrays(off, pa.array([], pa.int32())),
        "values": pa.ListArray.from_arrays(off, pa.array([], pa.float32())),
        "label": pa.array([], pa.float32())}), path)


def _smoke() -> int:
    import shutil
    import sys
    import tempfile
    from ..catalog import lookup
    from ..frame.evaluation import logloss
    from .weight_arena import score_error_bound, try_open_arena

    from ..testing import leaktrack, tsan
    if tsan.maybe_enable():
        print("bulk smoke: tsan sanitizer ON", file=sys.stderr)
    if leaktrack.maybe_enable():
        print("bulk smoke: leaktrack sanitizer ON", file=sys.stderr)
        leaktrack.snapshot()

    tmp = tempfile.mkdtemp(prefix="hivemall_tpu_bulk_smoke_")
    rc = 0
    try:
        dims = 4096
        opts = f"-dims {dims} -mini_batch 128"
        cls = lookup("train_classifier").resolve()
        trainer = cls(opts)
        trainer.fit(_synth(512, dims, 8, seed=1))
        ckdir = os.path.join(tmp, "ck")
        os.makedirs(ckdir)
        bpath = os.path.join(
            ckdir, f"{cls.NAME}-step{int(trainer._t):010d}.npz")
        trainer.save_bundle(bpath)

        test = _synth(700, dims, 8, seed=2)
        in_dir = os.path.join(tmp, "in")
        from .arrow import write_parquet_shards
        write_parquet_shards(test, in_dir, rows_per_shard=256)
        _write_empty_shard(os.path.join(in_dir, "shard-00003.parquet"))

        def _scores(out_dir):
            import pyarrow.parquet as pq
            from .arrow import _parquet_files
            return np.concatenate([
                pq.read_table(f).column("score").to_numpy(
                    zero_copy_only=False).astype(np.float32)
                for f in _parquet_files(out_dir)])

        # f32 / kernel / 2 worker processes: scored output must
        # BIT-match the offline predict_proba path
        r1 = bulk_predict(
            "train_classifier", in_dir, os.path.join(tmp, "out_f32"),
            options=opts, checkpoint_dir=ckdir, backend="kernel",
            precision="f32", workers=2, pool="process",
            cache_dir=os.path.join(tmp, "cache"))
        want = np.asarray(trainer.predict_proba(test), np.float32)
        got = _scores(os.path.join(tmp, "out_f32"))
        assert r1["rows"] == 700 and r1["shards"] == 4, r1
        assert np.array_equal(got, want), \
            f"f32 bulk != predict_proba (max delta " \
            f"{np.abs(got - want).max()})"
        ll = logloss(test.labels, want)
        assert abs(r1["metrics"]["logloss"] - ll) < 1e-4, r1["metrics"]
        assert r1["bundle_source"] == "newest" and r1["rows_per_sec"] > 0

        # int8 / arena / 2 workers: within the published error bound
        r2 = bulk_predict(
            "train_classifier", in_dir, os.path.join(tmp, "out_int8"),
            options=opts, checkpoint_dir=ckdir, backend="arena",
            precision="int8", workers=2, pool="process",
            cache_dir=os.path.join(tmp, "cache"))
        got8 = _scores(os.path.join(tmp, "out_int8"))
        arena = try_open_arena(bpath, trainer_name=cls.NAME,
                               precision="int8")
        assert arena is not None
        try:
            bound = np.empty(700, np.float32)
            for s, b in score_batches(test, 256):
                nv = b.n_valid or b.batch_size
                bound[s:s + nv] = np.asarray(
                    score_error_bound(arena, "int8", b),
                    np.float32)[:nv] / 4.0      # sigmoid is 1/4-Lipschitz
        finally:
            arena.release()
        over = np.abs(got8 - want) - (bound + 1e-6)
        assert (over <= 0).all(), \
            f"int8 bulk outside bound by {over.max()}"
        assert r2["backend"] == "arena" and r2["rows"] == 700

        print(json.dumps({"f32": {k: r1[k] for k in
                                  ("rows", "rows_per_sec", "backend",
                                   "worker_utilization", "metrics")},
                          "int8": {k: r2[k] for k in
                                   ("rows", "rows_per_sec", "backend")}},
                         default=str))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if leaktrack.enabled():
        n = leaktrack.check_and_report("bulk smoke leaktrack")
        print(f"bulk smoke leak_census: {'OK' if n == 0 else 'FAILED'} "
              f"({n} leaked resource(s) after pool drain)",
              file=sys.stderr)
        rc += 1 if n else 0
    print("bulk smoke: PASS" if rc == 0 else "bulk smoke: FAIL",
          file=sys.stderr)
    return rc


if __name__ == "__main__":
    import argparse
    import sys
    ap = argparse.ArgumentParser(prog="hivemall_tpu.io.bulk")
    ap.add_argument("--smoke", action="store_true")
    a = ap.parse_args()
    if a.smoke:
        sys.exit(_smoke())
    ap.error("only --smoke is supported; use `hivemall_tpu predict` "
             "for real jobs")
