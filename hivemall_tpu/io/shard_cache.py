"""Ahead-of-time packed shard cache — persist prepared batches on disk.

BENCH_r05 / docs/PERFORMANCE.md context: the fused FFM kernel sustains
~716k examples/sec but the e2e paths deliver 44.8k (in-RAM) and 39.4k
(Parquet streaming) because the host leg — string parse -> canonicalize ->
pack — re-runs as (mostly) single-parser Python every epoch and every
restart. The reference never met this wall (Hadoop re-ran the scan per
query but amortized it across mappers); the TPU-native analog is a
device-feeding data service where the host leg runs ONCE: after a shard is
parsed/canonicalized/packed the first time, the prepared bytes persist and
every later traversal mmaps them.

Two cache kinds share one container format (digest-keyed header + raw
array payload, written tmp -> fsync -> ``os.replace`` — the
io/checkpoint.py atomicity discipline):

:class:`PackedShardCache` — the fit()-path cache. Stores each dataset
  ROW's canonical unit-value field-major record (3-byte little-endian idx
  lanes at the shard's max canonical width + the 4 f32 label bytes +
  a per-row same-field multiplicity byte), keyed by (source identity,
  prep-config digest). Row-level storage is what makes SHUFFLED warm
  epochs bit-exact: an epoch is one permutation gather over the mmap'd
  record matrix re-sliced into ``io.sparse.PackedBatch`` buffers — the
  same bytes ``pack_unit_fieldmajor`` would have produced, so the loss
  trajectory reproduces the streamed path exactly (tests/test_shard_cache
  pins it at ``-steps_per_dispatch`` 1 and 8). Parse, canonicalize and
  pack never run on a warm epoch.

:class:`ShardDecodeCache` — the ParquetStream cache. Stores one decoded
  shard's CSR arrays (post parse + murmur hash), keyed by (shard file
  mtime/size, parse-config digest), so epoch >= 2 and restarts of the
  out-of-core path mmap the columns instead of re-reading + re-parsing
  the Parquet bytes.

Invalidation: the header carries the source identity (file mtime_ns/size,
or the dataset content sha256 when the source is RAM-only), the
prep-config digest, and a sha256 over the payload. A mutated source, a
changed prep config, or a corrupted/truncated cache file all read as a
MISS — the caller falls back to live prep and rewrites the cache
atomically. Counters (hits/misses/invalid/rebuilds/bytes) are one obs
registry section (``ingest_cache``), visible via ``/snapshot`` and
``/metrics``.

``python -m hivemall_tpu.io.shard_cache --smoke`` runs the seconds-scale
end-to-end check run_tests.sh wires in.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from ..obs.trace import get_tracer
from .sparse import PackedBatch, SparseDataset, pow2_len

__all__ = ["PackedShardCache", "CachedPackedShard", "ShardDecodeCache",
           "CacheInvalid", "write_cache_file", "read_cache_file",
           "counters", "file_source_id"]

_MAGIC = b"HMTSC001"
_FORMAT = 1


class CacheInvalid(ValueError):
    """A cache file failed validation (magic/truncation/digest)."""


# --- obs counters (registry section `ingest_cache`) -------------------------

class _Counters:
    """Process-wide cache counters; provider contract: cheap, JSON-ready."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.hits = 0
            self.misses = 0
            self.invalid = 0          # digest/magic/truncation failures
            self.rebuilds = 0         # cache files (re)written
            self.build_failed = 0     # builds aborted (uncacheable stream)
            self.bytes_mmapped = 0    # payload bytes opened for mmap reads
            self.bytes_written = 0

    def add(self, **kw: int) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def as_dict(self) -> dict:
        # canonicalizer status rides here (the ingest-path native surface):
        # report ONLY already-resolved state — a registry provider must
        # never trigger the first-use g++ build from a scrape thread
        from ..utils import native as _n
        lib = _n._LIB
        canon = ("native" if lib is not None and hasattr(lib, "canon_measure")
                 else ("python" if _n._TRIED else "unresolved"))
        with self._lock:
            return {
                "configured": True,
                "hits": self.hits,
                "misses": self.misses,
                "invalid": self.invalid,
                "rebuilds": self.rebuilds,
                "build_failed": self.build_failed,
                "bytes_mmapped": self.bytes_mmapped,
                "bytes_written": self.bytes_written,
                "canonicalizer": canon,
            }


counters = _Counters()

from ..obs.registry import registry as _registry  # noqa: E402

_registry.register("ingest_cache", counters.as_dict)


# --- container format -------------------------------------------------------

def _cfg_hash(cfg: dict) -> str:
    """Digest of a prep/parse config dict (sorted-key JSON, sha256)."""
    return hashlib.sha256(
        json.dumps(cfg, sort_keys=True, default=str).encode()).hexdigest()


def file_source_id(path: str, parse_cfg: Optional[dict] = None
                   ) -> Optional[str]:
    """mtime/size identity of a source file — the same staleness contract
    make uses; None when the file cannot be stat'ed. ``parse_cfg`` (the
    reader's own options: feature/label columns, zero_based, ffm, ...)
    folds into the identity, because the same bytes parsed differently
    yield a DIFFERENT dataset — without it the packed cache would serve
    one parse's records for another's key."""
    try:
        st = os.stat(path)
    except OSError:
        return None
    base = os.path.abspath(path)
    if parse_cfg:
        # parse hash rides BEFORE the volatile mtime/size fields so the
        # stable filename key (everything but the last two fields) keeps
        # one cache file per (path, parse config) that a mutation
        # invalidates IN PLACE
        base += f":parse={_cfg_hash(parse_cfg)[:16]}"
    return f"{base}:{st.st_mtime_ns}:{st.st_size}"


def write_cache_file(path: str, header: dict,
                     arrays: Dict[str, np.ndarray]) -> int:
    """Write one cache file atomically: magic | header-len | JSON header |
    raw array payload. The header carries per-array dtype/shape/offset and
    a sha256 over the payload; the write is tmp -> fsync -> ``os.replace``
    (+ best-effort directory fsync), the io/checkpoint.py idiom — a crash
    mid-write can never publish a torn cache. Returns payload bytes."""
    specs = {}
    blobs = []
    off = 0
    digest = hashlib.sha256()
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        specs[name] = {"dtype": a.dtype.str, "shape": list(a.shape),
                      "offset": off}
        off += int(a.nbytes)
        blobs.append(a)
        if a.nbytes:        # memoryview.cast rejects zero-size shapes
            digest.update(memoryview(a).cast("B"))
    header = dict(header, format=_FORMAT, arrays=specs,
                  payload_bytes=off, payload_sha256=digest.hexdigest())
    hb = json.dumps(header, sort_keys=True, default=str).encode()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", len(hb)))
            f.write(hb)
            for a in blobs:
                if a.nbytes:
                    f.write(memoryview(a).cast("B"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    counters.add(rebuilds=1, bytes_written=off)
    return off


def read_cache_file(path: str) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Open + validate one cache file; returns (header, name -> mmap view).

    Validation before any view is handed out: magic, header parse, exact
    file length (quick truncation check), then a streaming sha256 over the
    payload region against the header digest — a bit-flipped or torn cache
    can never silently feed the trainer. Raises :class:`CacheInvalid`."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if f.read(8) != _MAGIC:
            raise CacheInvalid(f"{path}: bad magic")
        raw = f.read(8)
        if len(raw) != 8:
            raise CacheInvalid(f"{path}: truncated header length")
        (hlen,) = struct.unpack("<Q", raw)
        if hlen > (1 << 26):
            raise CacheInvalid(f"{path}: implausible header length {hlen}")
        hb = f.read(hlen)
        if len(hb) != hlen:
            raise CacheInvalid(f"{path}: truncated header")
        try:
            header = json.loads(hb)
        except ValueError as e:
            raise CacheInvalid(f"{path}: header parse failed: {e}") from e
        base = 16 + hlen
        if size != base + int(header.get("payload_bytes", -1)):
            raise CacheInvalid(
                f"{path}: payload truncated ({size} bytes, expected "
                f"{base + int(header.get('payload_bytes', -1))})")
        digest = hashlib.sha256()
        f.seek(base)
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            digest.update(chunk)
        if digest.hexdigest() != header.get("payload_sha256"):
            raise CacheInvalid(f"{path}: payload digest mismatch — file "
                               f"corrupted; falling back to live prep")
        # map from the SAME open file object the digest pass validated —
        # re-opening by name would race a concurrent atomic rewrite
        # (os.replace swaps the inode) and serve unvalidated bytes at this
        # header's stale offsets; the mapping outlives the handle
        views = {}
        for name, s in header["arrays"].items():
            shape = tuple(s["shape"])
            dtype = np.dtype(s["dtype"])
            if int(np.prod(shape)) == 0:    # mmap rejects empty mappings
                views[name] = np.empty(shape, dtype)
            else:
                views[name] = np.memmap(f, mode="r", dtype=dtype,
                                        shape=shape,
                                        offset=base + s["offset"])
    counters.add(bytes_mmapped=int(header["payload_bytes"]))
    return header, views


def read_cache_header(path: str) -> Optional[dict]:
    """Header-only read (no payload digest pass) for cheap METADATA hints
    (e.g. a shard's max row length). Returns None on any failure. Never
    use this to admit payload bytes — that is :func:`read_cache_file`'s
    job."""
    try:
        with open(path, "rb") as f:
            if f.read(8) != _MAGIC:
                return None
            raw = f.read(8)
            if len(raw) != 8:
                return None
            (hlen,) = struct.unpack("<Q", raw)
            if hlen > (1 << 26):
                return None
            hb = f.read(hlen)
            if len(hb) != hlen:
                return None
            return json.loads(hb)
    except (OSError, ValueError):
        return None


# --- the fit()-path packed row-record cache ---------------------------------

def _dataset_source(ds: SparseDataset) -> Tuple[dict, str]:
    """(identity dict for the header, stable key for the filename).

    A file-backed dataset (readers attach ``source_id`` = path:mtime:size)
    keys on the PATH and validates mtime/size from the header, so a
    mutated source invalidates in place and the rewrite replaces the stale
    file; a RAM-only dataset keys on its content sha256 (identity and
    validity coincide)."""
    sid = getattr(ds, "source_id", None)
    if sid:
        return {"source_id": sid}, sid.rsplit(":", 2)[0]
    ck = ds.content_key()
    return {"content_sha256": ck}, ck


def _row_field_mults(ds: SparseDataset, F: int) -> Optional[np.ndarray]:
    """Per-row max same-field multiplicity over LIVE (val != 0) features —
    the m each row needs in the canonical field-major layout. int64 [n];
    None when the dataset has no field ids."""
    if ds.fields is None:
        return None
    n = len(ds)
    m_row = np.zeros(n, np.int64)
    live = ds.values != 0
    if live.any():
        rows = np.repeat(np.arange(n, dtype=np.int64),
                         np.diff(ds.indptr).astype(np.int64))
        keys = rows[live] * F + (ds.fields[live].astype(np.int64) % F)
        uniq, cnt = np.unique(keys, return_counts=True)
        np.maximum.at(m_row, uniq // F, cnt)
    return m_row


class CachedPackedShard:
    """One validated, mmap-opened packed shard: the record matrix
    [n, m_cap*F*3 + 4] (3-byte idx lanes + f32 label bytes per row) plus
    the per-row multiplicity vector. :meth:`batches` re-slices any row
    permutation into the exact ``PackedBatch`` buffers the streamed path
    would have packed."""

    def __init__(self, header: dict, records: np.ndarray,
                 m_row: np.ndarray):
        self.header = header
        self.records = records
        self.m_row = np.asarray(m_row)        # small; pull off the mmap
        self.F = int(header["F"])
        self.m_cap = int(header["m_cap"])
        self.n_rows = int(header["n_rows"])

    def batches(self, batch_size: int, order: np.ndarray, *, stats=None,
                pad_rows=None) -> Iterator[PackedBatch]:
        """Yield the epoch's PackedBatches for ``order`` (a permutation or
        arange over the dataset rows). Per batch: gather the records, pick
        the batch's canonical width from the rows' multiplicities (exactly
        how ``canonicalize_fieldmajor`` sizes the streamed batch), and lay
        the lanes/labels out as ``pack_unit_fieldmajor`` does. ``pad_rows``
        maps the logical batch size to the allocated row count (the parts
        layout's kernel-grid row padding); identity otherwise."""
        F, Lcap3 = self.F, self.m_cap * self.F * 3
        bs = int(batch_size)
        B = int(pad_rows(bs)) if pad_rows is not None else bs
        tracer = get_tracer()
        for s in range(0, len(order), bs):
            t0 = time.perf_counter()
            with tracer.span("ingest.cache"):
                take = order[s:s + bs]
                nv = len(take)
                m_b = pow2_len(max(1, int(self.m_row[take].max(initial=0))))
                Lb = m_b * F
                recs = self.records[take]             # mmap gather -> RAM
                idxp = np.zeros((B, Lb * 3), np.uint8)
                idxp[:nv] = recs[:, :Lb * 3]
                labp = np.zeros((B, 4), np.uint8)
                labp[:nv] = recs[:, Lcap3:]
                buf = np.concatenate([idxp.reshape(-1), labp.reshape(-1)])
            if stats is not None:
                stats.add(cache_assemble_seconds=time.perf_counter() - t0,
                          cache_batches=1)
            yield PackedBatch(buf, B, Lb,
                              n_valid=nv if nv < B else None)


class PackedShardWriter:
    """Collects one cold epoch's prepared PackedBatches into the row-record
    matrix (scattered to DATASET row positions via each batch's ``take``
    indices, so the build epoch may be shuffled) and publishes atomically
    on :meth:`commit`. Any batch that is not a PackedBatch, or whose
    canonical width disagrees with the per-row multiplicities, aborts the
    build — the cache only ever admits streams it can replay bit-exactly
    (fail-open: the caller just keeps streaming live)."""

    def __init__(self, cache: "PackedShardCache", ds: SparseDataset,
                 m_row: np.ndarray):
        self._cache = cache
        self._source, self._key = _dataset_source(ds)
        self.F = cache.F
        self.m_row = m_row
        self.m_cap = pow2_len(max(1, int(m_row.max(initial=0))))
        self.n = len(ds)
        self._rec = np.zeros((self.n, self.m_cap * self.F * 3 + 4), np.uint8)
        self._filled = 0
        self.ok = True

    def add(self, batch, take: np.ndarray) -> None:
        if not self.ok:
            return
        if not isinstance(batch, PackedBatch) \
                or not isinstance(batch.buf, np.ndarray):
            self.ok = False
            return
        nv = len(take)
        expect_L = pow2_len(max(1, int(self.m_row[take].max(initial=0)))) \
            * self.F
        if batch.L != expect_L or batch.L * 3 > self._rec.shape[1] - 4 \
                or (batch.n_valid or batch.B) < nv:
            self.ok = False               # prep drifted from the row model
            return
        lanes = batch.buf[:batch.B * batch.L * 3].reshape(batch.B,
                                                          batch.L * 3)
        labs = batch.buf[batch.B * batch.L * 3:].reshape(batch.B, 4)
        self._rec[take, :batch.L * 3] = lanes[:nv]
        self._rec[take, self.m_cap * self.F * 3:] = labs[:nv]
        self._filled += nv

    def commit(self) -> Optional[CachedPackedShard]:
        """Publish the cache file (tmp -> fsync -> replace) and reopen it
        mmap'd; None when the build aborted or did not cover every row."""
        if not self.ok or self._filled != self.n:
            counters.add(build_failed=1)
            return None
        path = self._cache._path_for(self._key)
        header = {"kind": "packed_rows", "prep_hash": self._cache.prep_hash,
                  "prep_config": self._cache.prep_cfg,
                  "source": self._source, "n_rows": self.n, "F": self.F,
                  "m_cap": self.m_cap}
        write_cache_file(path, header,
                         {"records": self._rec,
                          "m_row": np.minimum(self.m_row, 255)
                          .astype(np.uint8)})
        self._rec = None                  # free the RAM copy; serve mmap'd
        try:
            hdr, views = read_cache_file(path)
        except (CacheInvalid, OSError):
            return None
        return CachedPackedShard(hdr, views["records"], views["m_row"])


class PackedShardCache:
    """The fit()-path cache front end for one (cache dir, prep config)."""

    MAX_M = 4      # canonicalize_fieldmajor's max_m — rows past it never pack

    def __init__(self, cache_dir: str, prep_cfg: dict, *, F: int,
                 name: str = "shard"):
        self.dir = cache_dir
        self.prep_cfg = dict(prep_cfg)
        self.prep_hash = _cfg_hash(self.prep_cfg)
        self.F = int(F)
        self.name = name

    def _path_for(self, source_key: str) -> str:
        key = hashlib.sha256(
            (self.prep_hash + "\0" + source_key).encode()).hexdigest()
        return os.path.join(self.dir, f"{self.name}-{key[:20]}.pack")

    def load(self, ds: SparseDataset) -> Optional[CachedPackedShard]:
        """Open the cached shard for ``ds``, or None (miss). Stale identity
        (source mutated), prep-config drift, wrong row count, and corrupt
        files all miss; corrupt additionally counts ``invalid``."""
        source, key = _dataset_source(ds)
        path = self._path_for(key)
        if not os.path.exists(path):
            counters.add(misses=1)
            return None
        try:
            header, views = read_cache_file(path)
        except (CacheInvalid, OSError):
            counters.add(invalid=1, misses=1)
            return None
        if (header.get("kind") != "packed_rows"
                or header.get("prep_hash") != self.prep_hash
                or header.get("source") != source
                or int(header.get("n_rows", -1)) != len(ds)
                or int(header.get("F", -1)) != self.F):
            counters.add(misses=1)
            return None
        counters.add(hits=1)
        return CachedPackedShard(header, views["records"], views["m_row"])

    def writer(self, ds: SparseDataset) -> Optional[PackedShardWriter]:
        """A build-epoch writer, or None when the dataset can never cache
        (no field ids, or a row's same-field multiplicity exceeds the
        canonicalizer's max_m — such rows fall back to the pairs path)."""
        m_row = _row_field_mults(ds, self.F)
        if m_row is None or (len(m_row)
                             and int(m_row.max(initial=0)) > self.MAX_M):
            return None
        return PackedShardWriter(self, ds, m_row)


# --- the ParquetStream decoded-shard cache ----------------------------------

class ShardDecodeCache:
    """Per-shard decoded CSR cache for the out-of-core Parquet path.

    Keyed by (shard file path, parse config digest) and validated against
    the shard's mtime_ns/size + the payload sha256: epoch >= 2 and
    restarts skip the Parquet read + string parse + murmur hashing and
    mmap the columns instead (``SparseDataset`` over memmap views — the
    downstream pad/canonicalize/pack consumers are unchanged)."""

    def __init__(self, cache_dir: str, parse_cfg: dict):
        self.dir = cache_dir
        self.parse_cfg = dict(parse_cfg)
        self.hash = _cfg_hash({"kind": "csr_shard", **self.parse_cfg})
        # validated shards memoized per (path -> (source_id, dataset)):
        # the digest pass streams the whole payload, so re-validating
        # every epoch would re-read all cached bytes — exactly the I/O
        # warm epochs exist to skip. A source mutation changes the
        # source_id and drops the memo entry.
        self._memo: Dict[str, Tuple[str, SparseDataset]] = {}

    def _path_for(self, shard_path: str) -> str:
        key = hashlib.sha256(
            (self.hash + "\0" + os.path.abspath(shard_path)).encode()
        ).hexdigest()
        return os.path.join(self.dir, f"pq-{key[:20]}.csr")

    def load(self, shard_path: str) -> Optional[SparseDataset]:
        sid = file_source_id(shard_path)
        memo = self._memo.get(shard_path)
        if memo is not None and sid is not None and memo[0] == sid:
            counters.add(hits=1)
            return memo[1]
        path = self._path_for(shard_path)
        if sid is None or not os.path.exists(path):
            counters.add(misses=1)
            return None
        try:
            header, views = read_cache_file(path)
        except (CacheInvalid, OSError):
            counters.add(invalid=1, misses=1)
            return None
        if header.get("kind") != "csr_shard" \
                or header.get("source", {}).get("source_id") != sid:
            counters.add(misses=1)
            return None
        counters.add(hits=1)
        ds = SparseDataset(views["indices"], views["indptr"],
                           views["values"], views["labels"],
                           views.get("fields"))
        ds.source_id = sid
        self._memo[shard_path] = (sid, ds)
        return ds

    def max_row_len_hint(self, shard_path: str) -> Optional[int]:
        """Cached shard's max row length from a header-only read, or None.
        Lets ParquetStream size its padded batches without touching the
        source Parquet bytes on warm traversals; validated against the
        shard's current mtime/size (the metadata is right whenever the
        source is unchanged, independent of payload health)."""
        sid = file_source_id(shard_path)
        header = read_cache_header(self._path_for(shard_path))
        if (sid is None or header is None
                or header.get("kind") != "csr_shard"
                or header.get("source", {}).get("source_id") != sid):
            return None
        mrl = header.get("max_row_len")
        return int(mrl) if mrl is not None else None

    def store(self, shard_path: str, ds: SparseDataset) -> None:
        sid = file_source_id(shard_path)
        if sid is None:
            return
        arrays = {"indices": ds.indices, "indptr": ds.indptr,
                  "values": ds.values, "labels": ds.labels}
        if ds.fields is not None:
            arrays["fields"] = ds.fields
        write_cache_file(self._path_for(shard_path),
                         {"kind": "csr_shard", "parse_config": self.parse_cfg,
                          "source": {"source_id": sid},
                          "max_row_len": ds.max_row_len}, arrays)


# --- run_tests.sh smoke -----------------------------------------------------

def _smoke() -> int:                      # pragma: no cover - exercised by sh
    """Seconds-scale end-to-end check (run_tests.sh): build the packed
    cache cold, bit-match a warm restart's loss trajectory, prove the warm
    epoch never re-reads the source (serve after source-content mutation
    with preserved mtime/size), and exercise the Parquet decode cache."""
    import shutil
    import sys
    import tempfile

    from ..models.fm import FFMTrainer

    tmp = tempfile.mkdtemp(prefix="hmt_shard_cache_smoke_")
    failures = 0

    def check(name, cond):
        nonlocal failures
        print(f"shard-cache smoke {name}: {'OK' if cond else 'FAILED'}",
              file=sys.stderr)
        if not cond:
            failures += 1

    try:
        rng = np.random.default_rng(5)
        n, L, F, dims = 1024, 8, 8, 1 << 12
        idx = rng.integers(1, dims, (n, L)).astype(np.int32)
        fld = np.tile(np.arange(L, dtype=np.int32) % F, (n, 1))
        lab = (rng.integers(0, 2, n) * 2 - 1).astype(np.float32)
        ds = SparseDataset(idx.ravel(),
                           np.arange(0, n * L + 1, L, dtype=np.int64),
                           np.ones(n * L, np.float32), lab, fld.ravel())
        cfg = (f"-dims {dims} -factors 2 -fields {F} -mini_batch 128 "
               f"-classification -pack_input on "
               f"-shard_cache_dir {tmp}/cache")
        cold = FFMTrainer(cfg)
        cold._trace_losses = []
        cold.fit(ds, epochs=2, shuffle=True)
        packs = [f for f in os.listdir(f"{tmp}/cache")
                 if f.endswith(".pack")]
        check("cold build wrote a cache file", len(packs) == 1)
        warm = FFMTrainer(cfg)
        warm._trace_losses = []
        warm.fit(ds, epochs=2, shuffle=True)
        check("warm restart bit-matches cold trajectory",
              np.array_equal(np.asarray(cold._trace_losses),
                             np.asarray(warm._trace_losses)))
        check("warm epochs ran zero live prep",
              warm.pipeline_stats.batches_prepared == 0
              and warm.pipeline_stats.cache_batches > 0)
        snap = counters.as_dict()
        check("obs counters populated",
              snap["hits"] >= 1 and snap["rebuilds"] >= 1
              and snap["bytes_mmapped"] > 0)

        # Parquet decode cache: build, then corrupt the SOURCE content
        # while preserving mtime/size — a warm traversal must still serve
        # the original bytes (proof the mmap'd cache, not the source, is
        # what epoch >= 2 reads).
        try:
            import pyarrow  # noqa: F401
        except ImportError:
            print("shard-cache smoke: pyarrow absent, decode-cache leg "
                  "skipped", file=sys.stderr)
            return failures
        from .arrow import ParquetStream, write_parquet_shards
        pq_dir = f"{tmp}/pq"
        write_parquet_shards(ds, pq_dir, rows_per_shard=256)
        stream = ParquetStream(pq_dir, cache_dir=f"{tmp}/cache")
        ref = [b.idx.copy() for b in stream.batches(128, shuffle=False)]
        shard0 = sorted(os.path.join(pq_dir, f) for f in os.listdir(pq_dir)
                        if f.endswith(".parquet"))[0]
        st = os.stat(shard0)
        with open(shard0, "r+b") as f:      # same size, same mtime after
            f.seek(0)
            f.write(b"\0" * 64)
        os.utime(shard0, ns=(st.st_atime_ns, st.st_mtime_ns))
        warm_b = [b.idx.copy() for b in
                  ParquetStream(pq_dir, cache_dir=f"{tmp}/cache")
                  .batches(128, shuffle=False)]
        check("decode cache serves without re-reading the source",
              len(ref) == len(warm_b)
              and all(np.array_equal(a, b) for a, b in zip(ref, warm_b)))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return failures


if __name__ == "__main__":                # pragma: no cover
    # run the CANONICAL module's smoke, not __main__'s copy: `python -m`
    # executes this file as __main__, but the trainers it drives import
    # hivemall_tpu.io.shard_cache — two module instances would split the
    # counters and the smoke would assert against the empty half
    import sys

    from hivemall_tpu.io.shard_cache import _smoke as _canonical_smoke
    sys.exit(_canonical_smoke())
