"""Checkpoint bundles: params + optimizer state + counters, resumable.

Reference (SURVEY.md §6 "Checkpoint / resume"): in Hivemall every model IS a
durable table, and warm start is `-loadmodel` over an exported model file —
but optimizer state (AdaGrad accumulators etc.) is lost across restarts and
mid-epoch resume does not exist. The rebuild keeps the model-table path
(LearnerBase.save_model / -loadmodel) for weight-only warm starts and adds
what the reference lacks: a full bundle of every device array a trainer
needs to continue exactly where it stopped — weights, optimizer slots,
covariance tables, the global step (which drives EtaEstimator schedules),
example counts, stream position, and the hashed-id→name map.

Format: one .npz — flattened pytree leaves (bf16 stored as f32, original
dtype restored from the live trainer's reference tree on load) plus a json
metadata record carrying a manifest: format version + a sha256 digest over
the leaf tree, validated with a clear error on load so a truncated or
bit-flipped bundle can never silently resume. Writes are crash-atomic:
tmp file → fsync → ``os.replace`` — a crash mid-save leaves the previous
bundle intact, never a half-written one (docs/RELIABILITY.md).

:class:`CheckpointManager` adds autosave cadence + last-k retention for
the ``-checkpoint_dir`` / ``-checkpoint_every`` trainer options.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..obs.trace import get_tracer

__all__ = ["save_bundle", "load_bundle", "CheckpointManager", "list_bundles",
           "bundle_step", "newest_bundle", "verify_bundle"]

_FORMAT = 2          # 2 adds the digest manifest + stream position
_STEP_RE = re.compile(r"-step(\d+)\.npz$")


def _leaf_digest(arrays: List[np.ndarray]) -> str:
    """sha256 over the leaf tree — dtype/shape/bytes of every stored leaf,
    in order. Computed over the arrays as WRITTEN (post bf16→f32 cast) so
    load-side recomputation sees identical bytes."""
    h = hashlib.sha256()
    for i, a in enumerate(arrays):
        h.update(f"{i}:{a.dtype.str}:{a.shape}".encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def save_bundle(trainer, path: str) -> None:
    """Write the trainer's full resumable state to ``path`` (.npz),
    atomically: tmp → fsync → os.replace. A crash at any point leaves
    either the old bundle or the new one, never a torn file.

    Works for any trainer exposing `_checkpoint_arrays`/`_restore_arrays`;
    the LearnerBase counters (_examples, _loss_sum, _names) are optional so
    non-LearnerBase trainers (e.g. MF) bundle too. Traced as a
    ``checkpoint.save`` span — autosave stalls show up in the obs rollup
    next to the stages they steal wall time from."""
    with get_tracer().span("checkpoint.save"):
        _save_bundle(trainer, path)


def _save_bundle(trainer, path: str) -> None:
    if hasattr(trainer, "_fold_loss"):
        trainer._fold_loss()
    leaves, treedef = jax.tree_util.tree_flatten(trainer._checkpoint_arrays())
    arrays = {}
    stored: List[np.ndarray] = []
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf)
        if a.dtype.name == "bfloat16":      # npz can't take ml_dtypes leaves
            a = a.astype(np.float32)
        arrays[f"leaf_{i}"] = a
        stored.append(a)
    meta: Dict[str, Any] = {
        "format": _FORMAT,
        "trainer": trainer.NAME,
        "n_leaves": len(leaves),
        "digest": _leaf_digest(stored),
        "t": getattr(trainer, "_t", 0),
        "examples": getattr(trainer, "_examples", 0),
        "loss_sum": getattr(trainer, "_loss_sum", 0.0),
        "stream_pos": int(getattr(trainer, "_stream_pos", 0)),
        "names": {str(k): v for k, v in getattr(trainer, "_names",
                                                {}).items()},
        "scalars": (trainer._checkpoint_scalars()
                    if hasattr(trainer, "_checkpoint_scalars") else {}),
    }
    rng = getattr(trainer, "_rng", None)
    if rng is not None and hasattr(rng, "bit_generator"):
        meta["rng_state"] = rng.bit_generator.state   # np Generator state
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=np.array(json.dumps(meta)), **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):             # failed mid-write: no litter
            try:
                os.remove(tmp)
            except OSError:
                pass
    # fsync the directory so the rename itself is durable (best-effort:
    # not every filesystem supports opening a directory)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _read_validated(z, path: str, name: Optional[str]):
    """The shared manifest validation (format version, trainer name,
    sha256 leaf digest) for a loaded npz — ONE implementation, called by
    both ``load_bundle`` and ``verify_bundle`` so the fleet manager's
    pre-roll verification can never drift from what replicas actually
    enforce at load. Returns ``(meta, raw_leaf_arrays)``."""
    meta = json.loads(str(z["__meta__"]))
    if meta.get("format") not in (1, _FORMAT):
        raise ValueError(
            f"bundle format {meta.get('format')!r} != supported "
            f"{_FORMAT} — bundle written by an incompatible version")
    if name is not None and meta.get("trainer") != name:
        raise ValueError(
            f"bundle was written by {meta.get('trainer')!r}, "
            f"cannot resume {name!r}")
    raw = [z[f"leaf_{i}"] for i in range(int(meta["n_leaves"]))]
    if "digest" in meta and _leaf_digest(raw) != meta["digest"]:
        raise ValueError(
            f"bundle digest mismatch for {path!r} — file corrupted "
            f"or truncated (copied mid-write?); refusing to resume")
    return meta, raw


def load_bundle(trainer, path: str) -> None:
    """Restore a bundle into a freshly constructed trainer (same options).

    Validates the manifest before touching trainer state: format version,
    trainer name, leaf count/shapes, and (format >= 2) the sha256 leaf
    digest — a corrupted or truncated bundle raises ValueError with the
    cause rather than resuming garbage."""
    with np.load(path, allow_pickle=False) as z:
        meta, raw = _read_validated(z, path, trainer.NAME)
        ref_leaves, treedef = jax.tree_util.tree_flatten(
            trainer._checkpoint_arrays())
        if meta["n_leaves"] != len(ref_leaves):
            raise ValueError(
                f"bundle has {meta['n_leaves']} state arrays, trainer "
                f"expects {len(ref_leaves)} — options mismatch?")
        leaves = []
        for i, (a, ref) in enumerate(zip(raw, ref_leaves)):
            if tuple(a.shape) != tuple(ref.shape):
                raise ValueError(
                    f"state array {i}: bundle shape {a.shape} != "
                    f"trainer shape {tuple(ref.shape)} — options mismatch?")
            leaves.append(jax.numpy.asarray(a, dtype=ref.dtype))
    trainer._restore_arrays(jax.tree_util.tree_unflatten(treedef, leaves))
    trainer._t = int(meta["t"])
    for attr, val in (("_examples", int(meta["examples"])),
                      ("_loss_sum", float(meta["loss_sum"])),
                      ("_loss_pending", 0.0),
                      ("_stream_pos", int(meta.get("stream_pos", 0)))):
        if hasattr(trainer, attr):
            setattr(trainer, attr, val)
    if hasattr(trainer, "_names"):
        trainer._names.update({int(k): v for k, v in meta["names"].items()})
    if meta.get("scalars") and hasattr(trainer, "_restore_scalars"):
        trainer._restore_scalars(meta["scalars"])
    rng = getattr(trainer, "_rng", None)
    if meta.get("rng_state") and rng is not None \
            and hasattr(rng, "bit_generator"):
        rng.bit_generator.state = meta["rng_state"]
    if getattr(trainer, "mesh", None) is not None:
        trainer._reshard_state()      # bundles load replicated; re-shard


def verify_bundle(path: str, name: Optional[str] = None) -> dict:
    """Validate a bundle WITHOUT constructing a trainer: format version,
    trainer name (when ``name`` is given), and the sha256 leaf digest.
    Returns the bundle's meta dict on success; raises ValueError on any
    mismatch.

    The fleet replica manager runs this ONCE per newer bundle before
    rolling it across replicas — a corrupt autosave is rejected at the
    manager, not N times by N replicas mid-roll. Cheaper than a trainer
    load: no table allocation, no device transfer, no resharding. Runs
    the SAME validation block replicas run at load (_read_validated)."""
    with np.load(path, allow_pickle=False) as z:
        meta, _ = _read_validated(z, path, name)
    return meta


def list_bundles(checkpoint_dir: str, name: str) -> List[str]:
    """Autosaved step bundles for ``name`` under ``checkpoint_dir``,
    newest (highest step) first. Non-step .npz files are ignored."""
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return []
    found = []
    for fn in entries:
        if not fn.startswith(f"{name}-step"):
            continue
        m = _STEP_RE.search(fn)
        if m:
            found.append((int(m.group(1)), os.path.join(checkpoint_dir, fn)))
    return [p for _, p in sorted(found, reverse=True)]


def bundle_step(path: str) -> Optional[int]:
    """Optimizer step encoded in an autosaved bundle's filename, or None
    for non-step bundles (epoch bundles, explicit --save-bundle paths)."""
    m = _STEP_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def newest_bundle(checkpoint_dir: str, name: str
                  ) -> Optional[Tuple[int, str]]:
    """Newest autosaved step bundle for ``name`` as ``(step, path)``, or
    None when the directory holds none. The serve engine's hot-reload
    watch polls this: atomic ``os.replace`` writes mean a listed bundle is
    always complete (never a torn file), and the in-progress ``.tmp.npz``
    files a live trainer writes into a SHARED directory never match the
    step pattern, so trainer and server can safely share
    ``-checkpoint_dir``."""
    paths = list_bundles(checkpoint_dir, name)
    if not paths:
        return None
    step = bundle_step(paths[0])
    return None if step is None else (step, paths[0])


class CheckpointManager:
    """Autosave cadence + last-k retention over atomic ``save_bundle``.

    Drives the ``-checkpoint_dir`` / ``-checkpoint_every`` /
    ``-checkpoint_keep`` trainer options inside ``fit_stream``: a bundle
    lands every ``every`` optimizer steps (windows that cross several
    boundaries — fused K-step dispatch — save once), plus a final bundle at
    stream end; only the ``keep`` newest step bundles are retained."""

    def __init__(self, checkpoint_dir: str, name: str, *, keep: int = 3,
                 every: int = 0, start_step: int = 0):
        self.dir = checkpoint_dir
        self.name = name
        self.keep = max(1, int(keep))
        self.every = int(every)
        os.makedirs(checkpoint_dir, exist_ok=True)
        self._next = start_step + self.every if self.every else None
        self._last_saved_step: Optional[int] = None
        self._last_saved_ts: Optional[float] = None
        # bundle count is CACHED (updated at save/prune time, one scan
        # here at construction): the registry provider below runs inline
        # in the fit loop (-telemetry_every) and on scrape threads, and
        # the provider contract is cheap/non-blocking — a per-snapshot
        # listdir on a networked checkpoint FS would stall training
        self._bundles = len(list_bundles(checkpoint_dir, name))
        # obs registry section (weakly held — the registry is process-wide
        # and must not pin a dead manager or its trainer). A trainer-owned
        # manager is ALSO reachable through the trainer's own `checkpoint`
        # provider (LearnerBase._register_obs delegates to obs_section),
        # which re-registers on every trainer construction so a new
        # trainer can never inherit a previous trainer's section.
        from ..obs.registry import CHECKPOINT_STUB, registry
        ref = weakref.ref(self)

        def _obs() -> dict:
            m = ref()
            return m.obs_section() if m is not None \
                else dict(CHECKPOINT_STUB)

        registry.register("checkpoint", _obs)

    def obs_section(self) -> dict:
        """This manager's `checkpoint` registry section (cheap: every
        field is a cached attribute — no filesystem access)."""
        return {
            "configured": True,
            "dir": self.dir,
            "every": self.every,
            "keep": self.keep,
            "last_saved_step": self._last_saved_step,
            "age_seconds": (round(time.time() - self._last_saved_ts, 3)
                            if self._last_saved_ts else None),
            "bundles": self._bundles,
        }

    def maybe_save(self, trainer) -> Optional[str]:
        if self._next is None or trainer._t < self._next:
            return None
        path = self.save(trainer)
        while self._next <= trainer._t:
            self._next += self.every
        return path

    def save(self, trainer) -> str:
        path = os.path.join(self.dir,
                            f"{self.name}-step{trainer._t:010d}.npz")
        save_bundle(trainer, path)
        self._last_saved_step = int(trainer._t)
        self._last_saved_ts = time.time()
        self._prune()
        emit = getattr(trainer, "_emit_checkpoint_event", None)
        if emit is not None:            # one emitter for every save site
            emit(path, step=int(trainer._t))
        else:                           # non-LearnerBase trainers (MF, ...)
            from ..utils.metrics import get_stream
            stream = get_stream()
            if stream.enabled:
                stream.emit("checkpoint", trainer=self.name,
                            step=int(trainer._t), path=path)
        return path

    def save_final(self, trainer) -> Optional[str]:
        """End-of-stream bundle, skipped when the cadence already saved
        this exact step."""
        if self._last_saved_step == int(trainer._t):
            return None
        return self.save(trainer)

    def _prune(self) -> None:
        paths = list_bundles(self.dir, self.name)
        kept = len(paths)
        for path in paths[self.keep:]:
            try:
                os.remove(path)
                kept -= 1
            except OSError:
                pass
        self._bundles = kept
