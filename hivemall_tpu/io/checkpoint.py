"""Checkpoint bundles: params + optimizer state + counters, resumable.

Reference (SURVEY.md §6 "Checkpoint / resume"): in Hivemall every model IS a
durable table, and warm start is `-loadmodel` over an exported model file —
but optimizer state (AdaGrad accumulators etc.) is lost across restarts and
mid-epoch resume does not exist. The rebuild keeps the model-table path
(LearnerBase.save_model / -loadmodel) for weight-only warm starts and adds
what the reference lacks: a full bundle of every device array a trainer
needs to continue exactly where it stopped — weights, optimizer slots,
covariance tables, the global step (which drives EtaEstimator schedules),
example counts, stream position, and the hashed-id→name map.

Format: one .npz — flattened pytree leaves (bf16 stored as f32, original
dtype restored from the live trainer's reference tree on load) plus a json
metadata record carrying a manifest: format version + a sha256 digest over
the leaf tree, validated with a clear error on load so a truncated or
bit-flipped bundle can never silently resume. Writes are crash-atomic:
tmp file → fsync → ``os.replace`` — a crash mid-save leaves the previous
bundle intact, never a half-written one (docs/RELIABILITY.md).

:class:`CheckpointManager` adds autosave cadence + last-k retention for
the ``-checkpoint_dir`` / ``-checkpoint_every`` trainer options.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import re
import threading
import time
import weakref
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from ..obs.trace import get_tracer

__all__ = ["save_bundle", "load_bundle", "CheckpointManager", "list_bundles",
           "bundle_step", "newest_bundle", "verify_bundle", "bundle_meta",
           "read_promoted", "promoted_bundle", "promote_bundle",
           "finalize_promotion", "rollback_promoted", "reject_bundle",
           "is_rejected", "rejected_reason", "pinned_bundles",
           "pin_bundle", "unpin_bundle", "hold_bundle", "in_use_bundles"]

_FORMAT = 2          # 2 adds the digest manifest + stream position
_STEP_RE = re.compile(r"-step(\d+)\.npz$")

#: the promotion pointer file inside a checkpoint dir (docs/RELIABILITY.md
#: "Promotion and rollback"): serving follows THIS, not the newest step
_POINTER = "PROMOTED"
_POINTER_FORMAT = 1
#: quarantine marker suffix: `<bundle>.rejected` (JSON reason) — a bundle
#: that failed the promotion gate or was rolled back; watchers never retry
_REJECTED = ".rejected"


def _leaf_digest(arrays: List[np.ndarray]) -> str:
    """sha256 over the leaf tree — dtype/shape/bytes of every stored leaf,
    in order. Computed over the arrays as WRITTEN (post bf16→f32 cast) so
    load-side recomputation sees identical bytes."""
    h = hashlib.sha256()
    for i, a in enumerate(arrays):
        h.update(f"{i}:{a.dtype.str}:{a.shape}".encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def save_bundle(trainer, path: str) -> None:
    """Write the trainer's full resumable state to ``path`` (.npz),
    atomically: tmp → fsync → os.replace. A crash at any point leaves
    either the old bundle or the new one, never a torn file.

    Works for any trainer exposing `_checkpoint_arrays`/`_restore_arrays`;
    the LearnerBase counters (_examples, _loss_sum, _names) are optional so
    non-LearnerBase trainers (e.g. MF) bundle too. Traced as a
    ``checkpoint.save`` span — autosave stalls show up in the obs rollup
    next to the stages they steal wall time from."""
    with get_tracer().span("checkpoint.save"):
        _save_bundle(trainer, path)


def _save_bundle(trainer, path: str) -> None:
    if hasattr(trainer, "_fold_loss"):
        trainer._fold_loss()
    leaves, treedef = jax.tree_util.tree_flatten(trainer._checkpoint_arrays())
    arrays = {}
    stored: List[np.ndarray] = []
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf)
        if a.dtype.name == "bfloat16":      # npz can't take ml_dtypes leaves
            a = a.astype(np.float32)
        arrays[f"leaf_{i}"] = a
        stored.append(a)
    meta: Dict[str, Any] = {
        "format": _FORMAT,
        "trainer": trainer.NAME,
        "n_leaves": len(leaves),
        "digest": _leaf_digest(stored),
        "t": getattr(trainer, "_t", 0),
        "examples": getattr(trainer, "_examples", 0),
        "loss_sum": getattr(trainer, "_loss_sum", 0.0),
        "stream_pos": int(getattr(trainer, "_stream_pos", 0)),
        "names": {str(k): v for k, v in getattr(trainer, "_names",
                                                {}).items()},
        "scalars": (trainer._checkpoint_scalars()
                    if hasattr(trainer, "_checkpoint_scalars") else {}),
    }
    rng = getattr(trainer, "_rng", None)
    if rng is not None and hasattr(rng, "bit_generator"):
        meta["rng_state"] = rng.bit_generator.state   # np Generator state
    tmp = f"{path}.{os.getpid()}.tmp.npz"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=np.array(json.dumps(meta)), **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):             # failed mid-write: no litter
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(path)


def _fsync_dir(path: str) -> None:
    """fsync the containing directory so a rename into it is durable
    (best-effort: not every filesystem supports opening a directory)."""
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _atomic_write_json(path: str, obj: dict) -> None:
    """Crash-atomic small-file write (the bundle idiom: tmp → fsync →
    ``os.replace`` → dir fsync) — a reader always sees either the old
    record or the new one, never a torn file."""
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(path)


def _read_validated(z, path: str, name: Optional[str]):
    """The shared manifest validation (format version, trainer name,
    sha256 leaf digest) for a loaded npz — ONE implementation, called by
    both ``load_bundle`` and ``verify_bundle`` so the fleet manager's
    pre-roll verification can never drift from what replicas actually
    enforce at load. Returns ``(meta, raw_leaf_arrays)``."""
    meta = json.loads(str(z["__meta__"]))
    if meta.get("format") not in (1, _FORMAT):
        raise ValueError(
            f"bundle format {meta.get('format')!r} != supported "
            f"{_FORMAT} — bundle written by an incompatible version")
    if name is not None and meta.get("trainer") != name:
        raise ValueError(
            f"bundle was written by {meta.get('trainer')!r}, "
            f"cannot resume {name!r}")
    raw = [z[f"leaf_{i}"] for i in range(int(meta["n_leaves"]))]
    if "digest" in meta and _leaf_digest(raw) != meta["digest"]:
        raise ValueError(
            f"bundle digest mismatch for {path!r} — file corrupted "
            f"or truncated (copied mid-write?); refusing to resume")
    return meta, raw


def load_bundle(trainer, path: str) -> None:
    """Restore a bundle into a freshly constructed trainer (same options).

    Validates the manifest before touching trainer state: format version,
    trainer name, leaf count/shapes, and (format >= 2) the sha256 leaf
    digest — a corrupted or truncated bundle raises ValueError with the
    cause rather than resuming garbage."""
    with np.load(path, allow_pickle=False) as z:
        meta, raw = _read_validated(z, path, trainer.NAME)
        ref_leaves, treedef = jax.tree_util.tree_flatten(
            trainer._checkpoint_arrays())
        if meta["n_leaves"] != len(ref_leaves):
            raise ValueError(
                f"bundle has {meta['n_leaves']} state arrays, trainer "
                f"expects {len(ref_leaves)} — options mismatch?")
        leaves = []
        for i, (a, ref) in enumerate(zip(raw, ref_leaves)):
            if tuple(a.shape) != tuple(ref.shape):
                raise ValueError(
                    f"state array {i}: bundle shape {a.shape} != "
                    f"trainer shape {tuple(ref.shape)} — options mismatch?")
            leaves.append(jax.numpy.asarray(a, dtype=ref.dtype))
    trainer._restore_arrays(jax.tree_util.tree_unflatten(treedef, leaves))
    trainer._t = int(meta["t"])
    for attr, val in (("_examples", int(meta["examples"])),
                      ("_loss_sum", float(meta["loss_sum"])),
                      ("_loss_pending", 0.0),
                      ("_stream_pos", int(meta.get("stream_pos", 0)))):
        if hasattr(trainer, attr):
            setattr(trainer, attr, val)
    if hasattr(trainer, "_names"):
        trainer._names.update({int(k): v for k, v in meta["names"].items()})
    if meta.get("scalars") and hasattr(trainer, "_restore_scalars"):
        trainer._restore_scalars(meta["scalars"])
    rng = getattr(trainer, "_rng", None)
    if meta.get("rng_state") and rng is not None \
            and hasattr(rng, "bit_generator"):
        rng.bit_generator.state = meta["rng_state"]
    if getattr(trainer, "mesh", None) is not None:
        trainer._reshard_state()      # bundles load replicated; re-shard


def verify_bundle(path: str, name: Optional[str] = None) -> dict:
    """Validate a bundle WITHOUT constructing a trainer: format version,
    trainer name (when ``name`` is given), and the sha256 leaf digest.
    Returns the bundle's meta dict on success; raises ValueError on any
    mismatch.

    The fleet replica manager runs this ONCE per newer bundle before
    rolling it across replicas — a corrupt autosave is rejected at the
    manager, not N times by N replicas mid-roll. Cheaper than a trainer
    load: no table allocation, no device transfer, no resharding. Runs
    the SAME validation block replicas run at load (_read_validated)."""
    with np.load(path, allow_pickle=False) as z:
        meta, _ = _read_validated(z, path, name)
    return meta


def list_bundles(checkpoint_dir: str, name: str) -> List[str]:
    """Autosaved step bundles for ``name`` under ``checkpoint_dir``,
    newest (highest step) first. Non-step .npz files are ignored."""
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return []
    found = []
    for fn in entries:
        if not fn.startswith(f"{name}-step"):
            continue
        m = _STEP_RE.search(fn)
        if m:
            found.append((int(m.group(1)), os.path.join(checkpoint_dir, fn)))
    return [p for _, p in sorted(found, reverse=True)]


def bundle_step(path: str) -> Optional[int]:
    """Optimizer step encoded in an autosaved bundle's filename, or None
    for non-step bundles (epoch bundles, explicit --save-bundle paths)."""
    m = _STEP_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def newest_bundle(checkpoint_dir: str, name: str
                  ) -> Optional[Tuple[int, str]]:
    """Newest autosaved step bundle for ``name`` as ``(step, path)``, or
    None when the directory holds none. The serve engine's hot-reload
    watch polls this: atomic ``os.replace`` writes mean a listed bundle is
    always complete (never a torn file), and the in-progress ``.tmp.npz``
    files a live trainer writes into a SHARED directory never match the
    step pattern, so trainer and server can safely share
    ``-checkpoint_dir``."""
    paths = list_bundles(checkpoint_dir, name)
    if not paths:
        return None
    step = bundle_step(paths[0])
    return None if step is None else (step, paths[0])


# ---------------------------------------------------------------------------
# promotion protocol (docs/RELIABILITY.md "Promotion and rollback")
#
# Candidates keep landing in the autosave dir exactly as before, but a
# gated serving surface follows the atomically-updated `PROMOTED` pointer
# instead of "newest step wins". The pointer manifest records WHAT is
# promoted (bundle name, step, leaf digest, the gate report that admitted
# it) and the promotion history — the head of which is the rollback
# target. State "canary" marks a promotion still baking on a canary
# cohort; a fleet manager restarted mid-canary or mid-rollback recovers a
# consistent fleet from this one file.
# ---------------------------------------------------------------------------

def bundle_meta(path: str) -> dict:
    """A bundle's metadata record (step, trainer, leaf digest, ...)
    WITHOUT reading or validating the leaf arrays — cheap enough to call
    while building a pointer entry for a bundle the gate just
    digest-validated via a full load."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(str(z["__meta__"]))


def _pointer_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, _POINTER)


def read_promoted(checkpoint_dir: str) -> Optional[dict]:
    """The `PROMOTED` pointer manifest, or None when the directory has no
    (readable) pointer. Writes are atomic, so a well-formed file that
    fails to parse means external corruption — treated as "no pointer"
    (serving degrades to its fallback) rather than an exception on every
    poll tick."""
    try:
        with open(_pointer_path(checkpoint_dir)) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or not isinstance(m.get("current"), dict):
        return None
    return m


def promoted_bundle(checkpoint_dir: str,
                    name: Optional[str] = None) -> Optional[Tuple[int, str]]:
    """The currently-promoted bundle as ``(step, path)`` — the pointer-
    following analog of :func:`newest_bundle`. None when there is no
    pointer, the pointed-at file is gone, or (with ``name``) the entry
    was written for a different trainer."""
    m = read_promoted(checkpoint_dir)
    if m is None:
        return None
    cur = m["current"]
    if name is not None and cur.get("trainer") not in (None, name):
        return None
    path = os.path.join(checkpoint_dir, str(cur.get("bundle")))
    if not os.path.exists(path):
        return None
    return int(cur.get("step") or 0), path


def promote_bundle(checkpoint_dir: str, path: str, *,
                   gate: Optional[dict] = None,
                   state: str = "serving",
                   keep_history: int = 8) -> dict:
    """Flip the `PROMOTED` pointer to ``path`` atomically. The previous
    current entry is pushed onto the history head (= the rollback
    target). ``state="canary"`` marks the promotion as still baking —
    :func:`finalize_promotion` flips it to "serving" once the canary
    cohort passes. Returns the new manifest."""
    if state not in ("serving", "canary"):
        raise ValueError(f"unknown promotion state {state!r}")
    meta = bundle_meta(path)
    step = meta.get("t")
    entry = {
        "bundle": os.path.basename(path),
        "step": int(step if step is not None
                    else (bundle_step(path) or 0)),
        "digest": meta.get("digest"),
        "trainer": meta.get("trainer"),
        "promoted_at": round(time.time(), 3),
    }
    if gate is not None:
        entry["gate"] = gate
    m = read_promoted(checkpoint_dir) or {}
    hist = list(m.get("history") or [])
    if isinstance(m.get("current"), dict):
        hist.insert(0, m["current"])
    m.update({
        "format": _POINTER_FORMAT,
        "current": entry,
        "state": state,
        "history": hist[:max(0, int(keep_history))],
        "rollbacks": int(m.get("rollbacks") or 0),
    })
    _atomic_write_json(_pointer_path(checkpoint_dir), m)
    return m


def finalize_promotion(checkpoint_dir: str) -> Optional[dict]:
    """Mark the current promotion as fully rolled out (state "canary" →
    "serving"). No-op (returns the manifest unchanged) when already
    serving; None when there is no pointer."""
    m = read_promoted(checkpoint_dir)
    if m is None:
        return None
    if m.get("state") != "serving":
        m["state"] = "serving"
        _atomic_write_json(_pointer_path(checkpoint_dir), m)
    return m


def rollback_promoted(checkpoint_dir: str, reason: str = "") -> Optional[dict]:
    """Revert the pointer to the previous promotion (the history head).
    The reverted-from entry is recorded under ``last_rollback`` (with the
    reason) rather than back onto the history — a rollback target must
    never be a bundle that was just rolled back. Returns the new manifest,
    or None when there is no pointer or no history to roll back to."""
    m = read_promoted(checkpoint_dir)
    if m is None or not m.get("history"):
        return None
    hist = list(m["history"])
    bad = m.get("current")
    m["current"] = hist.pop(0)
    m["history"] = hist
    m["state"] = "serving"
    m["rollbacks"] = int(m.get("rollbacks") or 0) + 1
    m["last_rollback"] = {"from": bad, "reason": str(reason),
                          "ts": round(time.time(), 3)}
    _atomic_write_json(_pointer_path(checkpoint_dir), m)
    return m


def reject_bundle(path: str, reason: str = "") -> str:
    """Quarantine a bundle: write a ``<bundle>.rejected`` marker (JSON
    reason + ts) next to it. Gate watchers and the serve engine's
    newest-bundle scan skip marked bundles permanently — a candidate that
    failed the gate (or was auto-rolled-back) is never retried. Returns
    the marker path."""
    marker = path + _REJECTED
    _atomic_write_json(marker, {"reason": str(reason),
                                "ts": round(time.time(), 3)})
    return marker


def is_rejected(path: str) -> bool:
    return os.path.exists(path + _REJECTED)


def rejected_reason(path: str) -> Optional[str]:
    """The quarantine reason recorded for ``path``, or None."""
    try:
        with open(path + _REJECTED) as f:
            return str(json.load(f).get("reason"))
    except (OSError, ValueError):
        return None


def pinned_bundles(checkpoint_dir: str) -> set:
    """Bundle paths retention must NEVER delete: the currently-promoted
    bundle and the rollback target (history head). Everything else ages
    out of the last-k window normally."""
    m = read_promoted(checkpoint_dir)
    if m is None:
        return set()
    pinned = set()
    entries = [m.get("current")] + list(m.get("history") or [])[:1]
    for e in entries:
        if isinstance(e, dict) and e.get("bundle"):
            pinned.add(os.path.join(checkpoint_dir, str(e["bundle"])))
    return pinned


#: in-use marker suffix: `<bundle>.pin.<pid>` — a long-running reader (bulk
#: scoring job, gate evaluation) holds the bundle open; retention must not
#: GC it mid-run even when it has aged out of the last-k window
_PIN_SUFFIX = ".pin"
_PIN_RE = re.compile(r"\.pin\.(\d+)$")
_pin_lock = threading.Lock()
_pin_refs: Dict[str, int] = {}


def _pin_file(path: str) -> str:
    return f"{path}{_PIN_SUFFIX}.{os.getpid()}"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def pin_bundle(path: str) -> None:
    """Mark ``path`` in use by this process: an on-disk ``.pin.<pid>``
    sidecar (atomic write, same discipline as every other marker) that
    :meth:`CheckpointManager._prune` treats exactly like a pointer pin.
    Refcounted per process — nested holds write one sidecar."""
    with _pin_lock:
        n = _pin_refs.get(path, 0)
        if n == 0:
            _atomic_write_json(_pin_file(path),
                               {"pid": os.getpid(),
                                "ts": round(time.time(), 3)})
        _pin_refs[path] = n + 1


def unpin_bundle(path: str) -> None:
    """Drop one hold on ``path``; the sidecar is removed when the last
    in-process hold releases. Safe to call for a never-pinned path."""
    with _pin_lock:
        n = _pin_refs.get(path, 0)
        if n > 1:
            _pin_refs[path] = n - 1
            return
        _pin_refs.pop(path, None)
        try:
            os.remove(_pin_file(path))
        except OSError:
            pass


@contextlib.contextmanager
def hold_bundle(path: str) -> Iterator[str]:
    """Context-managed :func:`pin_bundle`/:func:`unpin_bundle` pair — the
    way a bulk job keeps its model bundle alive for the whole run."""
    pin_bundle(path)
    try:
        yield path
    finally:
        unpin_bundle(path)


def in_use_bundles(checkpoint_dir: str) -> set:
    """Bundle paths pinned by a LIVE process (``.pin.<pid>`` sidecars).
    Stale pins left by a crashed/killed holder are removed here — a dead
    pid must not leak retention forever."""
    out: set = set()
    try:
        names = os.listdir(checkpoint_dir)
    except OSError:
        return out
    for name in names:
        m = _PIN_RE.search(name)
        if not m:
            continue
        full = os.path.join(checkpoint_dir, name)
        if _pid_alive(int(m.group(1))):
            out.add(os.path.join(checkpoint_dir, name[: m.start()]))
        else:
            try:
                os.remove(full)
            except OSError:
                pass
    return out


class CheckpointManager:
    """Autosave cadence + last-k retention over atomic ``save_bundle``.

    Drives the ``-checkpoint_dir`` / ``-checkpoint_every`` /
    ``-checkpoint_keep`` trainer options inside ``fit_stream``: a bundle
    lands every ``every`` optimizer steps (windows that cross several
    boundaries — fused K-step dispatch — save once), plus a final bundle at
    stream end; only the ``keep`` newest step bundles are retained."""

    def __init__(self, checkpoint_dir: str, name: str, *, keep: int = 3,
                 every: int = 0, start_step: int = 0):
        self.dir = checkpoint_dir
        self.name = name
        self.keep = max(1, int(keep))
        self.every = int(every)
        os.makedirs(checkpoint_dir, exist_ok=True)
        self._next = start_step + self.every if self.every else None
        self._last_saved_step: Optional[int] = None
        self._last_saved_ts: Optional[float] = None
        # bundle count is CACHED (updated at save/prune time, one scan
        # here at construction): the registry provider below runs inline
        # in the fit loop (-telemetry_every) and on scrape threads, and
        # the provider contract is cheap/non-blocking — a per-snapshot
        # listdir on a networked checkpoint FS would stall training
        self._bundles = len(list_bundles(checkpoint_dir, name))
        # obs registry section (weakly held — the registry is process-wide
        # and must not pin a dead manager or its trainer). A trainer-owned
        # manager is ALSO reachable through the trainer's own `checkpoint`
        # provider (LearnerBase._register_obs delegates to obs_section),
        # which re-registers on every trainer construction so a new
        # trainer can never inherit a previous trainer's section.
        from ..obs.registry import CHECKPOINT_STUB, registry
        ref = weakref.ref(self)

        def _obs() -> dict:
            m = ref()
            return m.obs_section() if m is not None \
                else dict(CHECKPOINT_STUB)

        registry.register("checkpoint", _obs)

    def obs_section(self) -> dict:
        """This manager's `checkpoint` registry section (cheap: every
        field is a cached attribute — no filesystem access)."""
        return {
            "configured": True,
            "dir": self.dir,
            "every": self.every,
            "keep": self.keep,
            "last_saved_step": self._last_saved_step,
            "age_seconds": (round(time.monotonic() - self._last_saved_ts, 3)
                            if self._last_saved_ts else None),
            "bundles": self._bundles,
        }

    def maybe_save(self, trainer) -> Optional[str]:
        if self._next is None or trainer._t < self._next:
            return None
        path = self.save(trainer)
        while self._next <= trainer._t:
            self._next += self.every
        return path

    def save(self, trainer) -> str:
        path = os.path.join(self.dir,
                            f"{self.name}-step{trainer._t:010d}.npz")
        save_bundle(trainer, path)
        self._last_saved_step = int(trainer._t)
        self._last_saved_ts = time.monotonic()
        self._prune()
        emit = getattr(trainer, "_emit_checkpoint_event", None)
        if emit is not None:            # one emitter for every save site
            emit(path, step=int(trainer._t))
        else:                           # non-LearnerBase trainers (MF, ...)
            from ..utils.metrics import get_stream
            stream = get_stream()
            if stream.enabled:
                stream.emit("checkpoint", trainer=self.name,
                            step=int(trainer._t), path=path)
        return path

    def save_final(self, trainer) -> Optional[str]:
        """End-of-stream bundle, skipped when the cadence already saved
        this exact step."""
        if self._last_saved_step == int(trainer._t):
            return None
        return self.save(trainer)

    def _prune(self) -> None:
        paths = list_bundles(self.dir, self.name)
        kept = len(paths)
        # pointer-pinned bundles are EXEMPT from last-k retention: pruning
        # the currently-promoted bundle would take the serving model's
        # file out from under the fleet, and pruning the rollback target
        # would make auto-rollback impossible exactly when a bad canary
        # needs it (docs/RELIABILITY.md "Promotion and rollback")
        # ... and so are bundles a live reader holds open (.pin.<pid>
        # sidecars): a bulk scoring job that resolved its model at launch
        # must not have the file GC'd out from under it mid-run
        pinned = pinned_bundles(self.dir) | in_use_bundles(self.dir)
        for path in paths[self.keep:]:
            if path in pinned:
                continue
            try:
                os.remove(path)
                kept -= 1
            except OSError:
                continue
            # sidecars die with their bundle, never orphaned: the
            # quarantine marker and the mmap'd serving arena (pinned
            # bundles above keep theirs, so the promoted model's arena
            # and the rollback target's survive retention). Lazy import:
            # weight_arena imports back into io at call time only
            from .weight_arena import ARENA_SUFFIX
            for suffix in (_REJECTED, ARENA_SUFFIX):
                if os.path.exists(path + suffix):
                    try:
                        os.remove(path + suffix)
                    except OSError:
                        pass
        self._bundles = kept
