"""Checkpoint bundles: params + optimizer state + counters, resumable.

Reference (SURVEY.md §6 "Checkpoint / resume"): in Hivemall every model IS a
durable table, and warm start is `-loadmodel` over an exported model file —
but optimizer state (AdaGrad accumulators etc.) is lost across restarts and
mid-epoch resume does not exist. The rebuild keeps the model-table path
(LearnerBase.save_model / -loadmodel) for weight-only warm starts and adds
what the reference lacks: a full bundle of every device array a trainer
needs to continue exactly where it stopped — weights, optimizer slots,
covariance tables, the global step (which drives EtaEstimator schedules),
example counts, and the hashed-id→name map.

Format: one .npz — flattened pytree leaves (bf16 stored as f32, original
dtype restored from the live trainer's reference tree on load) plus a json
metadata record. Loading validates trainer name and leaf shapes so a bundle
can't silently resume onto a mismatched config.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import jax
import numpy as np

__all__ = ["save_bundle", "load_bundle"]

_FORMAT = 1


def save_bundle(trainer, path: str) -> None:
    """Write the trainer's full resumable state to ``path`` (.npz).

    Works for any trainer exposing `_checkpoint_arrays`/`_restore_arrays`;
    the LearnerBase counters (_examples, _loss_sum, _names) are optional so
    non-LearnerBase trainers (e.g. MF) bundle too."""
    if hasattr(trainer, "_fold_loss"):
        trainer._fold_loss()
    leaves, treedef = jax.tree_util.tree_flatten(trainer._checkpoint_arrays())
    meta: Dict[str, Any] = {
        "format": _FORMAT,
        "trainer": trainer.NAME,
        "n_leaves": len(leaves),
        "t": getattr(trainer, "_t", 0),
        "examples": getattr(trainer, "_examples", 0),
        "loss_sum": getattr(trainer, "_loss_sum", 0.0),
        "names": {str(k): v for k, v in getattr(trainer, "_names",
                                                {}).items()},
        "scalars": (trainer._checkpoint_scalars()
                    if hasattr(trainer, "_checkpoint_scalars") else {}),
    }
    arrays = {}
    for i, leaf in enumerate(leaves):
        a = np.asarray(leaf)
        if a.dtype.name == "bfloat16":      # npz can't take ml_dtypes leaves
            a = a.astype(np.float32)
        arrays[f"leaf_{i}"] = a
    np.savez(path, __meta__=np.array(json.dumps(meta)), **arrays)


def load_bundle(trainer, path: str) -> None:
    """Restore a bundle into a freshly constructed trainer (same options)."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        if meta.get("format") != _FORMAT:
            raise ValueError(
                f"bundle format {meta.get('format')!r} != supported "
                f"{_FORMAT} — bundle written by an incompatible version")
        if meta.get("trainer") != trainer.NAME:
            raise ValueError(
                f"bundle was written by {meta.get('trainer')!r}, "
                f"cannot resume {trainer.NAME!r}")
        ref_leaves, treedef = jax.tree_util.tree_flatten(
            trainer._checkpoint_arrays())
        if meta["n_leaves"] != len(ref_leaves):
            raise ValueError(
                f"bundle has {meta['n_leaves']} state arrays, trainer "
                f"expects {len(ref_leaves)} — options mismatch?")
        leaves = []
        for i, ref in enumerate(ref_leaves):
            a = z[f"leaf_{i}"]
            if tuple(a.shape) != tuple(ref.shape):
                raise ValueError(
                    f"state array {i}: bundle shape {a.shape} != "
                    f"trainer shape {tuple(ref.shape)} — options mismatch?")
            leaves.append(jax.numpy.asarray(a, dtype=ref.dtype))
    trainer._restore_arrays(jax.tree_util.tree_unflatten(treedef, leaves))
    trainer._t = int(meta["t"])
    for attr, val in (("_examples", int(meta["examples"])),
                      ("_loss_sum", float(meta["loss_sum"])),
                      ("_loss_pending", 0.0)):
        if hasattr(trainer, attr):
            setattr(trainer, attr, val)
    if hasattr(trainer, "_names"):
        trainer._names.update({int(k): v for k, v in meta["names"].items()})
    if meta.get("scalars") and hasattr(trainer, "_restore_scalars"):
        trainer._restore_scalars(meta["scalars"])
    if getattr(trainer, "mesh", None) is not None:
        trainer._reshard_state()      # bundles load replicated; re-shard
