"""Parallel host ingest pipeline — multi-worker batch prep, in order.

BENCH_r05 context: the fused FFM step sustains ~716k examples/sec while
end-to-end training reaches ~44k — the chip idles >90% of the wall because
host batch prep (string parse -> pad -> ``canonicalize_fieldmajor`` ->
``pack_unit_fieldmajor``) runs as single-threaded Python ahead of a
depth-2 ``DevicePrefetcher``. This is SURVEY §8's hard part verbatim
("the input path ... can easily be the bottleneck, not the TPU"); the
reference never met it because Hadoop amortized ingest across mappers.

:class:`IngestPipeline` shards the prep function over a pool of workers —
threads by default: the heavy kernels (``canonicalize_fieldmajor``,
``pack_unit_fieldmajor``, the padding fancy-indexing) are NumPy and release
the GIL — and delivers results **in the source order** with bounded
backpressure, so host prep, h2d transfer (``DevicePrefetcher``) and device
compute form a three-stage pipeline instead of two serialized legs::

    stats = PipelineStats()
    it = IngestPipeline(ds.batches(bs), trainer._preprocess_train_batch,
                        workers=4, stats=stats)
    for staged in DevicePrefetcher(it, depth=2, stats=stats):
        step(params, staged)

Ordering: a submitter thread walks the source iterator (serially — Python
generators are not thread-safe, and trainer hooks like ``_note_batch``
depend on stream order), submits each item to the pool, and enqueues the
FUTURES in submission order into a bounded queue; the consumer resolves
them in that same order. N-worker output is therefore the same batches in
the same order as the sequential path, and a worker exception surfaces on
the consumer within one batch (the failed future's ``result()`` raises)
instead of hanging the stream.

``workers<=1`` is a STRICT sequential fallback: no threads, no queue —
``next(src)`` then ``fn(item)`` inline, bit-exact with ``map(fn, src)``.

Every stage exports lightweight counters through :class:`PipelineStats`
(batches prepared/staged, per-stage busy and wait seconds, queue
occupancy) so later ingest work can see *where* the wall goes.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

from ..obs.trace import get_tracer

__all__ = ["PipelineStats", "IngestPipeline", "auto_workers"]

_STOP = object()


class _SourceError:
    """Marker carrying an exception raised by the SOURCE iterator (not a
    worker); the consumer re-raises it in stream position."""

    def __init__(self, e: BaseException):
        self.e = e


def drain_until_dead(q: "queue.Queue", thread: threading.Thread,
                     timeout: float = 5.0, cancel: bool = False) -> None:
    """Shared close() engine for producer-thread + bounded-queue stages
    (IngestPipeline, DevicePrefetcher): repeatedly drain ``q`` so a
    producer blocked on a full queue wakes, until ``thread`` exits or
    ``timeout`` elapses (a producer wedged OUTSIDE a queue op — e.g. a
    device_put hung on the relay — must not turn close() into a permanent
    hang; the daemon thread is abandoned instead). Leftover items,
    including any sentinel, are cleared; ``cancel=True`` also cancels
    drained futures."""
    deadline = time.monotonic() + timeout
    while thread.is_alive() and time.monotonic() < deadline:
        try:
            item = q.get_nowait()
            if cancel and hasattr(item, "cancel"):
                item.cancel()
        except queue.Empty:
            thread.join(timeout=0.05)
    while True:
        try:
            item = q.get_nowait()
            if cancel and hasattr(item, "cancel"):
                item.cancel()
        except queue.Empty:
            break


def _timed_call(fn, item):
    """Module-level so ProcessPoolExecutor can pickle the task (a bound
    pipeline method would drag the queue/lock along). Returns (result,
    seconds) so prep time is measured in the worker, recorded by the
    consumer. The ``ingest.prep`` span is likewise recorded IN the worker
    thread — the tracer's ring is thread-safe, and worker-side spans are
    what the obs rollup attributes prep time with (process pools record
    into the child's tracer, which is lost — thread pools are the default
    and the traced configuration)."""
    t0 = time.perf_counter()
    with get_tracer().span("ingest.prep"):
        out = fn(item)
    return out, time.perf_counter() - t0


def auto_workers() -> int:
    """Default prep-worker count: leave one core for the training loop /
    device runtime, cap at 8 (past that the bounded queue and the h2d link
    are the limiters, not prep parallelism)."""
    return max(1, min(8, (os.cpu_count() or 2) - 1))


@dataclass
class PipelineStats:
    """Lightweight cross-stage counters for the ingest pipeline.

    One instance is shared by every stage of a fit: the prep pool
    (:class:`IngestPipeline`), the h2d stage (``DevicePrefetcher``) and the
    consuming train loop. Busy seconds are summed across workers (they can
    exceed wall time under parallelism); wait seconds are the time a stage
    spent BLOCKED on its neighbour — the direct reading of where the wall
    goes: large ``consume_wait_seconds`` means input-bound, large
    ``prep_backpressure_seconds`` means compute/transfer-bound.
    """

    workers: int = 0                       # prep pool size (0 = no pipeline)
    pool: str = "none"                     # "none" | "thread" | "process"
    batches_prepared: int = 0              # prep outputs (fn() completions)
    prep_seconds: float = 0.0              # summed in-worker fn() time
    prep_wait_seconds: float = 0.0         # consumer blocked on prep output
    prep_backpressure_seconds: float = 0.0  # submitter blocked on full queue
    batches_staged: int = 0                # h2d stage outputs (device_put)
    stage_seconds: float = 0.0             # summed device_put time
    consume_wait_seconds: float = 0.0      # train loop blocked on h2d output
    steps_per_dispatch: int = 1            # fused-dispatch window K
    megabatches_staged: int = 0            # K-step windows stacked
    stack_seconds: float = 0.0             # host stacking time (stager)
    singles_flushed: int = 0               # K=1 fallbacks (ragged/kind-mix)
    cache_batches: int = 0                 # batches served from the packed
                                           # shard cache (no live prep ran)
    cache_assemble_seconds: float = 0.0    # mmap gather + buffer re-slice
    queue_occupancy_sum: int = 0           # qsize sampled at each get
    queue_samples: int = 0
    queue_peak: int = 0
    worker_errors: int = 0                 # prep fn() raised (re-raised in
                                           # stream position by the consumer)
    source_errors: int = 0                 # source iterator raised
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def add(self, **kw: float) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def sample_queue(self, qsize: int) -> None:
        with self._lock:
            self.queue_occupancy_sum += qsize
            self.queue_samples += 1
            if qsize > self.queue_peak:
                self.queue_peak = qsize

    @property
    def avg_queue_occupancy(self) -> float:
        return (self.queue_occupancy_sum / self.queue_samples
                if self.queue_samples else 0.0)

    def as_dict(self) -> dict:
        """JSON-ready snapshot (bench.py embeds this in its output dict)."""
        return {
            "workers": self.workers,
            "pool": self.pool,
            "batches_prepared": self.batches_prepared,
            "prep_seconds": round(self.prep_seconds, 4),
            "prep_wait_seconds": round(self.prep_wait_seconds, 4),
            "prep_backpressure_seconds":
                round(self.prep_backpressure_seconds, 4),
            "batches_staged": self.batches_staged,
            "stage_seconds": round(self.stage_seconds, 4),
            "consume_wait_seconds": round(self.consume_wait_seconds, 4),
            "steps_per_dispatch": self.steps_per_dispatch,
            "megabatches_staged": self.megabatches_staged,
            "stack_seconds": round(self.stack_seconds, 4),
            "singles_flushed": self.singles_flushed,
            "cache_batches": self.cache_batches,
            "cache_assemble_seconds": round(self.cache_assemble_seconds, 4),
            "avg_queue_occupancy": round(self.avg_queue_occupancy, 3),
            "queue_peak": self.queue_peak,
            "worker_errors": self.worker_errors,
            "source_errors": self.source_errors,
        }


class IngestPipeline:
    """Map ``fn`` over ``src`` with ``workers`` pool workers, delivering
    results in source order with bounded backpressure.

    ``pool="thread"`` (default) suits NumPy-heavy prep (releases the GIL);
    ``pool="process"`` is for string-parse-heavy sources where the prep is
    Python-bound — ``fn`` and the items must then be picklable, which rules
    out bound trainer methods (use a module-level parse function).

    ``depth`` bounds the prepared-but-unconsumed batches (default
    ``2*workers``); total in-flight work is ``depth`` queued + ``workers``
    executing + one pending submit.
    """

    def __init__(self, src: Iterable[Any], fn: Callable[[Any], Any], *,
                 workers: Optional[int] = None, depth: Optional[int] = None,
                 pool: str = "thread",
                 stats: Optional[PipelineStats] = None):
        if pool not in ("thread", "process"):
            raise ValueError(f"pool must be 'thread' or 'process': {pool!r}")
        self._workers = auto_workers() if workers is None or workers <= 0 \
            else int(workers)
        self.stats = stats if stats is not None else PipelineStats()
        self.stats.workers = self._workers
        self._fn = fn
        self._closed = threading.Event()
        if self._workers <= 1:
            # strict sequential fallback: no threads, no queue — bit-exact
            # with map(fn, src) (single-worker behavior is the pre-pipeline
            # contract tests pin)
            self.stats.pool = "none"
            self._src: Optional[Iterator[Any]] = iter(src)
            self._exec = None
            return
        import concurrent.futures as cf
        self.stats.pool = pool
        self._src = None
        self._q: queue.Queue = queue.Queue(
            maxsize=max(1, depth if depth is not None else 2 * self._workers))
        self._exec = (cf.ThreadPoolExecutor(self._workers,
                                            thread_name_prefix="ingest")
                      if pool == "thread"
                      else cf.ProcessPoolExecutor(self._workers))
        # the submitter closure captures LOCALS only, never self (a thread
        # is a GC root: a closure over self would keep an abandoned
        # pipeline reachable forever and __del__ could never run close())
        q, closed, ex, stats = self._q, self._closed, self._exec, self.stats

        def submit_loop(it: Iterator[Any]) -> None:
            try:
                for item in it:
                    f = ex.submit(_timed_call, fn, item)
                    t0 = time.perf_counter()
                    q.put(f)            # blocking; close() drains to wake
                    stats.add(
                        prep_backpressure_seconds=time.perf_counter() - t0)
                    if closed.is_set():
                        f.cancel()
                        return          # consumer abandoned the stream
            except BaseException as e:  # src iteration failed: surface it
                q.put(_SourceError(e))
            finally:
                # the sentinel MUST reach the consumer or next() blocks
                # forever; close() keeps draining until this thread exits,
                # so a blocked put always wakes
                q.put(_STOP)

        self._submitter = threading.Thread(target=submit_loop,
                                           args=(iter(src),), daemon=True)
        self._submitter.start()

    # -- consumer side ------------------------------------------------------
    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._closed.is_set():
            raise StopIteration
        if self._exec is None:          # sequential fallback
            item = next(self._src)      # StopIteration ends the stream
            t0 = time.perf_counter()
            try:
                with get_tracer().span("ingest.prep"):
                    out = self._fn(item)
            except BaseException:
                self.stats.add(worker_errors=1)
                self._closed.set()
                raise
            self.stats.add(prep_seconds=time.perf_counter() - t0,
                           batches_prepared=1)
            return out
        t0 = time.perf_counter()
        fut = self._q.get()             # blocking; sentinel always arrives
        if fut is _STOP:
            self._closed.set()
            self._submitter.join()
            self._exec.shutdown(wait=False)
            raise StopIteration
        if isinstance(fut, _SourceError):
            self.stats.add(source_errors=1)
            self.close()
            raise fut.e
        self.stats.sample_queue(self._q.qsize())
        try:
            out, dt = fut.result()      # worker exception re-raises HERE —
        except BaseException:           # within one batch of where it fired
            self.stats.add(worker_errors=1)
            self.close()
            raise
        self.stats.add(prep_wait_seconds=time.perf_counter() - t0,
                       prep_seconds=dt, batches_prepared=1)
        return out

    def close(self) -> None:
        """Release the submitter + pool (early exit; safe to call twice)."""
        self._closed.set()
        if self._exec is None:
            return
        drain_until_dead(self._q, self._submitter, cancel=True)
        self._exec.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except BaseException:
            pass
