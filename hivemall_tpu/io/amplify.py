"""amplify / rand_amplify — epochs-and-shuffle as dataset transforms.

Reference: hivemall.ftvec.amplify.{AmplifierUDTF,RandomAmplifierUDTF}
(SURVEY.md §3.12): under one-pass map-only SQL, multi-epoch training is
expressed by emitting each row ``xtimes`` and shuffling within a bounded
buffer. Here the same names become SparseDataset -> SparseDataset transforms
feeding the TPU input pipeline; trainers' ``-iters`` option is the direct
(preferred) route, these exist for catalog parity and pipeline composition.
"""

from __future__ import annotations

import numpy as np

from .sparse import SparseDataset

__all__ = ["amplify", "rand_amplify"]


def _take(ds: SparseDataset, order: np.ndarray) -> SparseDataset:
    lens = np.diff(ds.indptr)
    new_indptr = np.zeros(len(order) + 1, np.int64)
    new_indptr[1:] = np.cumsum(lens[order])
    total = int(new_indptr[-1])
    idx = np.empty(total, np.int32)
    val = np.empty(total, np.float32)
    fld = np.empty(total, np.int32) if ds.fields is not None else None
    for k, r in enumerate(order):
        s, e = ds.indptr[r], ds.indptr[r + 1]
        t = new_indptr[k]
        idx[t:t + (e - s)] = ds.indices[s:e]
        val[t:t + (e - s)] = ds.values[s:e]
        if fld is not None:
            fld[t:t + (e - s)] = ds.fields[s:e]
    return SparseDataset(idx, new_indptr, val, ds.labels[order], fld)


def amplify(ds: SparseDataset, xtimes: int) -> SparseDataset:
    """SQL: amplify(xtimes, *) — emit each row xtimes consecutively
    (r0,r0,...,r1,r1,... — the reference's per-row duplication order, which is
    what rand_amplify's bounded-buffer shuffle exists to break up)."""
    if xtimes <= 1:
        return ds
    order = np.repeat(np.arange(len(ds)), xtimes)
    return _take(ds, order)


def rand_amplify(ds: SparseDataset, xtimes: int, bufsize: int = 1000,
                 seed: int = 42) -> SparseDataset:
    """SQL: rand_amplify(xtimes, bufsize, *) — amplify then shuffle within a
    sliding buffer of ``bufsize`` rows (bounded-memory shuffle, matching the
    reference's within-buffer semantics rather than a global permutation)."""
    amped = amplify(ds, xtimes)
    n = len(amped)
    rng = np.random.default_rng(seed)
    order = np.arange(n)
    for s in range(0, n, bufsize):
        seg = order[s:s + bufsize]
        rng.shuffle(seg)
        order[s:s + bufsize] = seg
    return _take(amped, order)
