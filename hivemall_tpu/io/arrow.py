"""Arrow-native columnar ingest: Parquet/CSV/Arrow -> SparseDataset, plus
out-of-core streaming epochs over sharded Parquet directories.

Reference analogs (SURVEY.md §1 "Arrow-native columnar runtime", §8 M0
"Arrow ingest + LIBSVM reader", §3.20 NioStatefulSegment -> "Arrow input
pipeline, memory-map shards"): the reference's engine feeds trainer UDTFs
rows from Hive/Spark columnar scans; here pyarrow record batches are the
scan, and a directory of Parquet shards plays the split-per-task input.
Criteo-1TB cannot be an in-RAM LIBSVM parse — ParquetStream re-reads
shards per epoch so the resident set is one shard, not the dataset.

Two supported schemas per table:
  string features — `features: list<string>` of "name:val"/"idx:val"
    ("field:idx:val" with ffm=True) + numeric label column. Names hash
    through the bit-exact murmur3 (utils.hashing.mhash_batch).
  pre-parsed CSR — `indices: list<int32>` + optional `values: list<float>`
    (+ `fields: list<int32>`) + label column: zero parse cost, the Criteo
    fast path.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .sparse import SparseBatch, SparseDataset

__all__ = ["read_parquet", "read_csv", "read_arrow", "table_to_dataset",
           "ParquetStream", "write_parquet_shards"]


def _pa():
    try:
        import pyarrow
        return pyarrow
    except ImportError as e:            # pragma: no cover - baked in here
        raise ImportError(
            "pyarrow is required for Arrow/Parquet ingest; use the LIBSVM "
            "reader (io.libsvm) where it is unavailable") from e


def _parse_string_features(flat: np.ndarray, *, dims: Optional[int],
                           ffm: bool, num_fields: int
                           ) -> Tuple[np.ndarray, np.ndarray,
                                      Optional[np.ndarray]]:
    """Vectorized parse of flat feature strings.

    "name:val" (value defaults to 1) or, with ffm=True, "field:idx[:val]".
    Integer names pass through; non-integer names hash via murmur3 into
    [1, dims-1] (dims defaults to 2^24, the reference's feature_hashing
    default). np.char ops keep this C-speed; the per-string Python loop
    only runs for the non-integer residue."""
    from ..utils.hashing import mhash_batch

    u = flat.astype("U")
    if ffm:
        fld_s, _, rest = np.char.partition(u, ":").T[(0, 1, 2), :]
        name_s, _, val_s = np.char.partition(rest, ":").T[(0, 1, 2), :]
    else:
        # split on the LAST ':' so "ns:name:val" string names still parse
        name_s, _, val_s = np.char.rpartition(u, ":").T[(0, 1, 2), :]
        # bare "name" (no colon): rpartition puts it in the last slot
        bare = name_s == ""
        name_s = np.where(bare, val_s, name_s)
        val_s = np.where(bare, "1", val_s)
        fld_s = None
    val = np.where(val_s == "", "1", val_s).astype(np.float32)

    def ids_from(names: np.ndarray, space: int) -> np.ndarray:
        # only NON-NEGATIVE integer names pass through as direct indices;
        # anything else (including "-3") murmur-hashes into [1, space] —
        # negative gather indices would silently wrap to the table's end
        digits = np.char.isdigit(names)
        out = np.zeros(len(names), np.int64)
        if digits.any():
            out[digits] = names[digits].astype(np.int64)
        rest = ~digits
        if rest.any():
            out[rest] = mhash_batch([str(s) for s in names[rest]], space)
        return out

    idx = ids_from(name_s, (dims or (1 << 24)) - 1).astype(np.int32)
    fld = None
    if ffm:
        fld = (ids_from(fld_s, num_fields) % num_fields).astype(np.int32)
    return idx, val, fld


def table_to_dataset(table, *, feature_col: str = "features",
                     label_col: str = "label",
                     dims: Optional[int] = None, ffm: bool = False,
                     num_fields: int = 64) -> SparseDataset:
    """One pyarrow Table -> SparseDataset (schemas per module docstring)."""
    pa = _pa()
    names = set(table.column_names)
    labels = table.column(label_col).to_numpy(
        zero_copy_only=False).astype(np.float32)

    if "indices" in names:              # pre-parsed CSR fast path
        col = table.column("indices").combine_chunks()
        indices = col.flatten().to_numpy().astype(np.int32)
        indptr = col.offsets.to_numpy().astype(np.int64)
        if "values" in names:
            values = table.column("values").combine_chunks().flatten() \
                .to_numpy().astype(np.float32)
        else:
            values = np.ones(len(indices), np.float32)
        fields = None
        if "fields" in names:
            fields = table.column("fields").combine_chunks().flatten() \
                .to_numpy().astype(np.int32)
        return SparseDataset(indices, indptr, values, labels, fields)

    col = table.column(feature_col).combine_chunks()
    indptr = col.offsets.to_numpy().astype(np.int64)
    flat = col.flatten().to_numpy(zero_copy_only=False)
    if len(flat) and not isinstance(flat[0], str):
        # list<int> categorical ids, value 1.0
        indices = flat.astype(np.int32)
        return SparseDataset(indices, indptr,
                             np.ones(len(indices), np.float32), labels)
    idx, val, fld = _parse_string_features(
        np.asarray(flat, object), dims=dims, ffm=ffm, num_fields=num_fields)
    return SparseDataset(idx, indptr, val, labels, fld)


def _parquet_files(path: str) -> List[str]:
    if os.path.isdir(path):
        out = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith((".parquet", ".pq")))
        if not out:
            raise FileNotFoundError(f"no .parquet shards under {path}")
        return out
    if not os.path.exists(path):
        raise FileNotFoundError(f"parquet input not found: {path}")
    return [path]


def read_parquet(path: str, **kw) -> SparseDataset:
    """Read one Parquet file or a shard directory fully into RAM.
    For larger-than-RAM corpora use ParquetStream instead."""
    import pyarrow.parquet as pq
    pa = _pa()
    files = _parquet_files(path)
    ds = table_to_dataset(pa.concat_tables([pq.read_table(f)
                                            for f in files]), **kw)
    if len(files) == 1:
        # file identity for the packed shard cache (io.shard_cache):
        # mtime/size staleness discipline + the parse config (the same
        # bytes parsed differently are a different dataset)
        from .shard_cache import file_source_id
        sid = file_source_id(files[0], {"reader": "parquet", **kw})
        if sid:
            ds.source_id = sid
    return ds


def read_csv(path: str, *, feature_cols: Optional[Sequence[str]] = None,
             label_col: str = "label",
             dims: Optional[int] = None) -> SparseDataset:
    """CSV -> SparseDataset. With feature_cols=None every non-label column
    becomes a quantitative feature "col:value" (hashed name); explicit
    feature_cols restricts the set. The ftvec.trans quantitative_features
    analog at ingest level."""
    import pyarrow as pa
    from pyarrow import csv as pacsv
    from ..utils.hashing import mhash_batch
    table = pacsv.read_csv(path)

    def numeric(c):
        t = table.schema.field(c).type
        return (pa.types.is_integer(t) or pa.types.is_floating(t)
                or pa.types.is_boolean(t))     # bool casts cleanly to 0/1
    if feature_cols is not None:
        cols = list(feature_cols)
        bad = [c for c in cols if not numeric(c)]
        if bad:
            raise ValueError(
                f"non-numeric feature columns {bad}; encode them first "
                f"(e.g. ftvec categorical_features) or drop them")
    else:
        # id/name/text columns are common — only numeric columns become
        # quantitative features by default
        cols = [c for c in table.column_names
                if c != label_col and numeric(c)]
        if not cols:
            raise ValueError(
                f"no numeric feature columns in {path}; pass feature_cols")
    labels = table.column(label_col).to_numpy(
        zero_copy_only=False).astype(np.float32)
    n = len(labels)
    space = (dims or (1 << 24)) - 1
    ids = np.asarray(mhash_batch(cols, space), np.int32)
    mat = np.stack([table.column(c).to_numpy(zero_copy_only=False)
                    .astype(np.float32) for c in cols], axis=1)
    indices = np.tile(ids, n)
    values = mat.ravel()
    indptr = np.arange(0, n * len(cols) + 1, len(cols), dtype=np.int64)
    keep = values != 0                  # sparse semantics: drop zeros
    if not keep.all():
        counts = keep.reshape(n, len(cols)).sum(1)
        indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        indices, values = indices[keep], values[keep]
    return SparseDataset(indices, indptr, values, labels)


def read_arrow(path: str, **kw) -> SparseDataset:
    """Arrow IPC/feather file -> SparseDataset."""
    import pyarrow.feather as feather
    return table_to_dataset(feather.read_table(path), **kw)


def write_parquet_shards(ds: SparseDataset, out_dir: str, *,
                         rows_per_shard: int = 1 << 20) -> List[str]:
    """Spill a SparseDataset to a directory of CSR-schema Parquet shards
    (the inverse of ParquetStream; used to stage out-of-core corpora)."""
    pa = _pa()
    import pyarrow.parquet as pq
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    n = len(ds)
    for s0 in range(0, n, rows_per_shard):
        s1 = min(n, s0 + rows_per_shard)
        lo, hi = ds.indptr[s0], ds.indptr[s1]
        off = (ds.indptr[s0:s1 + 1] - lo).astype(np.int32)
        cols = {
            "indices": pa.ListArray.from_arrays(
                off, pa.array(ds.indices[lo:hi], pa.int32())),
            "values": pa.ListArray.from_arrays(
                off, pa.array(ds.values[lo:hi], pa.float32())),
            "label": pa.array(ds.labels[s0:s1], pa.float32()),
        }
        if ds.fields is not None:
            cols["fields"] = pa.ListArray.from_arrays(
                off, pa.array(ds.fields[lo:hi], pa.int32()))
        path = os.path.join(out_dir, f"shard-{s0 // rows_per_shard:05d}"
                                     f".parquet")
        pq.write_table(pa.table(cols), path)
        paths.append(path)
    return paths


class ParquetStream:
    """Out-of-core epochs over a directory of Parquet shards.

    The NioStatefulSegment rebuild at corpus scale: every epoch re-reads
    the shards from disk (shard order shuffled per epoch, rows shuffled
    within each shard) and yields fixed-shape padded SparseBatches; resident
    memory is one shard + one carry-over remainder, never the corpus.
    Feed the result to ``LearnerBase.fit_stream``.
    """

    def __init__(self, path: str, *, feature_col: str = "features",
                 label_col: str = "label", dims: Optional[int] = None,
                 ffm: bool = False, num_fields: int = 64,
                 decode_ahead: int = 1, cache_dir: Optional[str] = None):
        self.files = _parquet_files(path)
        self._kw = dict(feature_col=feature_col, label_col=label_col,
                        dims=dims, ffm=ffm, num_fields=num_fields)
        # decode-ahead: while training consumes the current shard's batches,
        # a reader thread decodes the NEXT decode_ahead shards (Parquet
        # read + string parse + hashing — pyarrow releases the GIL on the
        # IO/decode legs). 0 restores the synchronous per-shard re-read.
        self.decode_ahead = max(0, int(decode_ahead))
        # per-shard decoded-CSR cache (io.shard_cache.ShardDecodeCache):
        # the first decode of each (shard mtime/size, parse config) also
        # persists the parsed columns, so epoch >= 2 and RESTARTS mmap
        # them instead of re-paying Parquet read + string parse + murmur
        # hashing — the string-parse-heavy leg of the streaming wall
        # (docs/PERFORMANCE.md "Shard cache"). None = off.
        self._cache = None
        if cache_dir:
            from .shard_cache import ShardDecodeCache
            self._cache = ShardDecodeCache(cache_dir, self._kw)
        from .pipeline import PipelineStats
        self.stats = PipelineStats(pool="decode-ahead",
                                   workers=self.decode_ahead)

    def _shard(self, path: str) -> SparseDataset:
        import pyarrow.parquet as pq
        if self._cache is not None:
            ds = self._cache.load(path)
            if ds is not None:
                return ds
        ds = table_to_dataset(pq.read_table(path), **self._kw)
        if self._cache is not None:
            self._cache.store(path, ds)
        return ds

    def _iter_shards(self, files: List[str]) -> Iterator[SparseDataset]:
        """Yield decoded shards in order, reading up to ``decode_ahead``
        shards beyond the one being consumed. Row-shuffle rng calls stay in
        the CONSUMING loop, so shuffled epochs are bit-identical to the
        synchronous path — only the disk read/parse moves off it."""
        import time as _time
        if self.decode_ahead <= 0:
            for f in files:
                t0 = _time.perf_counter()
                ds = self._shard(f)
                self.stats.add(prep_seconds=_time.perf_counter() - t0,
                               batches_prepared=1)
                yield ds
            return
        import concurrent.futures as cf
        ex = cf.ThreadPoolExecutor(max_workers=1,
                                   thread_name_prefix="pq-decode")
        try:
            import itertools
            from collections import deque
            pending = deque()
            it = iter(files)

            def timed_shard(f):
                t0 = _time.perf_counter()
                ds = self._shard(f)
                self.stats.add(prep_seconds=_time.perf_counter() - t0,
                               batches_prepared=1)
                return ds

            # prime exactly decode_ahead futures: with the shard the
            # consumer holds, at most decode_ahead decoded shards sit in
            # ``pending`` — the memory bound the docs promise
            for f in itertools.islice(it, self.decode_ahead):
                pending.append(ex.submit(timed_shard, f))
            while pending:
                t0 = _time.perf_counter()
                ds = pending.popleft().result()
                self.stats.add(prep_wait_seconds=_time.perf_counter() - t0)
                nxt = next(it, None)
                if nxt is not None:
                    pending.append(ex.submit(timed_shard, nxt))
                yield ds
        finally:
            for p in pending:
                p.cancel()
            ex.shutdown(wait=False)

    def __len__(self) -> int:
        import pyarrow.parquet as pq
        return sum(pq.ParquetFile(f).metadata.num_rows for f in self.files)

    @property
    def max_row_len(self) -> int:
        """Longest row across shards, from the list column's OFFSETS only —
        no string parse, no hashing, one column read per shard. With the
        decode cache on, cached shards answer from a header-only read, so
        a fully warm traversal never opens the source Parquet bytes at
        all."""
        import pyarrow.parquet as pq
        m = 1
        for f in self.files:
            if self._cache is not None:
                hint = self._cache.max_row_len_hint(f)
                if hint is not None:
                    m = max(m, hint)
                    continue
            pf = pq.ParquetFile(f)
            col = "indices" if "indices" in pf.schema_arrow.names \
                else self._kw["feature_col"]
            t = pq.read_table(f, columns=[col])
            arr = t.column(col).combine_chunks()
            m = max(m, int(np.diff(arr.offsets.to_numpy()).max(initial=1)))
        return m

    def batches(self, batch_size: int, *, epochs: int = 1,
                shuffle: bool = True, seed: int = 42,
                max_len: Optional[int] = None,
                truncate: bool = False) -> Iterator[SparseBatch]:
        # fresh decode counters per stream traversal: repeat-fit callers
        # (the bench's best-of-3) read a per-call snapshot, not a lifetime
        # accumulation masquerading as one run's decode cost
        from .pipeline import PipelineStats
        self.stats = PipelineStats(pool="decode-ahead",
                                   workers=self.decode_ahead)
        L = max_len or self.max_row_len
        rng = np.random.default_rng(seed)
        for ep in range(epochs):
            order = rng.permutation(len(self.files)) if shuffle \
                else np.arange(len(self.files))
            carry: Optional[SparseDataset] = None
            for ds in self._iter_shards([self.files[fi] for fi in order]):
                if carry is not None:
                    ds = _concat_datasets(carry, ds)
                    carry = None
                n = len(ds)
                n_full = (n // batch_size) * batch_size
                row_order = rng.permutation(n) if shuffle else np.arange(n)
                full = _take_rows(ds, row_order[:n_full])
                yield from full.batches(batch_size, shuffle=False,
                                        max_len=L, truncate=truncate)
                if n_full < n:          # remainder rows roll into next shard
                    carry = _take_rows(ds, row_order[n_full:])
            if carry is not None and len(carry):
                yield from carry.batches(batch_size, shuffle=False,
                                         max_len=L, truncate=truncate)


def _take_rows(ds: SparseDataset, rows: np.ndarray) -> SparseDataset:
    lens = np.diff(ds.indptr)[rows]
    indptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    # gather the CSR payload of the selected rows in one vectorized fancy
    # index: position j of the output maps to start[row(j)] + (j - out_off)
    starts = ds.indptr[rows].astype(np.int64)
    total = int(indptr[-1])
    flat = (np.arange(total, dtype=np.int64)
            - np.repeat(indptr[:-1], lens)
            + np.repeat(starts, lens)) if total else np.zeros(0, np.int64)
    return SparseDataset(
        ds.indices[flat], indptr, ds.values[flat], ds.labels[rows],
        None if ds.fields is None else ds.fields[flat])


def _concat_datasets(a: SparseDataset, b: SparseDataset) -> SparseDataset:
    fields = None
    if a.fields is not None and b.fields is not None:
        fields = np.concatenate([a.fields, b.fields])
    return SparseDataset(
        np.concatenate([a.indices, b.indices]),
        np.concatenate([a.indptr, b.indptr[1:] + a.indptr[-1]]),
        np.concatenate([a.values, b.values]),
        np.concatenate([a.labels, b.labels]), fields)
