"""Weight arena — packed, mmap-able, multi-precision serving weights.

ROADMAP item 3 ("raw-speed serving"): every PR 7 fleet replica used to
deserialize its OWN copy of the promoted checkpoint bundle — npz decode,
host staging, device placement, optimizer-state ballast — so N replicas
cost N× host RAM and N× reload I/O for weights that serving only ever
READS. The arena is the shard-cache idiom (io/shard_cache.py container:
magic | json header | raw payload, sha256 over the payload, written
tmp → fsync → ``os.replace``) applied to inference weights:

- **publish once**: promotion (serve/promote.py PromotionGate) extracts
  the trainer's *serving tables* — the finalized f32 inference weights,
  NOT the training state — and writes ``<bundle>.npz.arena`` next to the
  bundle, carrying three precision tiers per table (f32, bf16 stored as
  uint16 bit patterns, int8 with a symmetric per-table scale) plus the
  source bundle's leaf digest so a stale or mismatched arena can never
  serve.
- **map everywhere**: every PredictEngine replica ``mmap``s the arena
  read-only instead of loading its own bundle copy. The kernel page
  cache shares the physical pages across processes — fleet-wide weight
  memory is O(1) in the replica count, and a rolling hot reload is a
  remap, not a deserialize (near-instant, no allocation spike).
- **score host-side**: the arena scorers are pure-NumPy twins of the
  jitted bucketed predict kernels (ops/linear.py::linear_margin,
  ops/fm.py::fm_score/ffm_score) operating directly on the mapped
  views. At serve batch shapes (B ≤ 256) the per-call XLA dispatch +
  h2d staging dominates the math by ~2 orders of magnitude on CPU
  hosts, so the gather-dot twins are both the zero-copy path AND the
  raw-speed path. They are numerically equivalent but NOT bit-identical
  to XLA (reduction order differs at the ulp level), which is why the
  engine's default f32 path stays on the trainer's jitted scorer —
  quantization off bit-matches the pre-arena serving path exactly
  (pinned by tests/test_weight_arena.py).

Quantization error contract (docs/PERFORMANCE.md "Weight arena +
quantized scoring"): int8 is symmetric per-table — ``scale =
max|w| / 127``, per-weight absolute error ≤ ``scale / 2``; bf16 keeps
8 mantissa bits — per-weight relative error ≤ 2^-8. Each family's
:func:`score_error_bound` propagates those per-weight errors through
the exact margin polynomial to a per-row MARGIN bound (probabilities
tighten it further: sigmoid is 1/4-Lipschitz). The bound is what the
property tests enforce and what the promotion gate's quantized scoring
leg inherits — an over-error quantized candidate fails the same
logloss/AUC/calibration deltas as any bad model and is quarantined.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from .shard_cache import (CacheInvalid, read_cache_file, write_cache_file)
from .sparse import SparseBatch

__all__ = ["ArenaUnsupported", "WeightArena", "arena_path",
           "publish_arena", "open_arena", "try_open_arena", "quantize_int8",
           "score_error_bound", "factor_score_error_bound",
           "host_rss_bytes", "PRECISIONS"]

ARENA_SUFFIX = ".arena"
ARENA_KIND = "weight_arena"
_FORMAT = 1
PRECISIONS = ("f32", "bf16", "int8")

#: per-weight relative error of a round-to-nearest bf16 cast (8 mantissa
#: bits): |w - bf16(w)| <= |w| * 2^-8
_BF16_REL = 2.0 ** -8

# fused joint-table row-hash constants — MUST stay equal to the jitted
# ffm_row_hash (ops/fm.py) or the arena would gather different rows than
# training wrote
from ..ops.fm import _J1 as _ROWHASH_J1, _J3 as _ROWHASH_J3  # noqa: E402


class ArenaUnsupported(ValueError):
    """The trainer's serving state has no arena mapping (e.g. the FFM
    ``parts`` layout, whose table geometry is kernel-grid-shaped). The
    engine degrades to the bundle path; quantized serving is refused."""


def arena_path(bundle_path: str) -> str:
    """The arena sidecar published next to a checkpoint bundle."""
    return bundle_path + ARENA_SUFFIX


def host_rss_bytes() -> Optional[int]:
    """This process's CURRENT resident set size in bytes (Linux
    /proc/self/statm), or None where unavailable. The serve/fleet obs
    gauge behind the arena's ≥4× fleet-memory claim — devprof's memory
    gauges cover device allocations only, host RSS was unmeasured."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * (os.sysconf("SC_PAGE_SIZE")
                            if hasattr(os, "sysconf") else 4096)
    except (OSError, ValueError, IndexError):
        return None


# --- quantization -----------------------------------------------------------

def quantize_int8(a: np.ndarray) -> Tuple[np.ndarray, float]:
    """Symmetric per-table int8: ``q = rint(a / scale)`` with ``scale =
    max|a| / 127`` (1.0 for an all-zero table so dequant is exact).
    Round-to-nearest ⇒ per-weight absolute error ≤ scale / 2."""
    a = np.asarray(a, np.float32)
    amax = float(np.max(np.abs(a))) if a.size else 0.0
    scale = amax / 127.0 if amax > 0 else 1.0
    q = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
    return q, scale


def _to_bf16_bits(a: np.ndarray) -> np.ndarray:
    """f32 → bf16 bit patterns stored as uint16 (the container has no
    bf16 dtype; ml_dtypes reinterprets the bits on the read side)."""
    import ml_dtypes
    return np.asarray(a, np.float32).astype(ml_dtypes.bfloat16) \
        .view(np.uint16)


def _bf16_bits_to_f32(bits: np.ndarray) -> np.ndarray:
    """bf16 is f32's top half: widen by a 16-bit left shift (measured
    ~5x the ml_dtypes astype on gathered slabs — the hot-path direction
    needs no rounding logic, only the publish-side narrowing does)."""
    return (bits.astype(np.uint32) << np.uint32(16)).view(np.float32)


# --- numpy scorer kernels (host twins of ops/linear.py + ops/fm.py) ---------

def _np_val(batch: SparseBatch) -> np.ndarray:
    v = batch.val
    if v is None:                    # unit-value elision: val == (idx != 0)
        return (np.asarray(batch.idx) != 0).astype(np.float32)
    return np.asarray(v, np.float32)


def _row_hash_np(idx: np.ndarray, Mr: int) -> np.ndarray:
    """NumPy twin of ops.fm.ffm_row_hash — identical uint32 mix (the
    uint64+mask form sidesteps NumPy overflow warnings)."""
    h = (idx.astype(np.uint64) & 0xFFFFFFFF) * _ROWHASH_J1 & 0xFFFFFFFF
    h = h ^ (h >> 15)
    h = (h * _ROWHASH_J3) & 0xFFFFFFFF
    h = h ^ (h >> 13)
    return (h & np.uint64(Mr - 1)).astype(np.int64)


def _linear_margin(gw, batch: SparseBatch) -> np.ndarray:
    val = _np_val(batch)
    return (gw(np.asarray(batch.idx)) * val).sum(axis=-1)


def _fm_margin(w0, gw, gV, batch: SparseBatch) -> np.ndarray:
    idx = np.asarray(batch.idx)
    val = _np_val(batch)
    wi = (gw(idx) * val).sum(-1)
    xv = gV(idx) * val[..., None]               # [B, L, K]
    s = xv.sum(1)
    s2 = (xv ** 2).sum(1)
    return w0 + wi + 0.5 * (s * s - s2).sum(-1)


def _pairwise_ffm_phi(w0, wg, A, val) -> np.ndarray:
    """phi from the gathered pair cube A[b,i,j,k] = V[feature_i][f_j]:
    the exact _ffm_slab_phi sum (upper triangle of A[i,j]·A[j,i])."""
    L = val.shape[1]
    inter = np.einsum("bijk,bjik->bij", A, A)
    xx = val[:, :, None] * val[:, None, :]
    iu = np.triu(np.ones((L, L), np.float32), k=1)
    return w0 + (wg * val).sum(-1) + (inter * xx * iu[None]).sum((1, 2))


def _ffm_joint_margin(w0, gT, Mr, F, K, batch: SparseBatch) -> np.ndarray:
    idx = np.asarray(batch.idx)
    val = _np_val(batch)
    fld = np.asarray(batch.field) % F
    B, L = idx.shape
    slab = gT(_row_hash_np(idx, Mr))            # [B, L, F*K + 1]
    Vg = slab[..., :F * K].reshape(B, L, F, K)
    wg = slab[..., F * K]
    A = Vg[np.arange(B)[:, None, None],
           np.arange(L)[None, :, None], fld[:, None, :], :]
    return _pairwise_ffm_phi(w0, wg, A, val)


def _ffm_dense_margin(w0, gw, gV2, F, batch: SparseBatch) -> np.ndarray:
    idx = np.asarray(batch.idx)
    val = _np_val(batch)
    fld = np.asarray(batch.field) % F
    flat = idx.astype(np.int64)[:, :, None] * F + fld[:, None, :]
    A = gV2(flat)                                # [B, L, L, K]
    return _pairwise_ffm_phi(w0, gw(idx), A, val)


def _sigmoid_exp(phi: np.ndarray) -> np.ndarray:
    """The FM family's historical probability form (models/fm.py
    ``predict``) — mirrored exactly so arena FM probabilities match the
    offline path's float behavior, not sigmoid_np's piecewise form."""
    with np.errstate(over="ignore"):
        return np.asarray(1.0 / (1.0 + np.exp(-np.asarray(phi,
                                                          np.float32))),
                          np.float32)


# --- publish ----------------------------------------------------------------

def publish_arena(bundle_path: str, trainer, *,
                  precisions: Tuple[str, ...] = PRECISIONS) -> str:
    """Extract ``trainer``'s serving tables and write the arena sidecar
    atomically next to ``bundle_path``. The trainer must be the one
    loaded FROM that bundle (the header records the bundle's leaf digest;
    readers refuse a digest mismatch). Returns the arena path. Raises
    :class:`ArenaUnsupported` for trainers/layouts without a serving-
    table mapping."""
    from .checkpoint import bundle_meta
    meta, tables = _serving_tables(trainer)
    bm = bundle_meta(bundle_path)
    scales: Dict[str, float] = {}
    arrays: Dict[str, np.ndarray] = {}
    for prec in precisions:
        if prec not in PRECISIONS:
            raise ValueError(f"unknown arena precision {prec!r}")
    for name, a in tables.items():
        a = np.asarray(a, np.float32)
        if "f32" in precisions:
            arrays[f"{name}/f32"] = a
        if "bf16" in precisions:
            arrays[f"{name}/bf16"] = _to_bf16_bits(a)
        if "int8" in precisions:
            q, scale = quantize_int8(a)
            arrays[f"{name}/int8"] = q
            scales[name] = scale
    header = {
        "kind": ARENA_KIND,
        "arena_format": _FORMAT,
        "precisions": list(precisions),
        "scales": scales,
        "source": {"bundle": os.path.basename(bundle_path),
                   "digest": bm.get("digest"),
                   "step": int(bm.get("t") or 0),
                   "trainer": bm.get("trainer")},
        **meta,
    }
    path = arena_path(bundle_path)
    write_cache_file(path, header, arrays)
    return path


def _serving_tables(trainer) -> Tuple[dict, Dict[str, np.ndarray]]:
    st = getattr(trainer, "serving_tables", None)
    if st is None:
        raise ArenaUnsupported(
            f"{type(trainer).__name__} has no serving_tables() surface")
    return st()


# --- open / score -----------------------------------------------------------

class WeightArena:
    """One validated, mmap-opened arena. ``table views`` are read-only
    ``np.memmap``s over the shared file — gathers copy only the touched
    rows into RAM; the table itself stays in the (cross-process shared)
    page cache."""

    def __init__(self, path: str, header: dict,
                 views: Dict[str, np.ndarray]):
        self.path = path
        self.header = header
        self._views = views
        self.family = str(header.get("family"))
        self.classification = bool(header.get("classification"))
        self.mapped_bytes = int(header.get("payload_bytes") or 0)
        self.step = int((header.get("source") or {}).get("step") or 0)
        self.trainer_name = (header.get("source") or {}).get("trainer")
        self.precisions = tuple(header.get("precisions") or ())
        self._scales = {k: float(v)
                        for k, v in (header.get("scales") or {}).items()}

    # -- validation ----------------------------------------------------------
    def matches_bundle(self, bundle_path: str) -> bool:
        """Does this arena's recorded source digest match the bundle it
        sits next to? A bundle rewritten in place (or an arena copied
        from elsewhere) reads as stale and the engine falls back."""
        from .checkpoint import bundle_meta
        try:
            bm = bundle_meta(bundle_path)
        except (OSError, ValueError, KeyError):
            return False
        src = self.header.get("source") or {}
        return bool(src.get("digest")) and src["digest"] == bm.get("digest")

    # -- gathers -------------------------------------------------------------
    def _view(self, name: str, precision: str) -> np.ndarray:
        key = f"{name}/{precision}"
        v = self._views.get(key)
        if v is None:
            raise KeyError(
                f"arena {self.path} has no {key} tier "
                f"(published precisions: {self.precisions})")
        return v

    def table(self, name: str, precision: str = "f32") -> np.ndarray:
        """The FULL table at a precision tier, as float32 values. The f32
        tier returns the mmap'd view itself (read-only, zero-copy — the
        retrieval plane's full-scan scoring and index builds read pages
        shared with every other replica); quantized tiers dequantize once
        into an owned array (bounded: one table per model version)."""
        if precision == "f32":
            return self._view(name, "f32")
        if precision == "bf16":
            return _bf16_bits_to_f32(np.asarray(self._view(name, "bf16")))
        if precision == "int8":
            return np.asarray(self._view(name, "int8"), np.float32) \
                * np.float32(self._scales.get(name, 1.0))
        raise ValueError(f"unknown precision {precision!r} "
                         f"(one of {PRECISIONS})")

    def gather(self, name: str, precision: str) -> Callable:
        """``fn(index_array) -> float32 gathered values`` at the given
        precision tier — dequantization runs on the gathered slab only
        (O(touched rows)), never on the full table. Indices are clamped
        to the table like XLA's gather (a client-supplied raw integer
        feature id past dims must degrade exactly as the jitted path
        does, never crash a replica)."""
        if precision == "f32":
            tbl = self._view(name, "f32")
            hi = tbl.shape[0] - 1
            return lambda i: np.asarray(tbl[np.clip(i, 0, hi)],
                                        np.float32)
        if precision == "bf16":
            tbl = self._view(name, "bf16")
            hi = tbl.shape[0] - 1
            return lambda i: _bf16_bits_to_f32(
                np.asarray(tbl[np.clip(i, 0, hi)]))
        if precision == "int8":
            tbl = self._view(name, "int8")
            hi = tbl.shape[0] - 1
            scale = np.float32(self._scales.get(name, 1.0))
            return lambda i: np.asarray(tbl[np.clip(i, 0, hi)],
                                        np.float32) * scale
        raise ValueError(f"unknown precision {precision!r} "
                         f"(one of {PRECISIONS})")

    # -- scorers -------------------------------------------------------------
    def margin_fn(self, precision: str = "f32") -> Callable:
        """``fn(SparseBatch) -> float32 [B] margins`` over the mapped
        tables — the numpy twin of the family's jitted predict kernel."""
        w0 = float(self.header.get("w0") or 0.0)
        if self.family == "linear":
            gw = self.gather("w", precision)
            return lambda b: _linear_margin(gw, b)
        if self.family == "fm":
            gw = self.gather("w", precision)
            gV = self.gather("V", precision)
            return lambda b: _fm_margin(w0, gw, gV, b)
        if self.family == "ffm_joint":
            gT = self.gather("T", precision)
            Mr = int(self.header["Mr"])
            F, K = int(self.header["F"]), int(self.header["k"])
            return lambda b: _ffm_joint_margin(w0, gT, Mr, F, K, b)
        if self.family == "ffm_dense":
            gw = self.gather("w", precision)
            gV2 = self.gather("V2", precision)
            F = int(self.header["F"])
            return lambda b: _ffm_dense_margin(w0, gw, gV2, F, b)
        if self.family == "factor":
            raise ArenaUnsupported(
                "factor arenas score (user, item) PAIRS, not SparseBatch "
                "rows — use factor_scorer() / the retrieval plane "
                "(serve.retrieve)")
        raise ArenaUnsupported(f"unknown arena family {self.family!r}")

    def factor_scorer(self, precision: str = "f32") -> Callable:
        """``fn(user_ids, item_ids) -> float32 scores`` for the factor
        family: ``mu + P[u].Q[i] (+ bu[u] + bi[i])`` over the mapped
        tables. Broadcasts like the gathers do — a scalar user against an
        item id array is the retrieval plane's candidate-rescore shape."""
        if self.family != "factor":
            raise ArenaUnsupported(
                f"factor_scorer on family {self.family!r}")
        mu = np.float32(self.header.get("mu") or 0.0)
        gP = self.gather("P", precision)
        gQ = self.gather("Q", precision)
        gbu = self.gather("bu", precision) \
            if self.header.get("user_bias") else None
        gbi = self.gather("bi", precision) \
            if self.header.get("item_bias") else None

        def score(users, items):
            out = mu + (gP(users) * gQ(items)).sum(-1)
            if gbu is not None:
                out = out + gbu(users)
            if gbi is not None:
                out = out + gbi(items)
            return np.asarray(out, np.float32)

        return score

    def scorer(self, precision: str = "f32") -> Callable:
        """Output-space scorer (probabilities for classification) —
        mirrors the family's own margin→probability map so arena scores
        line up with the offline path's float behavior: linear uses the
        shared stable sigmoid (models/base.py sigmoid_np), the FM family
        its historical ``1/(1+exp(-phi))`` form."""
        margin = self.margin_fn(precision)
        if not self.classification:
            return lambda b: np.asarray(margin(b), np.float32)
        if self.family == "linear":
            from ..models.base import sigmoid_np
            return lambda b: np.asarray(
                sigmoid_np(np.asarray(margin(b), np.float32)), np.float32)
        return lambda b: _sigmoid_exp(margin(b))

    # -- error bounds --------------------------------------------------------
    def _weight_err(self, name: str, precision: str) -> Callable:
        """``fn(index_array) -> per-weight absolute error bound`` for the
        tier, evaluated on the gathered slab (bf16's bound is relative,
        so it needs the f32 magnitudes)."""
        trail = tuple(self._view(name, "f32").shape[1:]) \
            if f"{name}/f32" in self._views else ()
        if precision == "f32":
            return lambda i: np.zeros(
                tuple(np.asarray(i).shape) + trail, np.float32)
        if precision == "int8":
            half = np.float32(self._scales.get(name, 1.0) * 0.5)
            return lambda i: np.full(
                tuple(np.asarray(i).shape) + trail, half, np.float32)
        if precision == "bf16":
            gw = self.gather(name, "f32")
            return lambda i: np.abs(gw(i)) * np.float32(_BF16_REL)
        raise ValueError(f"unknown precision {precision!r}")

    def release(self) -> None:
        """Drop the mmap views (GC then unmaps). The engine calls this on
        close so a drained replica's leak census reads clean."""
        self._views = {}


def score_error_bound(arena: WeightArena, precision: str,
                      batch: SparseBatch) -> np.ndarray:
    """Per-row upper bound on |quantized margin − f32 margin| for this
    batch, by propagating the tier's per-weight error through the exact
    margin polynomial (docs/PERFORMANCE.md "Weight arena + quantized
    scoring" derives the algebra; tests/test_weight_arena.py enforces
    it empirically across every (B, L) bucket and family). For
    classification probabilities divide by 4 (sigmoid is 1/4-Lipschitz).
    """
    idx = np.asarray(batch.idx)
    val = np.abs(_np_val(batch))
    fam = arena.family
    if fam == "linear":
        return (arena._weight_err("w", precision)(idx) * val).sum(-1)
    if fam == "fm":
        ew = (arena._weight_err("w", precision)(idx) * val).sum(-1)
        gV = arena.gather("V", "f32")
        eV = arena._weight_err("V", precision)
        # |Δ(0.5 Σ_k s_k² − Σ xv²)|: s_k = Σ_l V_lk x_l with per-element
        # error e_lk|x_l| ⇒ |Δs_k| ≤ εs_k; |Δs_k²| ≤ 2|s_k|εs_k + εs_k²;
        # |Δxv²| ≤ 2|xv|e|x| + (e|x|)²  — triangle inequality throughout
        xv = gV(idx) * _np_val(batch)[..., None]
        exv = eV(idx) * val[..., None]
        s = xv.sum(1)
        es = exv.sum(1)
        d_s2 = (2.0 * np.abs(s) * es + es ** 2).sum(-1)
        d_x2 = (2.0 * np.abs(xv) * exv + exv ** 2).sum((1, 2))
        return ew + 0.5 * (d_s2 + d_x2)
    if fam in ("ffm_joint", "ffm_dense"):
        F = int(arena.header["F"])
        K = int(arena.header["k"])
        fld = np.asarray(batch.field) % F
        B, L = idx.shape
        if fam == "ffm_joint":
            Mr = int(arena.header["Mr"])
            rows = _row_hash_np(idx, Mr)
            slab = arena.gather("T", "f32")(rows)
            eslab = arena._weight_err("T", precision)(rows)
            Vg = slab[..., :F * K].reshape(B, L, F, K)
            eVg = eslab[..., :F * K].reshape(B, L, F, K)
            ew_l = eslab[..., F * K]
            bsel = np.arange(B)[:, None, None]
            lsel = np.arange(L)[None, :, None]
            A = Vg[bsel, lsel, fld[:, None, :], :]
            eA = eVg[bsel, lsel, fld[:, None, :], :]
        else:
            flat = idx.astype(np.int64)[:, :, None] * F + fld[:, None, :]
            A = arena.gather("V2", "f32")(flat)
            eA = arena._weight_err("V2", precision)(flat)
            ew_l = arena._weight_err("w", precision)(idx)
        ew = (ew_l * val).sum(-1)
        # |Δ(A_ij·A_ji)| ≤ Σ_k |A_ij|εA_ji + |A_ji|εA_ij + εA_ij εA_ji
        At = np.swapaxes(np.abs(A), 1, 2)
        eAt = np.swapaxes(eA, 1, 2)
        d_pair = (np.abs(A) * eAt + At * eA + eA * eAt).sum(-1)
        xx = val[:, :, None] * val[:, None, :]
        iu = np.triu(np.ones((L, L), np.float32), k=1)
        return ew + (d_pair * xx * iu[None]).sum((1, 2))
    raise ArenaUnsupported(f"no error bound for family {fam!r}")


def factor_score_error_bound(arena: WeightArena, precision: str,
                             users, items) -> np.ndarray:
    """Per-pair upper bound on |quantized factor score − f32 score| for
    ``score = mu + P[u].Q[i] (+ bu[u] + bi[i])`` — the factor family's
    instance of :func:`score_error_bound`'s derivation, propagating the
    tier's per-weight error through the exact score polynomial:

        |Δ(p.q)| ≤ Σ_k |p_k|εq_k + |q_k|εp_k + εp_k εq_k

    (triangle inequality on (p+εp).(q+εq) − p.q) plus the bias tables'
    per-weight bounds. ``users``/``items`` broadcast like the gathers, so
    a scalar user against a candidate id array yields the candidate-set
    bound the retrieval plane's ranking guardrail needs: an LSH-tier
    top-k over an int8 arena can reorder two items only where their f32
    score gap is below the summed pair bounds."""
    if arena.family != "factor":
        raise ArenaUnsupported(
            f"factor_score_error_bound on family {arena.family!r}")
    u = np.asarray(users)
    i = np.asarray(items)
    pu = arena.gather("P", "f32")(u)
    qi = arena.gather("Q", "f32")(i)
    ep = arena._weight_err("P", precision)(u)
    eq = arena._weight_err("Q", precision)(i)
    bound = (np.abs(pu) * eq + np.abs(qi) * ep + ep * eq).sum(-1)
    if arena.header.get("user_bias"):
        bound = bound + arena._weight_err("bu", precision)(u)
    if arena.header.get("item_bias"):
        bound = bound + arena._weight_err("bi", precision)(i)
    return np.asarray(bound, np.float32)


def open_arena(path: str) -> WeightArena:
    """Open + validate an arena (magic, header, full payload sha256 —
    read_cache_file's contract: a torn or bit-flipped arena can never
    feed a scorer). Raises CacheInvalid / OSError on any failure."""
    header, views = read_cache_file(path)
    if header.get("kind") != ARENA_KIND:
        raise CacheInvalid(f"{path}: not a weight arena "
                           f"(kind={header.get('kind')!r})")
    return WeightArena(path, header, views)


def try_open_arena(bundle_path: str, *, trainer_name: Optional[str] = None,
                   precision: Optional[str] = None
                   ) -> Optional[WeightArena]:
    """Open ``<bundle>.arena`` IFF it is valid FOR THIS BUNDLE, else None.

    The shared open-or-miss step of the serve engine's arena load and the
    bulk scorer's arena backend: a missing, torn, stale (digest mismatch
    after an in-place republish), foreign-trainer, or partial-precision
    sidecar is a MISS — callers route into publish_arena — never an
    exception. A mismatched arena that did open is released before
    returning so the probe itself can never leak an mmap."""
    ap = arena_path(bundle_path)
    if not os.path.exists(ap):
        return None
    try:
        a = open_arena(ap)
    except (ValueError, OSError, KeyError):
        return None
    if a.matches_bundle(bundle_path) \
            and (trainer_name is None or a.trainer_name == trainer_name) \
            and (precision is None or precision in a.precisions):
        return a
    a.release()
    return None
