"""ReplayCache — the NioStatefulSegment analog: spill-to-disk epoch replay.

Reference: hivemall/utils/io/NioStatefulSegment [U] lets a one-pass UDTF run
``-iters > 1`` by recording the row stream to local disk on epoch 1 and
replaying it for epochs 2..N (SURVEY.md §3.20, §4.4). Here the same job is done
with a memory-mapped .npz shard: the first pass over a streaming source
materializes CSR arrays; later epochs re-open the mmap and re-shuffle.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterator, Optional

import numpy as np

from .sparse import SparseDataset

__all__ = ["ReplayCache"]


class ReplayCache:
    def __init__(self, dir: Optional[str] = None):
        self._dir = dir or tempfile.mkdtemp(prefix="hmtpu_replay_")
        self._path: Optional[str] = None

    _ARRAYS = ("indices", "indptr", "values", "labels", "fields")

    def record(self, ds: SparseDataset) -> str:
        """Spill a dataset to disk; returns the shard directory.

        Each CSR array goes to its own .npy file (NOT a zipped .npz — numpy
        silently ignores mmap_mode for .npz, which would defeat the whole
        spill-to-disk point) so replay() can truly memory-map them.
        """
        self._path = os.path.join(self._dir, "shard0")
        os.makedirs(self._path, exist_ok=True)
        for name in self._ARRAYS:
            arr = getattr(ds, name)
            if arr is not None:
                np.save(os.path.join(self._path, name + ".npy"), arr)
        return self._path

    def replay(self) -> SparseDataset:
        """Re-open the spilled shard memory-mapped (read-only)."""
        if self._path is None:
            raise RuntimeError("nothing recorded")

        def load(name):
            p = os.path.join(self._path, name + ".npy")
            return np.load(p, mmap_mode="r") if os.path.exists(p) else None

        return SparseDataset(*(load(n) for n in self._ARRAYS))

    def epochs(self, ds: SparseDataset, iters: int, batch_size: int,
               **kw) -> Iterator:
        """First epoch streams ``ds`` while recording; epochs 2..iters replay."""
        self.record(ds)
        yield from ds.batches(batch_size, epochs=1, **kw)
        if iters > 1:
            replayed = self.replay()
            for ep in range(1, iters):
                kw2 = dict(kw)
                kw2["seed"] = kw.get("seed", 42) + ep
                yield from replayed.batches(batch_size, epochs=1, **kw2)
