"""Device prefetcher — overlap host batch prep with TPU compute.

Reference context (SURVEY.md §8 hard parts): "the input path (hashing +
batching on host) can easily be the bottleneck, not the TPU". The reference
has no analog (Hadoop feeds rows to the UDTF synchronously); on TPU the
host→device link is latency the training step should never wait on. A
worker thread stages upcoming batches with ``jax.device_put`` while the
current step runs, keeping ``depth`` batches in flight — the same
double-buffering idea as the Pallas DMA pipeline, at the input-pipeline
level.

Usage:
    for batch in DevicePrefetcher(ds.batches(bs), depth=2):
        step(params, batch)           # batch arrays already on device

LearnerBase.fit uses this automatically on accelerator backends.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional

import jax

from .sparse import PackedBatch, SparseBatch

__all__ = ["DevicePrefetcher", "stage_batch"]

_STOP = object()


def stage_batch(b, device=None):
    """device_put every array of one batch. ``val=None`` (unit-value
    elision, see SparseBatch) and ``field=None`` are preserved — skipping
    the val transfer is the point: the host->device link is the e2e
    bottleneck (measured ~25 MB/s through the relay here), and the jitted
    unit-val step variants rebuild val from idx on device for free.
    A PackedBatch stages its single uint8 buffer — ONE transfer."""
    put = (lambda a: jax.device_put(a, device)) if device is not None \
        else jax.device_put
    if isinstance(b, PackedBatch):
        return PackedBatch(put(b.buf), b.B, b.L, b.n_valid)
    return SparseBatch(put(b.idx),
                       None if b.val is None else put(b.val),
                       put(b.label),
                       None if b.field is None else put(b.field),
                       b.n_valid, fieldmajor=b.fieldmajor)


class DevicePrefetcher:
    """Iterate ``src`` with up to ``depth`` device-staged batches in flight.

    The worker thread only calls device_put (thread-safe in JAX) and dies
    with the iterator; errors in ``src`` re-raise in the consumer thread.
    """

    def __init__(self, src: Iterable[SparseBatch], depth: int = 2,
                 device=None):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._err: Optional[BaseException] = None
        self._device = device
        self._closed = threading.Event()

        def work():
            try:
                for b in src:
                    staged = stage_batch(b, self._device)
                    while not self._closed.is_set():
                        try:
                            self._q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._closed.is_set():
                        return          # consumer abandoned the stream
            except BaseException as e:          # surfaced on next()
                self._err = e
            finally:
                # the sentinel MUST reach the consumer or __next__ blocks
                # forever; only an explicit close() may abandon delivery
                while not self._closed.is_set():
                    try:
                        self._q.put(_STOP, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Release the worker (called on early exit; safe to call twice)."""
        self._closed.set()
        while True:                     # drain so a blocked put wakes up
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)

    def __iter__(self) -> Iterator[SparseBatch]:
        return self

    def __next__(self) -> SparseBatch:
        while True:
            if self._closed.is_set():       # closed stream ends, never hangs
                raise StopIteration
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                continue
        if item is _STOP:
            self._closed.set()          # further next() calls end immediately
            self._thread.join()
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def __del__(self):
        self._closed.set()
