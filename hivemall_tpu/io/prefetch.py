"""Device prefetcher — overlap host batch prep with TPU compute.

Reference context (SURVEY.md §8 hard parts): "the input path (hashing +
batching on host) can easily be the bottleneck, not the TPU". The reference
has no analog (Hadoop feeds rows to the UDTF synchronously); on TPU the
host→device link is latency the training step should never wait on. A
worker thread stages upcoming batches with ``jax.device_put`` while the
current step runs, keeping ``depth`` batches in flight — the same
double-buffering idea as the Pallas DMA pipeline, at the input-pipeline
level.

Usage:
    for batch in DevicePrefetcher(ds.batches(bs), depth=2):
        step(params, batch)           # batch arrays already on device

LearnerBase.fit uses this automatically on accelerator backends; with
``-ingest_workers > 1`` the source is an :class:`io.pipeline.IngestPipeline`
and the two stages share one :class:`io.pipeline.PipelineStats`.

All queue operations BLOCK (no poll loops): the end of the stream is a
poison pill the worker always delivers, and ``close()`` wakes a worker
blocked on a full queue by draining until the thread exits. The previous
0.1 s timeout-poll put/get loops burned a core and added up to 100 ms
latency per batch at shutdown boundaries.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, Optional

import jax
import numpy as np

from ..obs.trace import get_tracer
from .sparse import MegaBatch, PackedBatch, PackedMegaBatch, SparseBatch

__all__ = ["DevicePrefetcher", "MegabatchStager", "stage_batch"]

_STOP = object()


def stage_batch(b, device=None):
    """Traced wrapper (``h2d.stage`` span) over :func:`_stage_batch` —
    the transfer is the seam the obs rollup attributes h2d time with."""
    with get_tracer().span("h2d.stage"):
        return _stage_batch(b, device)


def _stage_batch(b, device=None):
    """device_put every array of one batch. ``val=None`` (unit-value
    elision, see SparseBatch) and ``field=None`` are preserved — skipping
    the val transfer is the point: the host->device link is the e2e
    bottleneck (measured ~25 MB/s through the relay here), and the jitted
    unit-val step variants rebuild val from idx on device for free.
    A PackedBatch stages its single uint8 buffer — ONE transfer.

    Megabatches (MegaBatch / PackedMegaBatch — K stacked steps, ONE
    transfer) additionally BLOCK until the transfer completes before
    returning: the MegabatchStager upstream reuses its staging buffers
    across windows, and the reuse contract is "the previous window's
    h2d is done by the time the next window stacks" (this staging call
    and the next stack run on the same prefetcher worker thread, so
    blocking here is exactly that barrier). Transfer/compute overlap is
    untouched — the consumer thread keeps running the train step."""
    put = (lambda a: jax.device_put(a, device)) if device is not None \
        else jax.device_put
    if isinstance(b, PackedBatch):
        return PackedBatch(put(b.buf), b.B, b.L, b.n_valid)
    if isinstance(b, PackedMegaBatch):
        staged = PackedMegaBatch(put(b.buf), b.B, b.L, nv=b.nv,
                                 nv_dev=put(b.nv))
        jax.block_until_ready((staged.buf, staged.nv_dev))
        return staged
    if isinstance(b, MegaBatch):
        staged = MegaBatch(put(b.idx),
                           None if b.val is None else put(b.val),
                           put(b.label),
                           None if b.field is None else put(b.field),
                           nv=b.nv, nv_dev=put(b.nv),
                           fieldmajor=b.fieldmajor)
        jax.block_until_ready(
            [a for a in (staged.idx, staged.val, staged.label,
                         staged.field, staged.nv_dev) if a is not None])
        return staged
    return SparseBatch(put(b.idx),
                       None if b.val is None else put(b.val),
                       put(b.label),
                       None if b.field is None else put(b.field),
                       b.n_valid, fieldmajor=b.fieldmajor)


class DevicePrefetcher:
    """Iterate ``src`` with up to ``depth`` device-staged batches in flight.

    The worker thread only calls device_put (thread-safe in JAX) and dies
    with the iterator; errors in ``src`` re-raise in the consumer thread.
    Single-consumer: ``__next__`` and ``close()`` are meant to be called
    from one thread (the pattern every fit loop follows).

    ``stats`` (optional PipelineStats) records the h2d stage: batches
    staged, summed device_put seconds, and the consumer's blocked-on-get
    wait — the three numbers that say whether the wall is transfer-bound.
    """

    def __init__(self, src: Iterable[SparseBatch], depth: int = 2,
                 device=None, stats=None):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._errbox: list = []         # worker's exception, surfaced on next()
        self._closed = threading.Event()
        self._stats = stats

        # the worker closure captures LOCALS only, never self: a closure
        # over self would keep an abandoned prefetcher reachable forever
        # (the thread is a GC root), so __del__ could never fire to
        # release a worker blocked on a full queue
        q, closed, errbox = self._q, self._closed, self._errbox

        def work():
            try:
                for b in src:
                    t0 = time.perf_counter()
                    staged = stage_batch(b, device)
                    if stats is not None:
                        stats.add(stage_seconds=time.perf_counter() - t0,
                                  batches_staged=1)
                    # blocking put: no poll loop. If the consumer abandons
                    # the stream, close() drains the queue until this
                    # thread exits, so a put blocked on a full queue
                    # always wakes.
                    q.put(staged)
                    if closed.is_set():
                        return          # consumer abandoned the stream
            except BaseException as e:          # surfaced on next()
                errbox.append(e)
            finally:
                # the poison pill MUST reach the consumer or __next__
                # blocks forever; a blocked put here is woken by close()'s
                # drain-until-exit loop exactly like the staging put above
                q.put(_STOP)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Release the worker (called on early exit; safe to call twice).
        Drains the queue until the worker exits so a blocked put wakes;
        bounded at 5 s so a device_put hung on the relay can't turn
        close() into a permanent hang (the daemon thread is abandoned)."""
        self._closed.set()
        from .pipeline import drain_until_dead
        drain_until_dead(self._q, self._thread)

    def __iter__(self) -> Iterator[SparseBatch]:
        return self

    def __next__(self) -> SparseBatch:
        if self._closed.is_set():       # closed stream ends, never hangs
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()            # blocking; the pill always arrives
        if self._stats is not None:
            self._stats.add(consume_wait_seconds=time.perf_counter() - t0)
        if item is _STOP:
            self._closed.set()          # further next() calls end immediately
            self._thread.join()
            if self._errbox:
                raise self._errbox[0]
            raise StopIteration
        return item

    def __del__(self):
        # actually release the worker: setting the event alone left a
        # worker blocked on a full queue alive until process exit
        try:
            self.close()
        except BaseException:
            pass


class MegabatchStager:
    """Stack runs of K consecutive same-kind prepared batches into one
    megabatch (io.sparse.MegaBatch / PackedMegaBatch) so the dispatch
    path pays ONE h2d transfer and ONE jitted call per K optimizer steps
    (``-steps_per_dispatch``; ops.scan runs the K steps as a lax.scan).

    Synchronous iterator — no thread of its own; it runs on whichever
    thread consumes it (the DevicePrefetcher worker in the standard
    stack, the train loop when prefetch is off).

    Grouping: a window only ever holds batches of one KIND — same class
    (SparseBatch vs PackedBatch), same array shapes, same val/field
    presence, same fieldmajor flag. Unit-value elision therefore
    survives stacking: an idx-only window stays idx-only (no val array
    is ever materialized for it), and a real-valued batch arriving
    mid-window flushes the window instead of poisoning it. Flushed
    partials — ragged tails (last window < K), kind changes, stream end
    — fall back to the K=1 path one batch at a time, so every batch
    trains in source order exactly once either way.

    Staging-buffer reuse: on accelerator backends the stacked arrays are
    written into a per-kind ring of TWO pinned staging buffer sets. The
    downstream ``stage_batch`` blocks until each megabatch's transfer
    completes (same thread), so when window N stacks, window N-1's
    transfer is done and N-2's buffers are free — no copy races. On the
    CPU backend buffers are freshly allocated instead: device_put there
    may alias host memory, so reuse could corrupt a batch mid-step.

    ``stats`` (PipelineStats) records stack time, megabatches staged and
    singles flushed — the dispatch-overhead decomposition the bench
    reads.

    ``reuse=True`` is only valid when a DevicePrefetcher consumes this
    stager on its worker thread (its ``stage_batch`` provides the
    transfer-complete barrier the ring depends on); callers feeding
    megabatches straight into the train loop (mesh path, prefetch off)
    must leave it False — there device_put/dispatch is async and a
    reused buffer could be rewritten mid-transfer."""

    def __init__(self, src: Iterable, k: int, stats=None,
                 reuse: bool = False):
        if k < 2:
            raise ValueError(f"MegabatchStager needs k >= 2, got {k}")
        self._src = iter(src)
        self._k = int(k)
        self._stats = stats
        if stats is not None:
            stats.steps_per_dispatch = self._k
        self._window: list = []
        self._out: list = []            # flushed items pending emission
        self._done = False
        self._reuse = bool(reuse) and jax.default_backend() != "cpu"
        self._rings: dict = {}          # kind key -> [bufset, bufset]
        self._ring_pos: dict = {}

    # -- kind/grouping -------------------------------------------------------
    @staticmethod
    def _kind(b):
        if isinstance(b, PackedBatch):
            return ("packed", b.B, b.L, int(b.buf.size))
        if isinstance(b, SparseBatch) and isinstance(b.idx, np.ndarray):
            return ("sparse", b.idx.shape, b.val is None,
                    b.field is None, b.fieldmajor)
        return None                     # device-staged/foreign: never stack

    # -- staging-buffer ring -------------------------------------------------
    def _staging(self, key, shapes_dtypes):
        """One SET of stacked staging buffers for ``key`` — a dict of
        np arrays matching (name -> (shape, dtype)). Ring of two on
        accelerators (see class docstring); fresh allocation on CPU."""
        def alloc():
            return {name: np.empty(shape, dtype)
                    for name, (shape, dtype) in shapes_dtypes.items()}
        if not self._reuse:
            return alloc()
        ring = self._rings.get(key)
        if ring is None:
            ring = [None, None]
            self._rings[key] = ring
            self._ring_pos[key] = 0
        pos = self._ring_pos[key]
        self._ring_pos[key] = 1 - pos
        bufs = ring[pos]
        if bufs is None or any(bufs[n].shape != sd[0] or bufs[n].dtype != sd[1]
                               for n, sd in shapes_dtypes.items()):
            bufs = alloc()
            ring[pos] = bufs
        return bufs

    def _stack(self, window):
        with get_tracer().span("stager.stack"):
            return self._stack_inner(window)

    def _stack_inner(self, window):
        t0 = time.perf_counter()
        K = len(window)
        first = window[0]
        nv = np.asarray(
            [(b.n_valid if b.n_valid is not None else b.batch_size)
             for b in window], np.int32)
        if isinstance(first, PackedBatch):
            bufs = self._staging(self._kind(first),
                                 {"buf": ((K, first.buf.size), np.uint8)})
            for i, b in enumerate(window):
                bufs["buf"][i] = b.buf
            out = PackedMegaBatch(bufs["buf"], first.B, first.L, nv=nv)
        else:
            spec = {"idx": ((K,) + first.idx.shape, np.int32),
                    "label": ((K,) + first.label.shape, np.float32)}
            if first.val is not None:
                spec["val"] = ((K,) + first.val.shape, np.float32)
            if first.field is not None:
                spec["field"] = ((K,) + first.field.shape, np.int32)
            bufs = self._staging(self._kind(first), spec)
            for i, b in enumerate(window):
                bufs["idx"][i] = b.idx
                bufs["label"][i] = b.label
                if "val" in bufs:
                    bufs["val"][i] = b.val
                if "field" in bufs:
                    bufs["field"][i] = b.field
            out = MegaBatch(bufs["idx"], bufs.get("val"), bufs["label"],
                            bufs.get("field"), nv=nv,
                            fieldmajor=first.fieldmajor)
        if self._stats is not None:
            self._stats.add(stack_seconds=time.perf_counter() - t0,
                            megabatches_staged=1)
        return out

    def _flush(self, full: bool) -> None:
        """Move the current window to the output queue: stacked when it
        reached K, one-at-a-time (K=1 fallback) otherwise."""
        if not self._window:
            return
        if full:
            self._out.append(self._stack(self._window))
        else:
            self._out.extend(self._window)
            if self._stats is not None:
                self._stats.add(singles_flushed=len(self._window))
        self._window = []

    def __iter__(self):
        return self

    def __next__(self):
        while not self._out:
            if self._done:
                raise StopIteration
            try:
                b = next(self._src)
            except StopIteration:
                self._done = True
                self._flush(full=False)        # ragged tail -> K=1 path
                continue
            kind = self._kind(b)
            if kind is None:
                self._flush(full=False)
                self._out.append(b)
                if self._stats is not None:
                    self._stats.add(singles_flushed=1)
                continue
            if self._window and self._kind(self._window[0]) != kind:
                self._flush(full=False)        # kind change -> K=1 path
            self._window.append(b)
            if len(self._window) >= self._k:
                self._flush(full=True)
        return self._out.pop(0)
