"""Device prefetcher — overlap host batch prep with TPU compute.

Reference context (SURVEY.md §8 hard parts): "the input path (hashing +
batching on host) can easily be the bottleneck, not the TPU". The reference
has no analog (Hadoop feeds rows to the UDTF synchronously); on TPU the
host→device link is latency the training step should never wait on. A
worker thread stages upcoming batches with ``jax.device_put`` while the
current step runs, keeping ``depth`` batches in flight — the same
double-buffering idea as the Pallas DMA pipeline, at the input-pipeline
level.

Usage:
    for batch in DevicePrefetcher(ds.batches(bs), depth=2):
        step(params, batch)           # batch arrays already on device

LearnerBase.fit uses this automatically on accelerator backends; with
``-ingest_workers > 1`` the source is an :class:`io.pipeline.IngestPipeline`
and the two stages share one :class:`io.pipeline.PipelineStats`.

All queue operations BLOCK (no poll loops): the end of the stream is a
poison pill the worker always delivers, and ``close()`` wakes a worker
blocked on a full queue by draining until the thread exits. The previous
0.1 s timeout-poll put/get loops burned a core and added up to 100 ms
latency per batch at shutdown boundaries.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, Optional

import jax

from .sparse import PackedBatch, SparseBatch

__all__ = ["DevicePrefetcher", "stage_batch"]

_STOP = object()


def stage_batch(b, device=None):
    """device_put every array of one batch. ``val=None`` (unit-value
    elision, see SparseBatch) and ``field=None`` are preserved — skipping
    the val transfer is the point: the host->device link is the e2e
    bottleneck (measured ~25 MB/s through the relay here), and the jitted
    unit-val step variants rebuild val from idx on device for free.
    A PackedBatch stages its single uint8 buffer — ONE transfer."""
    put = (lambda a: jax.device_put(a, device)) if device is not None \
        else jax.device_put
    if isinstance(b, PackedBatch):
        return PackedBatch(put(b.buf), b.B, b.L, b.n_valid)
    return SparseBatch(put(b.idx),
                       None if b.val is None else put(b.val),
                       put(b.label),
                       None if b.field is None else put(b.field),
                       b.n_valid, fieldmajor=b.fieldmajor)


class DevicePrefetcher:
    """Iterate ``src`` with up to ``depth`` device-staged batches in flight.

    The worker thread only calls device_put (thread-safe in JAX) and dies
    with the iterator; errors in ``src`` re-raise in the consumer thread.
    Single-consumer: ``__next__`` and ``close()`` are meant to be called
    from one thread (the pattern every fit loop follows).

    ``stats`` (optional PipelineStats) records the h2d stage: batches
    staged, summed device_put seconds, and the consumer's blocked-on-get
    wait — the three numbers that say whether the wall is transfer-bound.
    """

    def __init__(self, src: Iterable[SparseBatch], depth: int = 2,
                 device=None, stats=None):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._errbox: list = []         # worker's exception, surfaced on next()
        self._closed = threading.Event()
        self._stats = stats

        # the worker closure captures LOCALS only, never self: a closure
        # over self would keep an abandoned prefetcher reachable forever
        # (the thread is a GC root), so __del__ could never fire to
        # release a worker blocked on a full queue
        q, closed, errbox = self._q, self._closed, self._errbox

        def work():
            try:
                for b in src:
                    t0 = time.perf_counter()
                    staged = stage_batch(b, device)
                    if stats is not None:
                        stats.add(stage_seconds=time.perf_counter() - t0,
                                  batches_staged=1)
                    # blocking put: no poll loop. If the consumer abandons
                    # the stream, close() drains the queue until this
                    # thread exits, so a put blocked on a full queue
                    # always wakes.
                    q.put(staged)
                    if closed.is_set():
                        return          # consumer abandoned the stream
            except BaseException as e:          # surfaced on next()
                errbox.append(e)
            finally:
                # the poison pill MUST reach the consumer or __next__
                # blocks forever; a blocked put here is woken by close()'s
                # drain-until-exit loop exactly like the staging put above
                q.put(_STOP)

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self) -> None:
        """Release the worker (called on early exit; safe to call twice).
        Drains the queue until the worker exits so a blocked put wakes;
        bounded at 5 s so a device_put hung on the relay can't turn
        close() into a permanent hang (the daemon thread is abandoned)."""
        self._closed.set()
        from .pipeline import drain_until_dead
        drain_until_dead(self._q, self._thread)

    def __iter__(self) -> Iterator[SparseBatch]:
        return self

    def __next__(self) -> SparseBatch:
        if self._closed.is_set():       # closed stream ends, never hangs
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()            # blocking; the pill always arrives
        if self._stats is not None:
            self._stats.add(consume_wait_seconds=time.perf_counter() - t0)
        if item is _STOP:
            self._closed.set()          # further next() calls end immediately
            self._thread.join()
            if self._errbox:
                raise self._errbox[0]
            raise StopIteration
        return item

    def __del__(self):
        # actually release the worker: setting the event alone left a
        # worker blocked on a full queue alive until process exit
        try:
            self.close()
        except BaseException:
            pass
