from .sparse import SparseBatch, SparseDataset, pad_examples  # noqa: F401
from .libsvm import read_libsvm, write_libsvm  # noqa: F401
from .amplify import amplify, rand_amplify  # noqa: F401
from .replay import ReplayCache  # noqa: F401
from .pipeline import IngestPipeline, PipelineStats  # noqa: F401
