"""Columnar sparse-example substrate: padded (idx, val) batches for TPU.

This replaces the reference's per-row ``FeatureValue[]`` parse inside
GenericUDTF.process() (SURVEY.md §4.1 hot path): variable-length feature lists
become fixed-shape ``int32[B, L]`` index / ``float32[B, L]`` value arrays padded
with (idx=0, val=0). Index 0 is reserved — feature ids start at 1 (mhash range
[1, N]) and ``add_bias`` uses a dedicated bias slot — and every kernel scales by
``val``, so zero-valued padding is arithmetically inert in forward and update.

Static shapes are what XLA needs: every batch from one dataset is padded to a
single fixed row length L (the dataset max, or an explicit ``max_len``), so jit
traces exactly one shape per (B, L) configuration.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SparseBatch", "SparseDataset", "MegaBatch", "PackedMegaBatch",
           "canonicalize_fieldmajor", "pad_examples",
           "parse_feature_strings", "split_feature", "pow2_len",
           "bucket_size", "score_batches"]


def pow2_len(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the shared shape bucket."""
    p = 1
    while p < n:
        p <<= 1
    return p


def bucket_size(n: int, lo: int = 1, hi: Optional[int] = None) -> int:
    """Power-of-two shape bucket for ``n``, clamped to ``[lo, hi]``.

    The shared batch-dimension bucketing of the scoring paths (online
    serve engine and offline ``score_batches``): padding every batch up to
    a power-of-two bucket bounds the number of distinct jit shapes at
    log2(hi/lo) + 1 instead of one compile per request/dataset size.
    ``lo`` floors tiny batches into one bucket; ``hi`` caps the bucket at
    the configured batch size (a tail can never out-shape the body: past
    ``hi`` the bucket IS ``hi`` itself — for a non-power-of-two batch
    size that is the body shape, already compiled)."""
    b = pow2_len(max(int(n), int(lo)))
    if hi is not None and b > int(hi):
        b = int(hi)
    return b


def score_batches(ds: "SparseDataset", batch_size: int, *,
                  min_rows: int = 8
                  ) -> Iterator[Tuple[int, "SparseBatch"]]:
    """Shape-BUCKETED scoring batches over ``ds``: ``(start_row, batch)``.

    The offline peer of the serve engine's bucketed predict (both sides
    share :func:`bucket_size`): row length is padded to the power-of-two
    bucket of the dataset max — datasets of nearby widths score through
    ONE compiled kernel instead of recompiling per max_row_len — and the
    ragged tail is padded to its own power-of-two row bucket (>=
    ``min_rows``, <= ``batch_size``) rather than the full batch size, so
    large offline scoring reuses a bounded set of (B, L) compiles and
    never burns a full-batch pad on a short tail. Padding is
    arithmetically inert (idx 0 / val 0), so per-row scores are unchanged;
    ``n_valid`` marks the real rows."""
    n = len(ds)
    if n == 0:
        return
    # shape-bucket telemetry (obs.devprof): first use of a (B, L) bucket
    # is the moment the scoring kernel compiles for it — recorded so the
    # devprof section shows how many distinct compiles bucketing allowed
    from ..obs.devprof import get_devprof
    devprof = get_devprof()
    bs = int(batch_size)
    L = pow2_len(ds.max_row_len)
    full_end = (n // bs) * bs
    if full_end:
        devprof.note_bucket("score_batches", bs, L)
        it = ds.batches(bs, shuffle=False, max_len=L, drop_remainder=True)
        for s, b in zip(range(0, full_end, bs), it):
            yield s, b
    if full_end < n:
        tail = n - full_end
        Bt = bucket_size(tail, lo=min(int(min_rows), bs), hi=bs)
        devprof.note_bucket("score_batches", Bt, L)
        tb = ds.take(np.arange(full_end, n, dtype=np.int64))
        yield full_end, next(tb.batches(Bt, shuffle=False, max_len=L))


def split_feature(f) -> Tuple[str, str]:
    """Split one feature string into (name, value-string).

    Reference semantics (hivemall.model.FeatureValue.parse): a bare
    ``"name"`` means value 1.0; ``"name:val"`` splits on the LAST ':' so
    names containing ':' still parse."""
    name, sep, v = str(f).rpartition(":")
    if not sep:
        return str(f), "1.0"
    return name, v


@dataclass
class SparseBatch:
    """One padded minibatch. ``field`` is present only for FFM-style features.

    ``fieldmajor=True`` marks the canonical FFM layout built by
    :func:`canonicalize_fieldmajor`: slot s holds a feature of field
    ``s % F`` (so no ``field`` array is needed; the jitted step derives the
    pattern statically).

    ``val=None`` is UNIT-VALUE ELISION: every present feature has value
    1.0, i.e. val == (idx != 0) exactly (categorical/CTR data — the Criteo
    case). Consumers that support it rebuild val from idx inside the
    jitted step; the h2d transfer of the val array (a third of batch
    bytes) is skipped entirely."""

    idx: np.ndarray                  # int32 [B, L], 0 = padding
    val: Optional[np.ndarray]        # float32 [B, L]; None = unit values
    label: np.ndarray                # float32 [B]
    field: Optional[np.ndarray] = None  # int32 [B, L], FFM only
    n_valid: Optional[int] = None    # rows < n_valid are real; rest are padding
    fieldmajor: bool = False         # canonical slot->field layout (FFM)

    @property
    def batch_size(self) -> int:
        return int(self.idx.shape[0])

    @property
    def row_mask(self) -> "jnp.ndarray":
        """Valid-row mask as a cached jax DEVICE array (not host numpy —
        callers that need host-side in-place numpy must np.asarray a copy).
        Building it fresh per access made every jitted-step call
        re-transfer 4*B bytes h2d (measured ~5 ms/step for B=32k through
        the ~25 MB/s relay when the same batch is stepped repeatedly). The
        cache also lets jax reuse the device buffer; the value is frozen at
        first access, which is correct because SparseBatch is write-once."""
        m = self.__dict__.get("_row_mask")
        if m is None:
            import jax.numpy as jnp
            b = self.batch_size
            n = b if self.n_valid is None else self.n_valid
            m = jnp.asarray((np.arange(b) < n).astype(np.float32))
            object.__setattr__(self, "_row_mask", m)
        return m


@dataclass
class PackedBatch:
    """One canonical unit-value field-major batch packed into a SINGLE
    uint8 buffer for ONE host->device transfer.

    The e2e flagship wall is the h2d link, which charges per TRANSFER
    (latency) and per BYTE (bandwidth): a SparseBatch moves 2-3 arrays
    (idx int32 + label f32 + row mask) = 2-3 latency hits and 4 bytes per
    index lane. Here idx packs to 3 little-endian bytes per lane (exact
    for dims <= 2^24 — every table size the trainers accept), the f32
    labels ride as raw bytes in the same buffer, and the row mask is
    rebuilt on device from the n_valid scalar. The jitted step unpacks
    with shifts/bitcasts (free against the link). Layout:
    ``buf[:B*L*3]`` = idx lanes, ``buf[B*L*3:]`` = label bytes."""

    buf: np.ndarray                  # uint8 [B*L*3 + B*4]
    B: int
    L: int
    n_valid: Optional[int] = None
    fieldmajor: bool = True

    @property
    def batch_size(self) -> int:
        return self.B


@dataclass
class MegaBatch:
    """K same-shape minibatches stacked on the leading axis for ONE
    host->device transfer and ONE jitted ``lax.scan`` dispatch of all K
    optimizer steps (``-steps_per_dispatch``, ops.scan.make_megastep).

    Built by io.prefetch.MegabatchStager from consecutive same-kind
    SparseBatches: a window never mixes unit-valued (``val=None``) and
    real-valued batches, so unit-value elision survives stacking — an
    idx-only window transfers no val array at all.

    ``nv`` is the per-step valid-row count as a HOST int32 [K] vector
    (the accounting side reads it without a device sync); ``nv_dev`` is
    its staged device copy, set by ``io.prefetch.stage_batch`` so the
    scan body can rebuild each step's row mask on device (4*B fewer
    bytes per step on the link than shipping the float masks)."""

    idx: np.ndarray                  # int32 [K, B, L]
    val: Optional[np.ndarray]        # float32 [K, B, L]; None = unit values
    label: np.ndarray                # float32 [K, B]
    field: Optional[np.ndarray] = None  # int32 [K, B, L], FFM pairs path
    nv: Optional[np.ndarray] = None  # int32 [K] valid rows per step (host)
    nv_dev: Optional[object] = None  # staged device copy of nv
    fieldmajor: bool = False

    @property
    def n_steps(self) -> int:
        return int(self.label.shape[0])

    @property
    def batch_size(self) -> int:
        return int(self.label.shape[1])

    @property
    def n_examples(self) -> int:
        return int(self.nv.sum())


@dataclass
class PackedMegaBatch:
    """K packed unit-value field-major batches (io.sparse.PackedBatch)
    stacked into one uint8 [K, nbytes] buffer — one transfer for K whole
    steps of the flagship packed FFM path."""

    buf: np.ndarray                  # uint8 [K, B*L*3 + B*4]
    B: int
    L: int
    nv: np.ndarray = None            # int32 [K] (host)
    nv_dev: Optional[object] = None

    @property
    def n_steps(self) -> int:
        return int(self.buf.shape[0])

    @property
    def batch_size(self) -> int:
        return self.B

    @property
    def n_examples(self) -> int:
        return int(self.nv.sum())


def pack_unit_fieldmajor(batch: SparseBatch) -> PackedBatch:
    """Pack a canonical unit-value field-major SparseBatch (host arrays)
    into a PackedBatch. Caller guarantees val is None (unit-value elision)
    and idx < 2^24."""
    idx = np.ascontiguousarray(np.asarray(batch.idx, np.int32))
    B, L = idx.shape
    lanes = idx.view(np.uint8).reshape(B, L, 4)[:, :, :3]   # little-endian
    lab = np.ascontiguousarray(np.asarray(batch.label, np.float32))
    buf = np.concatenate([np.ascontiguousarray(lanes).reshape(-1),
                          lab.view(np.uint8)])
    return PackedBatch(buf, B, L, n_valid=batch.n_valid)


def canonicalize_fieldmajor(idx: np.ndarray, val: np.ndarray,
                            fld: np.ndarray, F: int, *,
                            max_m: int = 4):
    """Reorder each row's features into FIELD-MAJOR slots.

    Output slot ``s = rank * F + field`` holds the rank-th feature of that
    field in the row (FFM is order-invariant, so reordering within a row is
    free). The jitted FFM step then derives every slot's field statically
    (``s % F``) — ops.fm._fused_phi_fieldmajor computes the pair
    interaction with no gather/scatter/matmul at all. Criteo-shaped rows
    (exactly one feature per field) canonicalize with m = 1, i.e. to a
    [B, F] batch.

    Fully vectorized (one argsort + cumulative ops — this runs on the e2e
    input path; the C++ twin in native/hivemall_native.cpp takes over when
    built, ~10x, rows OpenMP-parallel). Returns ``(idx2, val2, m)`` with
    arrays [B, m*F] and m a power of two, or ``None`` if some row has more
    than ``max_m`` features in one field (caller falls back to the general
    pair path).

    Field ids fold modulo F — the same normalization FFMTrainer._parse_row
    and every FFM kernel apply, so out-of-range ids keep their features
    instead of silently vanishing."""
    from ..utils.native import canonicalize_fieldmajor_native
    native = canonicalize_fieldmajor_native(idx, val, fld, F, max_m)
    if native is not NotImplemented:
        return native
    B, L = idx.shape
    live = val != 0
    fld = fld % F
    fkey = np.where(live, fld, F)                   # dead slots sort last
    order = np.argsort(fkey, axis=1, kind="stable")
    sf = np.take_along_axis(fkey, order, 1)
    pos = np.arange(L, dtype=np.int64)[None, :]
    # occurrence rank within each row's run of equal fields
    first = np.where((sf != np.roll(sf, 1, axis=1)) | (pos == 0), pos, 0)
    first = np.maximum.accumulate(first, 1)
    rank = pos - first
    alive = sf < F
    if not alive.any():
        return (np.zeros((B, F), np.int32), np.zeros((B, F), np.float32), 1)
    m_needed = int(rank[alive].max()) + 1
    if m_needed > max_m:
        return None
    m = pow2_len(m_needed)
    si = np.take_along_axis(idx, order, 1)
    sv = np.take_along_axis(val, order, 1)
    out_idx = np.zeros((B, m * F), np.int32)
    out_val = np.zeros((B, m * F), np.float32)
    slot = rank * F + sf                            # block-major: field s % F
    rowi = np.broadcast_to(np.arange(B)[:, None], (B, L))
    out_idx[rowi[alive], slot[alive]] = si[alive]
    out_val[rowi[alive], slot[alive]] = sv[alive]
    return out_idx, out_val, int(m)


def parse_feature_strings(features: Sequence[str],
                          *, int_feature: bool = False,
                          num_features: Optional[int] = None,
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse one row of ``"idx:val"`` / ``"idx"`` feature strings.

    Reference semantics: hivemall.model.FeatureValue.parse — a bare ``"idx"``
    means value 1.0 (categorical); ``"idx:val"`` splits on the LAST ':' so that
    string feature names containing ':' still parse (SURVEY.md §3.1).
    """
    idx: List[int] = []
    val: List[float] = []
    from ..utils.hashing import mhash
    for f in features:
        if f is None or f == "":
            continue
        name, v = split_feature(f)
        try:
            i = int(name)
        except ValueError:
            if int_feature:
                raise ValueError(
                    f"-int_feature is set but feature name {name!r} is not an "
                    f"integer index")
            # num_features means the weight-array SIZE (ids < num_features),
            # matching every other call site in the repo that passes dims;
            # mhash's range is [1, n] inclusive, hence the -1
            i = mhash(name) if num_features is None \
                else mhash(name, num_features - 1)
        idx.append(i)
        val.append(float(v))
    return np.asarray(idx, np.int32), np.asarray(val, np.float32)


def pad_examples(rows: Sequence[Tuple[np.ndarray, np.ndarray]],
                 labels: Sequence[float],
                 max_len: Optional[int] = None,
                 fields: Optional[Sequence[np.ndarray]] = None,
                 truncate: bool = False) -> SparseBatch:
    """Pad a list of (idx, val) rows to a rectangular SparseBatch.

    Rows longer than ``max_len`` raise unless ``truncate=True`` is explicit —
    silent feature loss is never the default.
    """
    B = len(rows)
    L = max_len or max((len(r[0]) for r in rows), default=1)
    L = max(L, 1)
    idx = np.zeros((B, L), np.int32)
    val = np.zeros((B, L), np.float32)
    fld = np.zeros((B, L), np.int32) if fields is not None else None
    for b, (i, v) in enumerate(rows):
        if len(i) > L and not truncate:
            raise ValueError(
                f"row {b} has {len(i)} features > max_len={L}; pass "
                f"truncate=True to drop the excess explicitly")
        n = min(len(i), L)
        idx[b, :n] = i[:n]
        val[b, :n] = v[:n]
        if fld is not None:
            fld[b, :n] = fields[b][:n]
    return SparseBatch(idx, val, np.asarray(labels, np.float32), fld, n_valid=B)


class SparseDataset:
    """In-memory sparse dataset with epoch/shuffle/minibatch iteration.

    Plays the role of the engine feeding rows into the UDTF plus the
    NioStatefulSegment replay buffer for ``-iters > 1`` (SURVEY.md §3.20):
    holding the parsed CSR arrays in host RAM, re-shuffling per epoch, and
    emitting fixed-shape padded batches (short final batch is padded up and
    carries ``n_valid`` so loss masks it out).
    """

    def __init__(self, indices: np.ndarray, indptr: np.ndarray,
                 values: np.ndarray, labels: np.ndarray,
                 fields: Optional[np.ndarray] = None):
        self.indices = np.asarray(indices, np.int32)    # flat feature ids
        self.indptr = np.asarray(indptr, np.int64)      # row offsets, len = n+1
        self.values = np.asarray(values, np.float32)
        self.labels = np.asarray(labels, np.float32)
        self.fields = None if fields is None else np.asarray(fields, np.int32)

    @classmethod
    def from_rows(cls, rows: Sequence[Tuple[np.ndarray, np.ndarray]],
                  labels: Sequence[float],
                  fields: Optional[Sequence[np.ndarray]] = None
                  ) -> "SparseDataset":
        indptr = np.zeros(len(rows) + 1, np.int64)
        for i, (ix, _) in enumerate(rows):
            indptr[i + 1] = indptr[i] + len(ix)
        indices = np.concatenate([np.asarray(r[0], np.int32) for r in rows]) \
            if rows else np.zeros(0, np.int32)
        values = np.concatenate([np.asarray(r[1], np.float32) for r in rows]) \
            if rows else np.zeros(0, np.float32)
        flds = None
        if fields is not None:
            flds = np.concatenate([np.asarray(f, np.int32) for f in fields]) \
                if len(fields) else np.zeros(0, np.int32)
        return cls(indices, indptr, values, np.asarray(labels, np.float32), flds)

    def __len__(self) -> int:
        return len(self.labels)

    def content_key(self) -> str:
        """sha256 over the CSR payload — the identity the shard cache keys
        RAM-only datasets by (io.shard_cache; file-backed datasets carry a
        ``source_id`` mtime/size identity from their reader instead).
        Cached after the first call; a SparseDataset is write-once."""
        ck = self.__dict__.get("_content_key")
        if ck is None:
            import hashlib
            h = hashlib.sha256()
            for a in (self.indices, self.indptr, self.values, self.labels,
                      self.fields):
                if a is not None:
                    a = np.ascontiguousarray(a)
                    h.update(f"{a.dtype.str}:{a.shape};".encode())
                    h.update(memoryview(a).cast("B"))
            ck = h.hexdigest()
            self.__dict__["_content_key"] = ck
        return ck

    @property
    def max_row_len(self) -> int:
        if len(self) == 0:
            return 1
        return int(np.max(np.diff(self.indptr)))

    def row(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        s, e = self.indptr[i], self.indptr[i + 1]
        return self.indices[s:e], self.values[s:e]

    def take(self, rows) -> "SparseDataset":
        """Row-subset view materialized as a new dataset (vectorized CSR
        range gather — no per-row Python). Used by train_fm's -adareg
        validation holdout; generally useful for CV splits."""
        rows = np.asarray(rows, np.int64)
        lens = (self.indptr[rows + 1] - self.indptr[rows])
        indptr = np.zeros(len(rows) + 1, np.int64)
        np.cumsum(lens, out=indptr[1:])
        total = int(indptr[-1])
        if total:
            starts = np.repeat(self.indptr[rows], lens)
            offs = np.arange(total, dtype=np.int64) - np.repeat(
                indptr[:-1], lens)
            src = starts + offs
            indices, values = self.indices[src], self.values[src]
            fields = None if self.fields is None else self.fields[src]
        else:
            indices = np.zeros(0, np.int32)
            values = np.zeros(0, np.float32)
            fields = None if self.fields is None else np.zeros(0, np.int32)
        return SparseDataset(indices, indptr, values, self.labels[rows],
                             fields)

    def batches(self, batch_size: int, *, epochs: int = 1, shuffle: bool = False,
                seed: int = 42, max_len: Optional[int] = None,
                drop_remainder: bool = False,
                truncate: bool = False) -> Iterator[SparseBatch]:
        n = len(self)
        L = max(1, max_len or self.max_row_len)
        if max_len is not None and not truncate and self.max_row_len > L:
            raise ValueError(
                f"max_len={L} would drop features from rows up to "
                f"{self.max_row_len} long; pass truncate=True to allow")
        rng = np.random.default_rng(seed)
        lens = np.diff(self.indptr).astype(np.int64)
        for ep in range(epochs):
            order = rng.permutation(n) if shuffle else np.arange(n)
            for s in range(0, n, batch_size):
                take = order[s: s + batch_size]
                nv = len(take)
                if nv < batch_size and drop_remainder:
                    break
                # vectorized padding: flat CSR positions of every kept slot
                # in one fancy index (no per-row Python — the host batch
                # assembly is on the e2e critical path, SURVEY.md §8)
                m = np.minimum(lens[take], L)                 # [nv]
                pos = np.arange(L, dtype=np.int64)[None, :]   # [1, L]
                keep = pos < m[:, None]                       # [nv, L]
                flat = np.where(keep, self.indptr[take][:, None] + pos, 0)
                idx = np.zeros((batch_size, L), np.int32)
                val = np.zeros((batch_size, L), np.float32)
                if len(self.indices):        # all-empty-rows dataset guard
                    idx[:nv] = np.where(keep, self.indices[flat], 0)
                    val[:nv] = np.where(keep, self.values[flat], 0.0)
                fld = None
                if self.fields is not None:
                    fld = np.zeros((batch_size, L), np.int32)
                    if len(self.fields):
                        fld[:nv] = np.where(keep, self.fields[flat], 0)
                lab = np.zeros(batch_size, np.float32)
                lab[:nv] = self.labels[take]
                yield SparseBatch(idx, val, lab, fld,
                                  n_valid=nv if nv < batch_size else None)
