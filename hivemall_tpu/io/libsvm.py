"""LIBSVM reader/writer — the a9a/news20-style ingest path.

Reference analog: the test-resource LIBSVM snippets Hivemall trains on
(SURVEY.md §5 item 2) plus the Hive-side EXPLODE/parse queries. A fast C++
parser in native/ takes over when built; this numpy path is the fallback and
the semantic definition.
"""

from __future__ import annotations

import gzip
from typing import Optional, TextIO, Tuple

import numpy as np

from .sparse import SparseDataset

__all__ = ["read_libsvm", "write_libsvm"]


def _open(path: str, mode: str = "rt"):
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def read_libsvm(path: str, *, zero_based: bool = False,
                binary_labels: bool = True, ffm: bool = False,
                num_fields: int = 64,
                dims: Optional[int] = None) -> SparseDataset:
    """Read a LIBSVM file into a SparseDataset.

    With ``ffm=True``, tokens are libffm-style ``field:index:value``
    triples (the ftvec.trans.ffm_features output format — reference
    FieldAwareFactorizationMachineUDTF input, SURVEY.md §3.6); the returned
    dataset carries per-feature field ids. Non-integer field names hash
    into [0, num_fields) and non-integer feature names into [1, dims-1]
    (or murmur3 default range without ``dims``) — the same normalization
    FFMTrainer._parse_row applies on the streaming path.

    Labels: by default +1/-1 style labels are kept as floats (trainers decide
    their own label convention); indices are shifted +1 if ``zero_based`` so
    id 0 stays the padding/bias slot.
    """
    from .shard_cache import file_source_id
    parse_cfg = {"reader": "libsvm", "zero_based": zero_based, "ffm": ffm,
                 "num_fields": num_fields if ffm else None, "dims": dims}

    def _with_sid(ds: SparseDataset) -> SparseDataset:
        # file identity for the packed shard cache (io.shard_cache):
        # mtime/size staleness discipline + the parse config (the same
        # bytes parsed differently are a different dataset)
        sid = file_source_id(path, parse_cfg)
        if sid:
            ds.source_id = sid
        return ds

    if not ffm:
        try:
            from ..utils.native import parse_libsvm_native
            parsed = parse_libsvm_native(path, zero_based=zero_based)
            if parsed is not None:
                return _with_sid(parsed)
        except ImportError:
            pass
    labels = []
    indices = []
    values = []
    fields = [] if ffm else None
    indptr = [0]
    shift = 1 if zero_based else 0
    if ffm:
        from ..utils.hashing import mhash   # hoisted out of the token loop
    with _open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                if ffm:
                    fs, _, rest = tok.partition(":")
                    i, _, v = rest.partition(":")
                    if not i:
                        raise ValueError(
                            f"FFM token needs field:index[:value]: {tok!r}")
                    try:
                        fi = int(fs)
                    except ValueError:
                        fi = mhash(fs, num_fields) - 1
                    fields.append(fi % num_fields)
                    try:
                        ii = int(i) + shift
                    except ValueError:
                        ii = mhash(i) if dims is None else mhash(i, dims - 1)
                    indices.append(ii)
                else:
                    i, _, v = tok.partition(":")
                    indices.append(int(i) + shift)
                values.append(float(v) if v else 1.0)
            indptr.append(len(indices))
    return _with_sid(SparseDataset(
        np.asarray(indices, np.int32), np.asarray(indptr, np.int64),
        np.asarray(values, np.float32), np.asarray(labels, np.float32),
        None if fields is None else np.asarray(fields, np.int32)))


def write_libsvm(ds: SparseDataset, path: str) -> None:
    with _open(path, "wt") as f:
        for r in range(len(ds)):
            idx, val = ds.row(r)
            feats = " ".join(f"{int(i)}:{float(v):g}" for i, v in zip(idx, val))
            lab = ds.labels[r]
            lab_s = f"{int(lab)}" if float(lab).is_integer() else f"{lab:g}"
            f.write(f"{lab_s} {feats}\n")


def synthetic_classification(n: int, dim: int, *, density: float = 0.1,
                             seed: int = 0, noise: float = 0.1
                             ) -> Tuple[SparseDataset, np.ndarray]:
    """Generate an a9a-like sparse binary classification set (labels ±1).

    Returns (dataset, true_weights) for convergence-smoke tests (SURVEY.md §5:
    "loss decreases; AUC above threshold" rather than exact numbers).
    """
    rng = np.random.default_rng(seed)
    w = rng.normal(0, 1, dim + 1).astype(np.float32)
    w[0] = 0.0  # padding slot never carries weight
    nnz = max(1, int(density * dim))
    indices = np.zeros((n, nnz), np.int64)
    for r in range(n):
        indices[r] = rng.choice(dim, nnz, replace=False) + 1
    values = rng.uniform(0.5, 1.5, (n, nnz)).astype(np.float32)
    margin = (w[indices] * values).sum(1) + rng.normal(0, noise, n)
    labels = np.where(margin > 0, 1.0, -1.0).astype(np.float32)
    indptr = np.arange(0, (n + 1) * nnz, nnz, dtype=np.int64)
    return SparseDataset(indices.ravel().astype(np.int32), indptr,
                         values.ravel(), labels), w
