"""Developer tooling that ships with the package (no runtime imports).

- :mod:`hivemall_tpu.tools.graftcheck` — the project-invariant static
  analyzer gating CI (docs/STATIC_ANALYSIS.md).
"""
