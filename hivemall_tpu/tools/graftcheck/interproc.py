"""graftcheck interprocedural layer — call graph + function summaries.

PR 11's rules were intra-module and syntactic: GC02 lost a tainted clock
value the moment it crossed a function boundary, GC04 only saw attribute
writes lexically inside a thread entry method, GC01 never looked at what
a factory's *caller* does with the product. This module gives the rules
a project-wide view without whole-program dataflow: one cheap pass per
file builds a :class:`FunctionSummary` per ``def`` (what it returns,
which attributes it writes on which parameter, which functions it calls
and under which locks, whether it performs a host transfer), a
name-based call graph links the summaries, and small fixpoint loops
close the transitive facts (returns-tainted, returns-fresh-jit).

Resolution is deliberately best-effort and NAME-BASED (no type
inference): ``self.m()`` resolves inside the enclosing class,
``helper()`` to the module's own top-level def or an imported symbol,
``mod.f()`` through the module's import map. Anything unresolvable —
dynamic dispatch, getattr, builtins, third-party — degrades to
"unknown", never to false certainty: a summary field the analysis
cannot prove stays at its conservative default.

Shared low-level AST helpers used by both this pass and the rule
implementations live here (rules.py imports them) so the two layers
agree on what counts as a jit creation, a lock, a thread constructor.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "FUNCS", "LOOPS", "FunctionSummary", "CallSite", "ModuleInfo",
    "ModuleFacts", "InterProcIndex", "build_index", "extract_module",
    "assemble_index", "dec_name", "is_cache_decorator",
    "is_memo_decorated", "is_jit_name", "is_jit_creation",
    "is_jit_decorator", "is_partial", "is_thread_ctor", "LOCKISH",
    "under_lock", "is_transfer_call", "module_name_of", "call_key",
    "is_acquisition", "donated_positions_of",
]

FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
LOOPS = (ast.For, ast.AsyncFor, ast.While)

LOCKISH = re.compile(r"lock|mutex|cond|\b_?cv\b", re.IGNORECASE)

_CACHE_NAMES = {"lru_cache", "_lru_cache", "cache", "cached"}
_FACTORY_NAMES = {"instrument_factory", "_instrument"}

#: host<->device transfer surface GC07 polices: a fetch forces a device
#: sync; inside a per-step loop it serializes the pipeline per iteration
_TRANSFER_ATTRS = {"block_until_ready", "device_get"}

#: compile-wrapper surface GC09 treats as tracing roots: a function
#: handed to any of these has TRACER parameters, not arrays
_TRACE_WRAPPER_NAMES = {"jit", "pjit", "pmap", "shard_map"}

#: attribute reads on a tracer that yield CONCRETE Python values (static
#: under trace) — they KILL tracer taint
_CONCRETE_ATTRS = {"shape", "dtype", "ndim", "size", "weak_type",
                   "sharding", "aval"}

#: numpy module aliases whose calls force host concretization of a
#: tracer (GC09's np-call hazard; jnp is the traced twin)
_NP_ALIASES = {"np", "numpy"}

#: builtins that concretize a tracer argument (TracerConversionError
#: under jit, silent per-trace recompute otherwise)
_CONCRETIZE_BUILTINS = {"float", "int", "bool", "complex"}

#: method calls that force a device sync + host conversion
_CONCRETIZE_METHODS = {"item", "tolist"}

#: resource-acquiring expressions GC12 polices (kind tags for messages).
#: ``open`` is the builtin; the rest are attribute calls on their module
#: or on a socket object.
_ACQUIRE_NAME_CALLS = {"open": "file"}
_ACQUIRE_ATTR_CALLS = {
    # (base name, attr) -> kind; base None = any base object
    ("socket", "socket"): "socket",
    ("socket", "create_connection"): "socket",
    ("socket", "create_server"): "socket",
    ("socket", "socketpair"): "socket",
    ("mmap", "mmap"): "mmap",
    ("os", "fdopen"): "file",
    (None, "makefile"): "file",
    (None, "accept"): "socket",
    # http-level wrappers that own a socket until .close()
    (None, "HTTPConnection"): "http-conn",
    ("request", "urlopen"): "http-response",
    (None, "urlopen"): "http-response",
}


def is_acquisition(node: ast.AST) -> Optional[str]:
    """Resource kind acquired by this Call expression, or None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        return _ACQUIRE_NAME_CALLS.get(f.id)
    if isinstance(f, ast.Attribute):
        base = f.value.id if isinstance(f.value, ast.Name) else None
        kind = _ACQUIRE_ATTR_CALLS.get((base, f.attr))
        if kind is not None:
            return kind
        return _ACQUIRE_ATTR_CALLS.get((None, f.attr))
    return None


def _int_tuple_literal(node: ast.AST) -> Tuple[int, ...]:
    """(0, 1)-style literal -> ints; anything else -> ()."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return ()
        return tuple(out)
    return ()


def _jit_call_kwargs(node: ast.AST, kw: str) -> Tuple[int, ...]:
    """``donate_argnums``/``static_argnums`` literal of a jit creation:
    ``jax.jit(f, kw=(0,1))``, ``partial(jax.jit, kw=(0,1))(f)`` or the
    same shapes in decorator position."""
    calls: List[ast.Call] = []
    if isinstance(node, ast.Call):
        calls.append(node)
        if isinstance(node.func, ast.Call):
            calls.append(node.func)      # partial(jax.jit, ...)(f)
    for c in calls:
        for k in c.keywords:
            if k.arg == kw:
                got = _int_tuple_literal(k.value)
                if got:
                    return got
    return ()


def donated_positions_of(fn: ast.AST) -> Tuple[int, ...]:
    """donate_argnums positions a def's jit decorator declares, () when
    the def is not donation-jitted (or the literal is not static)."""
    for d in getattr(fn, "decorator_list", []):
        if is_jit_decorator(d):
            got = _jit_call_kwargs(d, "donate_argnums")
            if got:
                return got
    return ()


def dec_name(dec: ast.AST) -> str:
    """The rightmost identifier of a (possibly called) decorator/callee."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return ""


def is_cache_decorator(dec: ast.AST) -> bool:
    return dec_name(dec) in _CACHE_NAMES


def is_memo_decorated(fn: ast.AST) -> bool:
    """lru_cache / instrument_factory on the def: a memoized compile
    factory — jit creations inside it happen once per config key."""
    return any(dec_name(d) in (_CACHE_NAMES | _FACTORY_NAMES)
               for d in getattr(fn, "decorator_list", []))


def is_jit_name(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "jit") or \
        (isinstance(node, ast.Attribute) and node.attr == "jit")


def is_partial(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dec_name(node) in (
        "partial", "_partial")


def is_jit_creation(node: ast.AST) -> bool:
    """A Call producing a jit-compiled callable: ``jax.jit(f)``,
    ``jit(f)``, or ``partial(jax.jit, ...)(f)``."""
    if not isinstance(node, ast.Call):
        return False
    if is_jit_name(node.func):
        return True
    if isinstance(node.func, ast.Call) and is_partial(node.func) \
            and node.func.args and is_jit_name(node.func.args[0]):
        return True
    return False


def is_jit_decorator(dec: ast.AST) -> bool:
    if is_jit_name(dec):
        return True
    if is_partial(dec) and dec.args and is_jit_name(dec.args[0]):
        return True
    if isinstance(dec, ast.Call) and is_jit_name(dec.func):
        return True
    return False


def is_thread_ctor(call: ast.Call) -> bool:
    return dec_name(call) == "Thread"


def is_transfer_call(node: ast.AST) -> bool:
    """``np.asarray(...)``, ``jax.device_get(...)``,
    ``x.block_until_ready()`` — a forced device->host sync."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _TRANSFER_ATTRS:
            return True
        if f.attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in ("np", "numpy"):
            return True
    elif isinstance(f, ast.Name) and f.id in ("device_get",
                                              "block_until_ready"):
        return True
    return False


def under_lock(ctx: Any, node: ast.AST, top: Optional[ast.AST]) -> bool:
    """Is ``node`` lexically inside a ``with <…lock…>:`` block below
    ``top`` (exclusive)? Shared by GC04 and the summary builder so the
    static guard test is one definition. The per-With verdict is
    memoized on the context — this runs for every call site and every
    attribute write, and unparse is the expensive part."""
    memo = getattr(ctx, "_lockish_withs", None)
    if memo is None:
        memo = {}
        ctx._lockish_withs = memo
    for a in ctx.ancestors(node):
        if isinstance(a, ast.With):
            verdict = memo.get(id(a))
            if verdict is None:
                verdict = False
                for item in a.items:
                    try:
                        src = ast.unparse(item.context_expr)
                    except Exception:  # noqa: BLE001 — odd nodes
                        src = ""
                    if LOCKISH.search(src):
                        verdict = True
                        break
                memo[id(a)] = verdict
            if verdict:
                return True
        if a is top:
            break
    return False


def module_name_of(relpath: str) -> str:
    """Dotted module name a scan-root-relative path imports as:
    ``hivemall_tpu/serve/engine.py`` -> ``hivemall_tpu.serve.engine``,
    ``bench.py`` -> ``bench``; packages drop the ``__init__``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

#: a function's identity across the project: (relpath, dotted qualname)
FuncId = Tuple[str, str]


@dataclass
class CallSite:
    """One call expression inside a function body."""
    line: int
    callee: Optional[FuncId]          # resolved target, None = unknown
    under_lock: bool                  # lexically inside `with <lock>:`
    self_arg_positions: Tuple[int, ...] = ()   # positions passing bare
    #                                            `self` (GC04 escape)
    callee_repr: str = ""             # for messages on resolved calls
    #: structural callee key (resolved into ``callee`` once the whole
    #: project's name tables exist — extraction stays per-module pure,
    #: which is what lets the engine fan the summary pass across cores)
    key: Optional[Tuple] = None
    #: positional args carrying param-derived taint: (pos, (param, ...))
    #: — the GC09 propagation edges (a traced value handed to a callee
    #: taints the callee's parameter at that position)
    arg_taints: Tuple[Tuple[int, Tuple[str, ...]], ...] = ()
    #: same for keyword args: (kwarg name, (param, ...))
    kw_taints: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()


@dataclass
class FunctionSummary:
    """What one ``def`` does, as far as name-based analysis can prove.

    Every field defaults to the conservative "nothing proven" value —
    an exotic construct (decorators we don't know, dynamic dispatch,
    lambdas) leaves the default in place rather than inventing facts.
    """
    fid: FuncId
    name: str
    lineno: int
    class_name: Optional[str] = None  # enclosing class, if a method
    is_method: bool = False
    self_name: Optional[str] = None   # first positional arg of a method
    params: Tuple[str, ...] = ()
    memoized: bool = False            # lru_cache/instrument_factory'd
    #: returns an expression derived from time.time() (direct taint)
    returns_wall_direct: bool = False
    #: callees whose return value this function returns (taint/jit chains)
    return_call_targets: List[FuncId] = field(default_factory=list)
    #: returns a FRESH jit closure per call (False when memoized)
    returns_fresh_jit_direct: bool = False
    #: attr writes on `self`: (attr, line, guarded_at_site)
    self_attr_writes: List[Tuple[str, int, bool]] = field(
        default_factory=list)
    #: attr writes on non-self params: param name -> [(attr, line,
    #: guarded_at_site)] — how a cross-module helper mutates an object
    #: the caller passed in
    param_attr_writes: Dict[str, List[Tuple[str, int, bool]]] = field(
        default_factory=dict)
    calls: List[CallSite] = field(default_factory=list)
    #: calls np.asarray/device_get/block_until_ready directly (GC07
    #: follows exactly ONE function boundary, so no transitive closure)
    transfer_direct: bool = False
    has_while_loop: bool = False
    #: `self.<attr>` event names gating a while loop (`while not
    #: self._stop.is_set()` / `.wait(t)`) — GC08 poison-pill evidence
    loop_event_gates: Set[str] = field(default_factory=set)
    # -- v3 facts (GC09-GC12) -----------------------------------------
    #: params traced when this def is jit/pjit/pmap/shard_map-DECORATED
    #: (static_argnums positions excluded) — a GC09 tracing root
    jit_params: Tuple[str, ...] = ()
    #: donate_argnums positions of this def's jit decorator (GC11)
    donated_positions: Tuple[int, ...] = ()
    #: host-concretizing calls on param-derived values: param ->
    #: [(line, kind, repr)] with kind np|cast|item (np is --fix-able)
    param_np_calls: Dict[str, List[Tuple[int, str, str]]] = field(
        default_factory=dict)
    #: Python control flow (if/while/assert truthiness) on a
    #: param-derived value: param -> [line, ...]
    param_branches: Dict[str, List[int]] = field(default_factory=dict)
    #: functions this body hands to jit/pjit/pmap/shard_map — local
    #: nested defs resolve at extraction (fids), module/imported names
    #: resolve later (keys); each with its static_argnums positions
    jit_root_fids: List[Tuple[FuncId, Tuple[int, ...]]] = field(
        default_factory=list)
    jit_root_keys: List[Tuple[Tuple, Tuple[int, ...]]] = field(
        default_factory=list)
    #: functions this body hands to lax.scan as the scan BODY (GC10)
    scan_body_fids: List[FuncId] = field(default_factory=list)
    scan_body_keys: List[Tuple] = field(default_factory=list)
    #: return value is a raw acquired resource (socket/file/mmap kind)
    returns_resource_direct: Optional[str] = None
    #: returns a donate-jitted closure (direct evidence only)
    returns_donated_direct: Tuple[int, ...] = ()
    #: callee keys whose return value this function returns (resolved
    #: into return_call_targets by assemble_index)
    return_call_keys: List[Tuple] = field(default_factory=list)
    # transitive facts, filled by the fixpoint in build_index()
    returns_wall: bool = False
    returns_fresh_jit: bool = False
    returns_resource: Optional[str] = None
    returns_donated: Tuple[int, ...] = ()


@dataclass
class ModuleInfo:
    """Per-module resolution state."""
    relpath: str
    modname: str
    is_package: bool = False             # an __init__.py
    #: local name -> dotted module it stands for (import x.y as z)
    import_modules: Dict[str, str] = field(default_factory=dict)
    #: local name -> (dotted module, symbol)  (from m import f)
    import_symbols: Dict[str, Tuple[str, str]] = field(
        default_factory=dict)
    #: top-level def name -> FuncId
    toplevel: Dict[str, FuncId] = field(default_factory=dict)
    #: class name -> {method name -> FuncId}
    classes: Dict[str, Dict[str, FuncId]] = field(default_factory=dict)


def call_key(call: ast.Call) -> Optional[Tuple]:
    """Picklable structural key of a call's callee expression —
    resolution against the project name tables happens later (and
    possibly in another process), so extraction never needs the index:
    ``("n", f)`` bare name, ``("a", base, attr)`` one-level attribute,
    ``("d", dotted, attr)`` dotted chain, None unresolvable."""
    f = call.func
    if isinstance(f, ast.Name):
        return ("n", f.id)
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            return ("a", v.id, f.attr)
        if isinstance(v, ast.Attribute):
            try:
                dotted = ast.unparse(v)
            except Exception:  # noqa: BLE001 — odd nodes
                return None
            return ("d", dotted, f.attr)
    return None


class InterProcIndex:
    """Project-wide function summaries + name-based resolution."""

    def __init__(self) -> None:
        self.functions: Dict[FuncId, FunctionSummary] = {}
        self.modules: Dict[str, ModuleInfo] = {}      # modname -> info
        self.modules_by_path: Dict[str, ModuleInfo] = {}
        #: (FuncId, param name) pairs provably reachable as TRACED
        #: values from a jit/scan/shard_map root (GC09's worklist
        #: closure over the forwarding edges)
        self.traced: Set[Tuple[FuncId, str]] = set()
        #: functions used as a lax.scan BODY anywhere in the project
        self.scan_bodies: Set[FuncId] = set()

    # -- resolution -----------------------------------------------------
    def resolve_symbol(self, modname: str, symbol: str) \
            -> Optional[FuncId]:
        """``symbol`` as a top-level def of ``modname`` (following one
        from-import hop so re-exports resolve)."""
        mi = self.modules.get(modname)
        if mi is None:
            return None
        fid = mi.toplevel.get(symbol)
        if fid is not None:
            return fid
        hop = mi.import_symbols.get(symbol)
        if hop is not None:
            m2, s2 = hop
            mi2 = self.modules.get(m2)
            if mi2 is not None:
                return mi2.toplevel.get(s2)
        return None

    def resolve_key(self, mi: ModuleInfo, key: Optional[Tuple],
                    class_name: Optional[str],
                    self_name: Optional[str]) -> Optional[FuncId]:
        """Best-effort callee for a :func:`call_key` as seen from a
        function inside class ``class_name`` of module ``mi``."""
        if key is None:
            return None
        tag = key[0]
        if tag == "n":
            fid = mi.toplevel.get(key[1])
            if fid is not None:
                return fid
            hop = mi.import_symbols.get(key[1])
            if hop is not None:
                return self.resolve_symbol(*hop)
            return None
        if tag == "a":
            _, base, attr = key
            if self_name is not None and base == self_name \
                    and class_name is not None:
                methods = mi.classes.get(class_name, {})
                return methods.get(attr)
            target_mod = mi.import_modules.get(base)
            if target_mod is not None:
                return self.resolve_symbol(target_mod, attr)
            hop = mi.import_symbols.get(base)
            if hop is not None:
                # `from pkg import mod` then `mod.f()`
                return self.resolve_symbol(f"{hop[0]}.{hop[1]}", attr)
            return None
        if tag == "d":
            # dotted module chain: x.y.f() under `import x.y` or
            # `import pkg.x as x` — the HEAD name is the local
            # binding; substituting its target module for it yields
            # the absolute dotted module the chain names
            _, dotted, attr = key
            head, _sep, rest = dotted.partition(".")
            if head in mi.import_modules:
                base = mi.import_modules[head]
                mod = f"{base}.{rest}" if rest else base
                return self.resolve_symbol(mod, attr)
            return self.resolve_symbol(dotted, attr)
        return None

    def resolve_call(self, mi: ModuleInfo, call: ast.Call,
                     class_name: Optional[str],
                     self_name: Optional[str]) -> Optional[FuncId]:
        """Best-effort callee of ``call`` as seen from a function inside
        class ``class_name`` of module ``mi``. None = unknown."""
        return self.resolve_key(mi, call_key(call), class_name,
                                self_name)


# ---------------------------------------------------------------------------
# per-module extraction
# ---------------------------------------------------------------------------

def _resolve_relative(modname: str, is_package: bool, level: int,
                      module: Optional[str]) -> Optional[str]:
    """Absolute dotted name of a ``from ...x import y`` target.
    ``is_package`` distinguishes ``a/b/__init__.py`` (where ``from .``
    means ``a.b`` itself) from ``a/b.py`` (where it means ``a``) —
    without it, every re-export in an ``__init__.py`` resolved one
    level too high and package-mediated taint went invisible."""
    if level == 0:
        return module
    parts = modname.split(".")
    if is_package:
        parts = parts + ["__init__"]
    if level > len(parts):
        return None
    base = parts[:len(parts) - level]
    if module:
        base.append(module)
    return ".".join(base) if base else None


def _collect_imports(mi: ModuleInfo, tree: ast.Module) -> None:
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.asname:
                    mi.import_modules[a.asname] = a.name
                else:
                    mi.import_modules[a.name.split(".")[0]] = \
                        a.name.split(".")[0]
                    mi.import_modules.setdefault(a.name, a.name)
        elif isinstance(n, ast.ImportFrom):
            target = _resolve_relative(mi.modname, mi.is_package,
                                       n.level, n.module)
            if target is None:
                continue
            for a in n.names:
                local = a.asname or a.name
                mi.import_symbols[local] = (target, a.name)


def _wall_call(n: ast.AST, bare_time: bool) -> bool:
    if not isinstance(n, ast.Call):
        return False
    f = n.func
    if isinstance(f, ast.Attribute) and f.attr == "time" \
            and isinstance(f.value, ast.Name) and f.value.id == "time":
        return True
    return bare_time and isinstance(f, ast.Name) and f.id == "time"


def _has_bare_time_import(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            if any(a.name == "time" for a in n.names):
                return True
    return False


def _scope_nodes(fn: ast.AST) -> List[ast.AST]:
    """Nodes of ``fn``'s own scope (nested defs/lambdas excluded)."""
    out: List[ast.AST] = []
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, FUNCS + (ast.Lambda,)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _event_gates(fn: ast.AST, self_name: Optional[str]) -> Set[str]:
    """``self.<attr>`` names whose ``.wait()`` / ``.is_set()`` gate a
    while-loop condition — the poison-pill discipline GC08 credits."""
    gates: Set[str] = set()
    if self_name is None:
        return gates
    for n in ast.walk(fn):
        if not isinstance(n, ast.While):
            continue
        for c in ast.walk(n.test):
            if isinstance(c, ast.Call) \
                    and isinstance(c.func, ast.Attribute) \
                    and c.func.attr in ("wait", "is_set"):
                v = c.func.value
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == self_name:
                    gates.add(v.attr)
    return gates


#: builtins whose results are CONCRETE even on tracer args (static
#: under trace) — they kill taint inside branch tests and expressions
_STATIC_BUILTINS = {"len", "isinstance", "callable", "hasattr",
                    "getattr", "type", "id", "repr", "str"}


def _taint_origins(expr: ast.AST, origins: Dict[str, Set[str]],
                   branch: bool = False) -> Set[str]:
    """Root params whose (possibly derived) values feed ``expr``.
    Concrete-under-trace constructs are skipped: ``x.shape``-style
    attribute reads, static builtins, nested function definitions.
    ``branch=True`` additionally skips ``is``/``is not`` comparisons —
    ``if val is None`` branches on static None-ness, not on a tracer."""
    out: Set[str] = set()
    stack = [expr]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and n.attr in _CONCRETE_ATTRS:
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in _STATIC_BUILTINS:
            continue
        if branch and isinstance(n, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops):
            continue
        if isinstance(n, FUNCS + (ast.Lambda,)):
            continue
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out |= origins.get(n.id, set())
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _assign_edges(nodes: List[ast.AST]) \
        -> List[Tuple[List[str], ast.AST]]:
    """(target names, value expr) pairs for taint propagation: plain and
    annotated assignments, augmented assignment, and for-loop bindings
    (an iterable's taint reaches its loop variable)."""
    edges: List[Tuple[List[str], ast.AST]] = []

    def names_of(t: ast.AST) -> List[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            return [x for e in t.elts for x in names_of(e)]
        if isinstance(t, ast.Starred):
            return names_of(t.value)
        return []

    for n in nodes:
        if isinstance(n, ast.Assign):
            tg = [x for t in n.targets for x in names_of(t)]
            if tg:
                edges.append((tg, n.value))
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            tg = names_of(n.target)
            if tg:
                edges.append((tg, n.value))
        elif isinstance(n, ast.AugAssign):
            tg = names_of(n.target)
            if tg:
                edges.append((tg, n.value))
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            tg = names_of(n.target)
            if tg:
                edges.append((tg, n.iter))
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            tg = names_of(n.optional_vars)
            if tg:
                edges.append((tg, n.context_expr))
    return edges


def _propagate_taint(edges, origins: Dict[str, Set[str]]) -> None:
    """Close name-level taint over the assignment edges (flow-insensitive
    fixpoint; scopes are small, 2-3 rounds in practice)."""
    for _ in range(8):
        changed = False
        for targets, value in edges:
            o = _taint_origins(value, origins)
            if not o:
                continue
            for t in targets:
                cur = origins.setdefault(t, set())
                if not o <= cur:
                    cur |= o
                    changed = True
        if not changed:
            return


def _is_trace_wrapper_call(n: ast.Call) -> bool:
    """jit/pjit/pmap/shard_map applied as a CALL: ``jax.jit(f)``,
    ``shard_map(f, ...)``, ``partial(jax.jit, ...)(f)``."""
    if is_jit_creation(n):
        return True
    return dec_name(n) in _TRACE_WRAPPER_NAMES


def _is_scan_call(n: ast.Call) -> bool:
    f = n.func
    if isinstance(f, ast.Attribute) and f.attr == "scan":
        try:
            base = ast.unparse(f.value)
        except Exception:  # noqa: BLE001 — odd nodes
            return False
        return base.endswith("lax")
    return False


def _is_traced_def(fn: ast.AST) -> bool:
    """def decorated with any compile wrapper (jit/pjit/pmap/shard_map,
    bare or through partial) — its params are tracers."""
    for d in getattr(fn, "decorator_list", []):
        if is_jit_decorator(d) or dec_name(d) in _TRACE_WRAPPER_NAMES:
            return True
    return False


def _static_positions_of(fn: ast.AST) -> Tuple[int, ...]:
    for d in getattr(fn, "decorator_list", []):
        if is_jit_decorator(d) or dec_name(d) in _TRACE_WRAPPER_NAMES:
            got = _jit_call_kwargs(d, "static_argnums")
            if got:
                return got
    return ()


def _summarize_function(ctx: Any, mi: ModuleInfo, fn: ast.AST,
                        class_name: Optional[str], direct_method: bool,
                        bare_time: bool) -> FunctionSummary:
    qual = ctx.qualname(fn)
    fid: FuncId = (ctx.relpath, qual)
    args = fn.args
    params = tuple(a.arg for a in
                   list(args.posonlyargs) + list(args.args))
    is_method = direct_method and class_name is not None \
        and bool(params) \
        and not any(dec_name(d) == "staticmethod"
                    for d in fn.decorator_list)
    # a closure nested under a class method captures the literal `self`
    # from its enclosing method — its self.<attr> writes and self.m()
    # calls belong to the class exactly like a method's do
    self_name = params[0] if is_method else (
        "self" if class_name is not None and not direct_method else None)
    s = FunctionSummary(
        fid=fid, name=fn.name, lineno=fn.lineno, class_name=class_name,
        is_method=is_method, self_name=self_name, params=params,
        memoized=is_memo_decorated(fn),
    )

    nodes = _scope_nodes(fn)

    # local taint: names assigned from time.time()-derived expressions,
    # names assigned from fresh jit creations, names assigned from calls
    tainted: Set[str] = set()
    jit_named: Set[str] = set()
    for n in nodes:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(n, ast.Assign):
            targets, value = list(n.targets), n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        if value is None:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            continue
        if any(_wall_call(x, bare_time) for x in ast.walk(value)):
            tainted.update(names)
        if is_jit_creation(value):
            jit_named.update(names)

    def derives_wall(expr: ast.AST) -> bool:
        for x in ast.walk(expr):
            if _wall_call(x, bare_time):
                return True
            if isinstance(x, ast.Name) and x.id in tainted \
                    and isinstance(x.ctx, ast.Load):
                return True
        return False

    # nested @jit defs whose NAME is returned count as fresh-jit returns
    jit_defs = {n.name for n in ast.walk(fn)
                if isinstance(n, FUNCS) and n is not fn
                and any(is_jit_decorator(d) for d in n.decorator_list)}
    # nested defs by name (jit/scan root targets resolve locally: the
    # ops/ factories jit a `def core` defined right inside themselves)
    nested_defs: Dict[str, ast.AST] = {}
    for d in ast.walk(fn):
        if isinstance(d, FUNCS) and d is not fn \
                and d.name not in nested_defs:
            nested_defs[d.name] = d
    donated_named: Dict[str, Tuple[int, ...]] = {}
    donated_defs = {name: donated_positions_of(d)
                    for name, d in nested_defs.items()
                    if donated_positions_of(d)}
    acq_named: Dict[str, str] = {}       # name -> acquired resource kind
    for n in nodes:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            tgt_names = [t.id for t in n.targets
                         if isinstance(t, ast.Name)]
            if not tgt_names:
                continue
            dp = _jit_call_kwargs(n.value, "donate_argnums")
            if is_jit_creation(n.value) and dp:
                for t in tgt_names:
                    donated_named[t] = dp
            kind = is_acquisition(n.value)
            if kind is not None:
                for t in tgt_names:
                    acq_named[t] = kind

    for n in nodes:
        if isinstance(n, ast.Return) and n.value is not None:
            v = n.value
            if derives_wall(v):
                s.returns_wall_direct = True
            if is_jit_creation(v) or (
                    isinstance(v, ast.Name)
                    and (v.id in jit_named or v.id in jit_defs)):
                s.returns_fresh_jit_direct = True
            if not s.returns_donated_direct:
                if isinstance(v, ast.Call):
                    dp = _jit_call_kwargs(v, "donate_argnums")
                    if is_jit_creation(v) and dp:
                        s.returns_donated_direct = dp
                elif isinstance(v, ast.Name):
                    s.returns_donated_direct = donated_named.get(
                        v.id, donated_defs.get(v.id, ()))
            if s.returns_resource_direct is None:
                if isinstance(v, ast.Call):
                    s.returns_resource_direct = is_acquisition(v)
                elif isinstance(v, ast.Name):
                    s.returns_resource_direct = acq_named.get(v.id)

    # return-value call edges (taint/jit/resource chains), by key
    s.return_call_keys = _return_call_keys(nodes)

    # -- v3: tracer-taint origins, hazards, compile roots ---------------
    # local-shadow guard: a bare-Name callee that is a parameter, a
    # locally-assigned name or a nested def must NOT resolve against
    # the module's top-level table (a param named like a module def
    # would misattribute facts to the wrong function)
    edges = _assign_edges(nodes)
    shadowed = set(params) | set(nested_defs)
    for tg, _v in edges:
        shadowed.update(tg)
    origins: Dict[str, Set[str]] = {p: {p} for p in params}
    _propagate_taint(edges, origins)

    if _is_traced_def(fn):
        static = set(_static_positions_of(fn))
        s.jit_params = tuple(p for i, p in enumerate(params)
                             if i not in static)
    s.donated_positions = donated_positions_of(fn)

    def root_target(call: ast.Call):
        """(fid, None) for a local nested def handed to a wrapper,
        (None, key) for a module-level/imported name, (None, None) for
        anything opaque (a param, a local variable, a lambda)."""
        args = call.args
        # partial(jax.jit, ...)(f): the wrapped fn is the OUTER call's arg
        if not args:
            return None, None
        a = args[0]
        if is_jit_name(a) or is_partial(a):
            return None, None            # the partial(jax.jit, ...) form:
        #                                  handled via the outer call
        if isinstance(a, ast.Name):
            d = nested_defs.get(a.id)
            if d is not None:
                return (ctx.relpath, ctx.qualname(d)), None
            if a.id in shadowed:
                return None, None
            return None, ("n", a.id)
        return None, None

    for n in nodes:
        if not isinstance(n, ast.Call):
            continue
        if _is_trace_wrapper_call(n):
            fid, key = root_target(n)
            statics = _jit_call_kwargs(n, "static_argnums")
            if fid is not None:
                s.jit_root_fids.append((fid, statics))
            elif key is not None:
                s.jit_root_keys.append((key, statics))
        elif _is_scan_call(n):
            fid, key = root_target(n)
            if fid is not None:
                s.scan_body_fids.append(fid)
            elif key is not None:
                s.scan_body_keys.append(key)
        f = n.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in _NP_ALIASES:
            o: Set[str] = set()
            for a in list(n.args) + [k.value for k in n.keywords]:
                o |= _taint_origins(a, origins)
            for p in o:
                s.param_np_calls.setdefault(p, []).append(
                    (n.lineno, "np", f"{f.value.id}.{f.attr}"))
        elif isinstance(f, ast.Name) and f.id in _CONCRETIZE_BUILTINS \
                and n.args:
            for p in _taint_origins(n.args[0], origins):
                s.param_np_calls.setdefault(p, []).append(
                    (n.lineno, "cast", f"{f.id}()"))
        elif isinstance(f, ast.Attribute) \
                and f.attr in _CONCRETIZE_METHODS:
            for p in _taint_origins(f.value, origins):
                s.param_np_calls.setdefault(p, []).append(
                    (n.lineno, "item", f".{f.attr}()"))
    for n in nodes:
        test = None
        if isinstance(n, (ast.If, ast.While)):
            test = n.test
        elif isinstance(n, ast.Assert):
            test = n.test
        elif isinstance(n, ast.IfExp):
            test = n.test
        if test is None:
            continue
        for p in _taint_origins(test, origins, branch=True):
            s.param_branches.setdefault(p, []).append(n.lineno)

    # attr writes on self / params, call sites, loops, transfers
    watched = set(params) | ({self_name} if self_name else set())
    for n in nodes:
        tgts: List[ast.Attribute] = []
        if isinstance(n, ast.Assign):
            tgts = [t for t in n.targets if isinstance(t, ast.Attribute)]
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(n.target, ast.Attribute):
            tgts = [n.target]
        for t in tgts:
            if isinstance(t.value, ast.Name) and t.value.id in watched:
                rec = (t.attr, n.lineno, under_lock(ctx, n, fn))
                if t.value.id == self_name:
                    s.self_attr_writes.append(rec)
                else:
                    s.param_attr_writes.setdefault(
                        t.value.id, []).append(rec)
        if isinstance(n, ast.While):
            s.has_while_loop = True
        if is_transfer_call(n):
            s.transfer_direct = True
        if isinstance(n, ast.Call):
            self_pos: Tuple[int, ...] = ()
            if self_name is not None:
                self_pos = tuple(
                    i for i, a in enumerate(n.args)
                    if isinstance(a, ast.Name) and a.id == self_name)
            try:
                crepr = ast.unparse(n.func)
            except Exception:  # noqa: BLE001 — odd nodes
                crepr = dec_name(n)
            key = call_key(n)
            if key is not None and key[0] == "n" \
                    and key[1] in shadowed:
                key = None               # local-shadow guard (above)
            at = tuple((i, tuple(sorted(o)))
                       for i, a in enumerate(n.args)
                       for o in [_taint_origins(a, origins)] if o)
            kt = tuple((k.arg, tuple(sorted(o)))
                       for k in n.keywords if k.arg is not None
                       for o in [_taint_origins(k.value, origins)] if o)
            s.calls.append(CallSite(
                line=n.lineno, callee=None,
                under_lock=under_lock(ctx, n, fn),
                self_arg_positions=self_pos, callee_repr=crepr,
                key=key, arg_taints=at, kw_taints=kt))

    s.loop_event_gates = _event_gates(fn, self_name)
    return s


def _return_call_keys(nodes: List[ast.AST]) -> List[Tuple]:
    """Callee keys whose return value this function returns (directly or
    through one local name) — the taint/jit/resource chain edges,
    resolved by :func:`assemble_index` once the name tables exist."""
    out: List[Tuple] = []
    call_named: Dict[str, ast.Call] = {}
    for n in nodes:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    call_named[t.id] = n.value
    for n in nodes:
        if not (isinstance(n, ast.Return) and n.value is not None):
            continue
        calls: List[ast.Call] = []
        if isinstance(n.value, ast.Call):
            calls.append(n.value)
        elif isinstance(n.value, ast.Name) \
                and n.value.id in call_named:
            calls.append(call_named[n.value.id])
        else:
            # `return now() - t0` style: every call inside the returned
            # expression can carry taint into the return value
            calls.extend(x for x in ast.walk(n.value)
                         if isinstance(x, ast.Call))
        for c in calls:
            key = call_key(c)
            if key is not None:
                out.append(key)
    return out


@dataclass
class ModuleFacts:
    """Everything one module contributes to the project index, extracted
    WITHOUT any cross-module resolution — plain picklable data, so the
    engine can fan this pass across worker processes and ship the facts
    back (call sites carry structural :func:`call_key` keys that
    :func:`assemble_index` resolves once every module's name tables
    exist)."""
    info: ModuleInfo
    summaries: List[FunctionSummary] = field(default_factory=list)
    #: *_STUB const name -> top-level literal keys (GC05 raw material)
    stubs: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    #: alias function name -> *_STUB const it stands for
    stub_aliases: Dict[str, str] = field(default_factory=dict)


def extract_module(ctx: Any) -> ModuleFacts:
    """Pure per-module extraction: import maps, def tables, function
    summaries with UNRESOLVED callee keys. Runs with no project state —
    safe to execute in a worker process."""
    mi = ModuleInfo(ctx.relpath, module_name_of(ctx.relpath),
                    is_package=ctx.relpath.endswith("__init__.py"))
    _collect_imports(mi, ctx.tree)
    for n in ctx.tree.body:
        if isinstance(n, FUNCS):
            mi.toplevel[n.name] = (ctx.relpath, n.name)
        elif isinstance(n, ast.ClassDef):
            methods = {}
            for m in n.body:
                if isinstance(m, FUNCS):
                    methods[m.name] = (ctx.relpath,
                                       f"{n.name}.{m.name}")
            mi.classes[n.name] = methods
    facts = ModuleFacts(info=mi)
    bare = _has_bare_time_import(ctx.tree)
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, FUNCS):
            continue
        # NEAREST enclosing class (nested closures inherit it via
        # the captured `self`); direct methods get param-0 self
        cls = None
        for a in ctx.ancestors(fn):
            if isinstance(a, ast.ClassDef):
                cls = a.name
                break
        direct = isinstance(ctx.parent(fn), ast.ClassDef)
        try:
            facts.summaries.append(
                _summarize_function(ctx, mi, fn, cls, direct, bare))
        except Exception:  # noqa: BLE001 — one intractable function
            pass           # degrades ALONE to "unknown"; the module's
            #                imports, stubs and sibling summaries (GC05's
            #                raw material) must survive it
    # GC05 raw material (rules.collect_project folds these project-wide)
    for n in ctx.tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id.endswith("_STUB") \
                and isinstance(n.value, ast.Dict):
            facts.stubs[n.targets[0].id] = tuple(
                k.value for k in n.value.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str))
        elif isinstance(n, FUNCS):
            refs = {x.id for x in ast.walk(n)
                    if isinstance(x, ast.Name)
                    and x.id.endswith("_STUB")}
            if len(refs) == 1:
                facts.stub_aliases[n.name] = refs.pop()
    return facts


def assemble_index(all_facts: List[Any]) -> InterProcIndex:
    """Resolve every module's structural keys against the now-complete
    project name tables, then run the transitive fixpoints (wall-clock
    taint, fresh-jit, resource, donation) and the traced-parameter
    worklist closure GC09/GC10 consume."""
    idx = InterProcIndex()
    for facts in all_facts:
        idx.modules[facts.info.modname] = facts.info
        idx.modules_by_path[facts.info.relpath] = facts.info
        for s in facts.summaries:
            idx.functions[s.fid] = s
    for facts in all_facts:
        mi = facts.info
        for s in facts.summaries:
            for c in s.calls:
                if c.callee is None and c.key is not None:
                    c.callee = idx.resolve_key(mi, c.key, s.class_name,
                                               s.self_name)
            s.return_call_targets = [
                fid for key in s.return_call_keys
                for fid in (idx.resolve_key(mi, key, s.class_name,
                                            s.self_name),)
                if fid is not None]
            for key, statics in s.jit_root_keys:
                fid = idx.resolve_key(mi, key, s.class_name, s.self_name)
                if fid is not None:
                    s.jit_root_fids.append((fid, statics))
            s.jit_root_keys = []         # resolved — keep idempotent
            for key in s.scan_body_keys:
                fid = idx.resolve_key(mi, key, s.class_name, s.self_name)
                if fid is not None:
                    s.scan_body_fids.append(fid)
            s.scan_body_keys = []
    _fixpoint(idx)
    _close_traced(idx)
    return idx


def build_index(contexts: List[Any]) -> InterProcIndex:
    """Serial convenience: extract every module in-process, then
    assemble (the engine's parallel path runs :func:`extract_module` in
    worker processes and calls :func:`assemble_index` itself)."""
    return assemble_index([extract_module(ctx) for ctx in contexts])


def _close_traced(idx: InterProcIndex) -> None:
    """GC09's worklist closure: (function, param) pairs provably reached
    by TRACED values. Seeds are compile-wrapper surfaces — jit-decorated
    defs, functions handed to jit/pjit/pmap/shard_map (minus their
    static_argnums positions), and lax.scan bodies — and taint flows
    along call edges whose arguments derive from an already-traced
    parameter."""
    traced = idx.traced
    for s in idx.functions.values():
        for p in s.jit_params:
            traced.add((s.fid, p))
        for fid, statics in s.jit_root_fids:
            t = idx.functions.get(fid)
            if t is not None:
                skip = set(statics)
                for i, p in enumerate(t.params):
                    if i not in skip:
                        traced.add((t.fid, p))
        for fid in s.scan_body_fids:
            t = idx.functions.get(fid)
            if t is not None:
                idx.scan_bodies.add(t.fid)
                for p in t.params:
                    traced.add((t.fid, p))
    work = list(traced)
    while work:
        fid, p = work.pop()
        s = idx.functions.get(fid)
        if s is None:
            continue
        for c in s.calls:
            if c.callee is None:
                continue
            t = idx.functions.get(c.callee)
            if t is None:
                continue
            # `self.m(x)`: positional arg 0 lands on params[1] (self
            # occupies slot 0 of the method's parameter tuple)
            off = 1 if (t.is_method and c.key is not None
                        and c.key[0] == "a"
                        and c.key[1] == s.self_name) else 0
            for pos, origins in c.arg_taints:
                if p in origins and pos + off < len(t.params):
                    tp = (t.fid, t.params[pos + off])
                    if tp not in traced:
                        traced.add(tp)
                        work.append(tp)
            for kw, origins in c.kw_taints:
                if p in origins and kw in t.params:
                    tp = (t.fid, kw)
                    if tp not in traced:
                        traced.add(tp)
                        work.append(tp)


def _fixpoint(idx: InterProcIndex) -> None:
    """Close returns_wall / returns_fresh_jit / returns_resource /
    returns_donated over the call graph. Monotone lattices (booleans,
    first-resource-kind-wins, first-donation-tuple-wins) -> terminates."""
    for s in idx.functions.values():
        s.returns_wall = s.returns_wall_direct
        # a memoized factory hands back the SAME closure per config key:
        # calling it per step is a cache hit, not a fresh compile
        s.returns_fresh_jit = s.returns_fresh_jit_direct \
            and not s.memoized
        s.returns_resource = s.returns_resource_direct
        # donation is a property of the returned callable's SIGNATURE —
        # a memoized factory still hands back a donating callable, so
        # (unlike fresh-jit) memoization does not clear the fact
        s.returns_donated = s.returns_donated_direct
    changed = True
    while changed:
        changed = False
        for s in idx.functions.values():
            if not s.returns_wall:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_wall:
                        s.returns_wall = True
                        changed = True
                        break
            if not s.returns_fresh_jit and not s.memoized:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_fresh_jit:
                        s.returns_fresh_jit = True
                        changed = True
                        break
            if s.returns_resource is None:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_resource:
                        s.returns_resource = ts.returns_resource
                        changed = True
                        break
            if not s.returns_donated:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_donated:
                        s.returns_donated = ts.returns_donated
                        changed = True
                        break


# ---------------------------------------------------------------------------
# GC04 helper: transitive attr-write collection from a thread entry
# ---------------------------------------------------------------------------

def collect_entry_writes(idx: InterProcIndex, ctx: Any,
                         entry_fid: FuncId, max_depth: int = 4) \
        -> List[Tuple[str, int, bool, str]]:
    """Every ``self.<attr>`` write reachable from thread entry point
    ``entry_fid`` by following method calls on self (and helper calls
    that receive self as an argument), with the lock context each call
    edge carries: a write is *guarded* when its own site sits under a
    ``with <lock>:`` OR every call edge leading to it held a lock.

    Returns ``(attr, report_line, guarded, via)`` where ``report_line``
    is always a line in the ENTRY's module (cross-module writes are
    reported at the call site that reaches them) and ``via`` names the
    callee chain for the finding message ("" for direct writes).
    """
    out: List[Tuple[str, int, bool, str]] = []
    seen: Set[Tuple[FuncId, bool]] = set()

    def visit(fid: FuncId, lock_held: bool, depth: int,
              report_line: Optional[int], via: str) -> None:
        if depth > max_depth or (fid, lock_held) in seen:
            return
        seen.add((fid, lock_held))
        s = idx.functions.get(fid)
        if s is None:
            return
        for attr, line, guarded in s.self_attr_writes:
            out.append((attr, report_line if report_line is not None
                        else line, guarded or lock_held, via))
        for c in s.calls:
            if c.callee is None:
                continue
            t = idx.functions.get(c.callee)
            if t is None:
                continue
            edge_locked = lock_held or c.under_lock
            nxt_via = c.callee_repr if not via \
                else f"{via} -> {c.callee_repr}"
            # same-class method on self: follow with the callee's own
            # line numbers when it lives in the same module (precise
            # report), else pin the report to this call site
            same_module = c.callee[0] == fid[0]
            rl = report_line if report_line is not None else (
                None if same_module else c.line)
            if t.is_method and t.class_name == s.class_name \
                    and same_module:
                visit(c.callee, edge_locked, depth + 1, rl, nxt_via)
            elif t.param_attr_writes or t.calls:
                # helper receiving self positionally: its writes to that
                # param are writes to our object
                for pos in c.self_arg_positions:
                    if pos < len(t.params):
                        pname = t.params[pos]
                        for attr, line, guarded in \
                                t.param_attr_writes.get(pname, []):
                            out.append((
                                attr,
                                report_line if report_line is not None
                                else c.line,
                                guarded or edge_locked, nxt_via))

    visit(entry_fid, False, 0, None, "")
    return out
