"""graftcheck interprocedural layer — call graph + function summaries.

PR 11's rules were intra-module and syntactic: GC02 lost a tainted clock
value the moment it crossed a function boundary, GC04 only saw attribute
writes lexically inside a thread entry method, GC01 never looked at what
a factory's *caller* does with the product. This module gives the rules
a project-wide view without whole-program dataflow: one cheap pass per
file builds a :class:`FunctionSummary` per ``def`` (what it returns,
which attributes it writes on which parameter, which functions it calls
and under which locks, whether it performs a host transfer), a
name-based call graph links the summaries, and small fixpoint loops
close the transitive facts (returns-tainted, returns-fresh-jit).

Resolution is deliberately best-effort and NAME-BASED (no type
inference): ``self.m()`` resolves inside the enclosing class,
``helper()`` to the module's own top-level def or an imported symbol,
``mod.f()`` through the module's import map. Anything unresolvable —
dynamic dispatch, getattr, builtins, third-party — degrades to
"unknown", never to false certainty: a summary field the analysis
cannot prove stays at its conservative default.

Shared low-level AST helpers used by both this pass and the rule
implementations live here (rules.py imports them) so the two layers
agree on what counts as a jit creation, a lock, a thread constructor.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "FUNCS", "LOOPS", "FunctionSummary", "CallSite", "ModuleInfo",
    "InterProcIndex", "build_index", "dec_name", "is_cache_decorator",
    "is_memo_decorated", "is_jit_name", "is_jit_creation",
    "is_jit_decorator", "is_partial", "is_thread_ctor", "LOCKISH",
    "under_lock", "is_transfer_call", "module_name_of",
]

FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
LOOPS = (ast.For, ast.AsyncFor, ast.While)

LOCKISH = re.compile(r"lock|mutex|cond|\b_?cv\b", re.IGNORECASE)

_CACHE_NAMES = {"lru_cache", "_lru_cache", "cache", "cached"}
_FACTORY_NAMES = {"instrument_factory", "_instrument"}

#: host<->device transfer surface GC07 polices: a fetch forces a device
#: sync; inside a per-step loop it serializes the pipeline per iteration
_TRANSFER_ATTRS = {"block_until_ready", "device_get"}


def dec_name(dec: ast.AST) -> str:
    """The rightmost identifier of a (possibly called) decorator/callee."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return ""


def is_cache_decorator(dec: ast.AST) -> bool:
    return dec_name(dec) in _CACHE_NAMES


def is_memo_decorated(fn: ast.AST) -> bool:
    """lru_cache / instrument_factory on the def: a memoized compile
    factory — jit creations inside it happen once per config key."""
    return any(dec_name(d) in (_CACHE_NAMES | _FACTORY_NAMES)
               for d in getattr(fn, "decorator_list", []))


def is_jit_name(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "jit") or \
        (isinstance(node, ast.Attribute) and node.attr == "jit")


def is_partial(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dec_name(node) in (
        "partial", "_partial")


def is_jit_creation(node: ast.AST) -> bool:
    """A Call producing a jit-compiled callable: ``jax.jit(f)``,
    ``jit(f)``, or ``partial(jax.jit, ...)(f)``."""
    if not isinstance(node, ast.Call):
        return False
    if is_jit_name(node.func):
        return True
    if isinstance(node.func, ast.Call) and is_partial(node.func) \
            and node.func.args and is_jit_name(node.func.args[0]):
        return True
    return False


def is_jit_decorator(dec: ast.AST) -> bool:
    if is_jit_name(dec):
        return True
    if is_partial(dec) and dec.args and is_jit_name(dec.args[0]):
        return True
    if isinstance(dec, ast.Call) and is_jit_name(dec.func):
        return True
    return False


def is_thread_ctor(call: ast.Call) -> bool:
    return dec_name(call) == "Thread"


def is_transfer_call(node: ast.AST) -> bool:
    """``np.asarray(...)``, ``jax.device_get(...)``,
    ``x.block_until_ready()`` — a forced device->host sync."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in _TRANSFER_ATTRS:
            return True
        if f.attr == "asarray" and isinstance(f.value, ast.Name) \
                and f.value.id in ("np", "numpy"):
            return True
    elif isinstance(f, ast.Name) and f.id in ("device_get",
                                              "block_until_ready"):
        return True
    return False


def under_lock(ctx: Any, node: ast.AST, top: Optional[ast.AST]) -> bool:
    """Is ``node`` lexically inside a ``with <…lock…>:`` block below
    ``top`` (exclusive)? Shared by GC04 and the summary builder so the
    static guard test is one definition. The per-With verdict is
    memoized on the context — this runs for every call site and every
    attribute write, and unparse is the expensive part."""
    memo = getattr(ctx, "_lockish_withs", None)
    if memo is None:
        memo = {}
        ctx._lockish_withs = memo
    for a in ctx.ancestors(node):
        if isinstance(a, ast.With):
            verdict = memo.get(id(a))
            if verdict is None:
                verdict = False
                for item in a.items:
                    try:
                        src = ast.unparse(item.context_expr)
                    except Exception:  # noqa: BLE001 — odd nodes
                        src = ""
                    if LOCKISH.search(src):
                        verdict = True
                        break
                memo[id(a)] = verdict
            if verdict:
                return True
        if a is top:
            break
    return False


def module_name_of(relpath: str) -> str:
    """Dotted module name a scan-root-relative path imports as:
    ``hivemall_tpu/serve/engine.py`` -> ``hivemall_tpu.serve.engine``,
    ``bench.py`` -> ``bench``; packages drop the ``__init__``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

#: a function's identity across the project: (relpath, dotted qualname)
FuncId = Tuple[str, str]


@dataclass
class CallSite:
    """One call expression inside a function body."""
    line: int
    callee: Optional[FuncId]          # resolved target, None = unknown
    under_lock: bool                  # lexically inside `with <lock>:`
    self_arg_positions: Tuple[int, ...] = ()   # positions passing bare
    #                                            `self` (GC04 escape)
    callee_repr: str = ""             # for messages on resolved calls


@dataclass
class FunctionSummary:
    """What one ``def`` does, as far as name-based analysis can prove.

    Every field defaults to the conservative "nothing proven" value —
    an exotic construct (decorators we don't know, dynamic dispatch,
    lambdas) leaves the default in place rather than inventing facts.
    """
    fid: FuncId
    name: str
    lineno: int
    class_name: Optional[str] = None  # enclosing class, if a method
    is_method: bool = False
    self_name: Optional[str] = None   # first positional arg of a method
    params: Tuple[str, ...] = ()
    memoized: bool = False            # lru_cache/instrument_factory'd
    #: returns an expression derived from time.time() (direct taint)
    returns_wall_direct: bool = False
    #: callees whose return value this function returns (taint/jit chains)
    return_call_targets: List[FuncId] = field(default_factory=list)
    #: returns a FRESH jit closure per call (False when memoized)
    returns_fresh_jit_direct: bool = False
    #: attr writes on `self`: (attr, line, guarded_at_site)
    self_attr_writes: List[Tuple[str, int, bool]] = field(
        default_factory=list)
    #: attr writes on non-self params: param name -> [(attr, line,
    #: guarded_at_site)] — how a cross-module helper mutates an object
    #: the caller passed in
    param_attr_writes: Dict[str, List[Tuple[str, int, bool]]] = field(
        default_factory=dict)
    calls: List[CallSite] = field(default_factory=list)
    #: calls np.asarray/device_get/block_until_ready directly (GC07
    #: follows exactly ONE function boundary, so no transitive closure)
    transfer_direct: bool = False
    has_while_loop: bool = False
    #: `self.<attr>` event names gating a while loop (`while not
    #: self._stop.is_set()` / `.wait(t)`) — GC08 poison-pill evidence
    loop_event_gates: Set[str] = field(default_factory=set)
    # transitive facts, filled by the fixpoint in build_index()
    returns_wall: bool = False
    returns_fresh_jit: bool = False


@dataclass
class ModuleInfo:
    """Per-module resolution state."""
    relpath: str
    modname: str
    is_package: bool = False             # an __init__.py
    #: local name -> dotted module it stands for (import x.y as z)
    import_modules: Dict[str, str] = field(default_factory=dict)
    #: local name -> (dotted module, symbol)  (from m import f)
    import_symbols: Dict[str, Tuple[str, str]] = field(
        default_factory=dict)
    #: top-level def name -> FuncId
    toplevel: Dict[str, FuncId] = field(default_factory=dict)
    #: class name -> {method name -> FuncId}
    classes: Dict[str, Dict[str, FuncId]] = field(default_factory=dict)


class InterProcIndex:
    """Project-wide function summaries + name-based resolution."""

    def __init__(self) -> None:
        self.functions: Dict[FuncId, FunctionSummary] = {}
        self.modules: Dict[str, ModuleInfo] = {}      # modname -> info
        self.modules_by_path: Dict[str, ModuleInfo] = {}

    # -- resolution -----------------------------------------------------
    def resolve_symbol(self, modname: str, symbol: str) \
            -> Optional[FuncId]:
        """``symbol`` as a top-level def of ``modname`` (following one
        from-import hop so re-exports resolve)."""
        mi = self.modules.get(modname)
        if mi is None:
            return None
        fid = mi.toplevel.get(symbol)
        if fid is not None:
            return fid
        hop = mi.import_symbols.get(symbol)
        if hop is not None:
            m2, s2 = hop
            mi2 = self.modules.get(m2)
            if mi2 is not None:
                return mi2.toplevel.get(s2)
        return None

    def resolve_call(self, mi: ModuleInfo, call: ast.Call,
                     class_name: Optional[str],
                     self_name: Optional[str]) -> Optional[FuncId]:
        """Best-effort callee of ``call`` as seen from a function inside
        class ``class_name`` of module ``mi``. None = unknown."""
        f = call.func
        if isinstance(f, ast.Name):
            fid = mi.toplevel.get(f.id)
            if fid is not None:
                return fid
            hop = mi.import_symbols.get(f.id)
            if hop is not None:
                return self.resolve_symbol(*hop)
            return None
        if isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name):
                if self_name is not None and v.id == self_name \
                        and class_name is not None:
                    methods = mi.classes.get(class_name, {})
                    return methods.get(f.attr)
                target_mod = mi.import_modules.get(v.id)
                if target_mod is not None:
                    return self.resolve_symbol(target_mod, f.attr)
                hop = mi.import_symbols.get(v.id)
                if hop is not None:
                    # `from pkg import mod` then `mod.f()`
                    return self.resolve_symbol(
                        f"{hop[0]}.{hop[1]}", f.attr)
            elif isinstance(v, ast.Attribute):
                # dotted module chain: x.y.f() under `import x.y` or
                # `import pkg.x as x` — the HEAD name is the local
                # binding; substituting its target module for it yields
                # the absolute dotted module the chain names
                try:
                    dotted = ast.unparse(v)
                except Exception:  # noqa: BLE001 — odd nodes
                    return None
                head, _, rest = dotted.partition(".")
                if head in mi.import_modules:
                    base = mi.import_modules[head]
                    mod = f"{base}.{rest}" if rest else base
                    return self.resolve_symbol(mod, f.attr)
                return self.resolve_symbol(dotted, f.attr)
        return None


# ---------------------------------------------------------------------------
# per-module extraction
# ---------------------------------------------------------------------------

def _resolve_relative(modname: str, is_package: bool, level: int,
                      module: Optional[str]) -> Optional[str]:
    """Absolute dotted name of a ``from ...x import y`` target.
    ``is_package`` distinguishes ``a/b/__init__.py`` (where ``from .``
    means ``a.b`` itself) from ``a/b.py`` (where it means ``a``) —
    without it, every re-export in an ``__init__.py`` resolved one
    level too high and package-mediated taint went invisible."""
    if level == 0:
        return module
    parts = modname.split(".")
    if is_package:
        parts = parts + ["__init__"]
    if level > len(parts):
        return None
    base = parts[:len(parts) - level]
    if module:
        base.append(module)
    return ".".join(base) if base else None


def _collect_imports(mi: ModuleInfo, tree: ast.Module) -> None:
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.asname:
                    mi.import_modules[a.asname] = a.name
                else:
                    mi.import_modules[a.name.split(".")[0]] = \
                        a.name.split(".")[0]
                    mi.import_modules.setdefault(a.name, a.name)
        elif isinstance(n, ast.ImportFrom):
            target = _resolve_relative(mi.modname, mi.is_package,
                                       n.level, n.module)
            if target is None:
                continue
            for a in n.names:
                local = a.asname or a.name
                mi.import_symbols[local] = (target, a.name)


def _wall_call(n: ast.AST, bare_time: bool) -> bool:
    if not isinstance(n, ast.Call):
        return False
    f = n.func
    if isinstance(f, ast.Attribute) and f.attr == "time" \
            and isinstance(f.value, ast.Name) and f.value.id == "time":
        return True
    return bare_time and isinstance(f, ast.Name) and f.id == "time"


def _has_bare_time_import(tree: ast.Module) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module == "time":
            if any(a.name == "time" for a in n.names):
                return True
    return False


def _scope_nodes(fn: ast.AST) -> List[ast.AST]:
    """Nodes of ``fn``'s own scope (nested defs/lambdas excluded)."""
    out: List[ast.AST] = []
    stack = list(fn.body)
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, FUNCS + (ast.Lambda,)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _event_gates(fn: ast.AST, self_name: Optional[str]) -> Set[str]:
    """``self.<attr>`` names whose ``.wait()`` / ``.is_set()`` gate a
    while-loop condition — the poison-pill discipline GC08 credits."""
    gates: Set[str] = set()
    if self_name is None:
        return gates
    for n in ast.walk(fn):
        if not isinstance(n, ast.While):
            continue
        for c in ast.walk(n.test):
            if isinstance(c, ast.Call) \
                    and isinstance(c.func, ast.Attribute) \
                    and c.func.attr in ("wait", "is_set"):
                v = c.func.value
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == self_name:
                    gates.add(v.attr)
    return gates


def _summarize_function(ctx: Any, mi: ModuleInfo, fn: ast.AST,
                        class_name: Optional[str], direct_method: bool,
                        bare_time: bool, resolver) -> FunctionSummary:
    qual = ctx.qualname(fn)
    fid: FuncId = (ctx.relpath, qual)
    args = fn.args
    params = tuple(a.arg for a in
                   list(args.posonlyargs) + list(args.args))
    is_method = direct_method and class_name is not None \
        and bool(params) \
        and not any(dec_name(d) == "staticmethod"
                    for d in fn.decorator_list)
    # a closure nested under a class method captures the literal `self`
    # from its enclosing method — its self.<attr> writes and self.m()
    # calls belong to the class exactly like a method's do
    self_name = params[0] if is_method else (
        "self" if class_name is not None and not direct_method else None)
    s = FunctionSummary(
        fid=fid, name=fn.name, lineno=fn.lineno, class_name=class_name,
        is_method=is_method, self_name=self_name, params=params,
        memoized=is_memo_decorated(fn),
    )

    nodes = _scope_nodes(fn)

    # local taint: names assigned from time.time()-derived expressions,
    # names assigned from fresh jit creations, names assigned from calls
    tainted: Set[str] = set()
    jit_named: Set[str] = set()
    for n in nodes:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(n, ast.Assign):
            targets, value = list(n.targets), n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        if value is None:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names:
            continue
        if any(_wall_call(x, bare_time) for x in ast.walk(value)):
            tainted.update(names)
        if is_jit_creation(value):
            jit_named.update(names)

    def derives_wall(expr: ast.AST) -> bool:
        for x in ast.walk(expr):
            if _wall_call(x, bare_time):
                return True
            if isinstance(x, ast.Name) and x.id in tainted \
                    and isinstance(x.ctx, ast.Load):
                return True
        return False

    # nested @jit defs whose NAME is returned count as fresh-jit returns
    jit_defs = {n.name for n in ast.walk(fn)
                if isinstance(n, FUNCS) and n is not fn
                and any(is_jit_decorator(d) for d in n.decorator_list)}

    for n in nodes:
        if isinstance(n, ast.Return) and n.value is not None:
            v = n.value
            if derives_wall(v):
                s.returns_wall_direct = True
            if is_jit_creation(v) or (
                    isinstance(v, ast.Name)
                    and (v.id in jit_named or v.id in jit_defs)):
                s.returns_fresh_jit_direct = True
    # return_call_targets are resolved by the caller (_return_targets)
    # once the whole module table exists

    # attr writes on self / params, call sites, loops, transfers
    watched = set(params) | ({self_name} if self_name else set())
    for n in nodes:
        tgts: List[ast.Attribute] = []
        if isinstance(n, ast.Assign):
            tgts = [t for t in n.targets if isinstance(t, ast.Attribute)]
        elif isinstance(n, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(n.target, ast.Attribute):
            tgts = [n.target]
        for t in tgts:
            if isinstance(t.value, ast.Name) and t.value.id in watched:
                rec = (t.attr, n.lineno, under_lock(ctx, n, fn))
                if t.value.id == self_name:
                    s.self_attr_writes.append(rec)
                else:
                    s.param_attr_writes.setdefault(
                        t.value.id, []).append(rec)
        if isinstance(n, ast.While):
            s.has_while_loop = True
        if is_transfer_call(n):
            s.transfer_direct = True
        if isinstance(n, ast.Call):
            callee = None
            try:
                callee = resolver(mi, n, class_name, self_name)
            except Exception:  # noqa: BLE001 — resolution must never
                callee = None  # crash pass 1; degrade to unknown
            self_pos: Tuple[int, ...] = ()
            if self_name is not None:
                self_pos = tuple(
                    i for i, a in enumerate(n.args)
                    if isinstance(a, ast.Name) and a.id == self_name)
            try:
                crepr = ast.unparse(n.func)
            except Exception:  # noqa: BLE001 — odd nodes
                crepr = dec_name(n)
            s.calls.append(CallSite(
                line=n.lineno, callee=callee,
                under_lock=under_lock(ctx, n, fn),
                self_arg_positions=self_pos, callee_repr=crepr))

    s.loop_event_gates = _event_gates(fn, self_name)
    return s


def build_index(contexts: List[Any]) -> InterProcIndex:
    """Two-phase pass over every parsed module: (1) import maps +
    top-level def / class-method tables, (2) per-function summaries with
    call resolution, then the transitive fixpoints."""
    idx = InterProcIndex()

    # phase 1: names
    for ctx in contexts:
        mi = ModuleInfo(ctx.relpath, module_name_of(ctx.relpath),
                        is_package=ctx.relpath.endswith("__init__.py"))
        _collect_imports(mi, ctx.tree)
        for n in ctx.tree.body:
            if isinstance(n, FUNCS):
                mi.toplevel[n.name] = (ctx.relpath, n.name)
            elif isinstance(n, ast.ClassDef):
                methods = {}
                for m in n.body:
                    if isinstance(m, FUNCS):
                        methods[m.name] = (ctx.relpath,
                                           f"{n.name}.{m.name}")
                mi.classes[n.name] = methods
        idx.modules[mi.modname] = mi
        idx.modules_by_path[ctx.relpath] = mi

    # phase 2: summaries (imports + toplevel maps are complete, so call
    # sites resolve against the full project as they are extracted)
    for ctx in contexts:
        mi = idx.modules_by_path[ctx.relpath]
        bare = _has_bare_time_import(ctx.tree)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, FUNCS):
                continue
            # NEAREST enclosing class (nested closures inherit it via
            # the captured `self`); direct methods get param-0 self
            cls = None
            for a in ctx.ancestors(fn):
                if isinstance(a, ast.ClassDef):
                    cls = a.name
                    break
            direct = isinstance(ctx.parent(fn), ast.ClassDef)
            s = _summarize_function(ctx, mi, fn, cls, direct, bare,
                                    idx.resolve_call)
            s.return_call_targets = _return_targets(
                mi, fn, cls, s.self_name, idx.resolve_call)
            idx.functions[s.fid] = s

    _fixpoint(idx)
    return idx


def _return_targets(mi: ModuleInfo, fn: ast.AST,
                    class_name: Optional[str],
                    self_name: Optional[str], resolver) -> List[FuncId]:
    """Callees whose return value ``fn`` returns (directly or through
    one local name) — the taint/jit propagation edges."""
    out: List[FuncId] = []
    nodes = _scope_nodes(fn)
    call_named: Dict[str, ast.Call] = {}
    for n in nodes:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    call_named[t.id] = n.value
    for n in nodes:
        if not (isinstance(n, ast.Return) and n.value is not None):
            continue
        calls: List[ast.Call] = []
        if isinstance(n.value, ast.Call):
            calls.append(n.value)
        elif isinstance(n.value, ast.Name) \
                and n.value.id in call_named:
            calls.append(call_named[n.value.id])
        else:
            # `return now() - t0` style: every call inside the returned
            # expression can carry taint into the return value
            calls.extend(x for x in ast.walk(n.value)
                         if isinstance(x, ast.Call))
        for c in calls:
            try:
                fid = resolver(mi, c, class_name, self_name)
            except Exception:  # noqa: BLE001 — degrade to unknown
                fid = None
            if fid is not None:
                out.append(fid)
    return out


def _fixpoint(idx: InterProcIndex) -> None:
    """Close returns_wall / returns_fresh_jit over
    the call graph. Monotone boolean lattice -> terminates."""
    for s in idx.functions.values():
        s.returns_wall = s.returns_wall_direct
        # a memoized factory hands back the SAME closure per config key:
        # calling it per step is a cache hit, not a fresh compile
        s.returns_fresh_jit = s.returns_fresh_jit_direct \
            and not s.memoized
    changed = True
    while changed:
        changed = False
        for s in idx.functions.values():
            if not s.returns_wall:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_wall:
                        s.returns_wall = True
                        changed = True
                        break
            if not s.returns_fresh_jit and not s.memoized:
                for t in s.return_call_targets:
                    ts = idx.functions.get(t)
                    if ts is not None and ts.returns_fresh_jit:
                        s.returns_fresh_jit = True
                        changed = True
                        break


# ---------------------------------------------------------------------------
# GC04 helper: transitive attr-write collection from a thread entry
# ---------------------------------------------------------------------------

def collect_entry_writes(idx: InterProcIndex, ctx: Any,
                         entry_fid: FuncId, max_depth: int = 4) \
        -> List[Tuple[str, int, bool, str]]:
    """Every ``self.<attr>`` write reachable from thread entry point
    ``entry_fid`` by following method calls on self (and helper calls
    that receive self as an argument), with the lock context each call
    edge carries: a write is *guarded* when its own site sits under a
    ``with <lock>:`` OR every call edge leading to it held a lock.

    Returns ``(attr, report_line, guarded, via)`` where ``report_line``
    is always a line in the ENTRY's module (cross-module writes are
    reported at the call site that reaches them) and ``via`` names the
    callee chain for the finding message ("" for direct writes).
    """
    out: List[Tuple[str, int, bool, str]] = []
    seen: Set[Tuple[FuncId, bool]] = set()

    def visit(fid: FuncId, lock_held: bool, depth: int,
              report_line: Optional[int], via: str) -> None:
        if depth > max_depth or (fid, lock_held) in seen:
            return
        seen.add((fid, lock_held))
        s = idx.functions.get(fid)
        if s is None:
            return
        for attr, line, guarded in s.self_attr_writes:
            out.append((attr, report_line if report_line is not None
                        else line, guarded or lock_held, via))
        for c in s.calls:
            if c.callee is None:
                continue
            t = idx.functions.get(c.callee)
            if t is None:
                continue
            edge_locked = lock_held or c.under_lock
            nxt_via = c.callee_repr if not via \
                else f"{via} -> {c.callee_repr}"
            # same-class method on self: follow with the callee's own
            # line numbers when it lives in the same module (precise
            # report), else pin the report to this call site
            same_module = c.callee[0] == fid[0]
            rl = report_line if report_line is not None else (
                None if same_module else c.line)
            if t.is_method and t.class_name == s.class_name \
                    and same_module:
                visit(c.callee, edge_locked, depth + 1, rl, nxt_via)
            elif t.param_attr_writes or t.calls:
                # helper receiving self positionally: its writes to that
                # param are writes to our object
                for pos in c.self_arg_positions:
                    if pos < len(t.params):
                        pname = t.params[pos]
                        for attr, line, guarded in \
                                t.param_attr_writes.get(pname, []):
                            out.append((
                                attr,
                                report_line if report_line is not None
                                else c.line,
                                guarded or edge_locked, nxt_via))

    visit(entry_fid, False, 0, None, "")
    return out
