"""graftcheck engine — file walking, suppressions, baseline, CLI.

Two passes over the scanned tree: pass 1 parses every file and collects
the cross-file :class:`~.rules.ProjectIndex` (registry stub constants +
alias functions), pass 2 runs every rule per module. Suppression
comments (``# graftcheck: disable=GC02`` — trailing on the flagged line,
or alone on the line above) are honored before the baseline is applied.

Baseline semantics (``--baseline graftcheck_baseline.json``): a JSON
list of finding fingerprints tolerated for now. The gate fails on any
NON-baselined finding AND on any stale entry — a fixed finding must
leave the baseline in the same PR, so the debt list only ever shrinks.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .rules import (Finding, ModuleContext, ProjectIndex, RULES,
                    collect_project, run_rules)

__all__ = ["Finding", "run_paths", "scan_file", "load_baseline",
           "write_baseline", "main"]

_DIRECTIVE = re.compile(r"graftcheck:\s*disable=([A-Z0-9,\s]+)")
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)


def _comment_map(source: str) -> Tuple[Dict[int, str], Set[int]]:
    """line -> comment text, plus the set of comment-ONLY lines (a
    directive alone on its own line applies to the next code line)."""
    comments: Dict[int, str] = {}
    only: Set[int] = set()
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return comments, only
    code_lines: Set[int] = set()
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            comments[tok.start[0]] = tok.string
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            for line in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(line)
    only = {ln for ln in comments if ln not in code_lines}
    return comments, only


def _suppressions(comments: Dict[int, str],
                  comment_only: Set[int]) -> Dict[int, Set[str]]:
    """Effective per-line suppressed codes: a trailing directive covers
    its own line; a directive alone on a line covers the next line."""
    supp: Dict[int, Set[str]] = {}
    for line, text in comments.items():
        m = _DIRECTIVE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        supp.setdefault(line, set()).update(codes)
        if line in comment_only:
            supp.setdefault(line + 1, set()).update(codes)
    return supp


def _parse_one(path: str, relpath: str) \
        -> Tuple[Optional[ModuleContext], Optional[Finding]]:
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding("GC00", relpath, e.lineno or 0, 0,
                             f"syntax error: {e.msg}",
                             "graftcheck cannot analyze unparseable "
                             "source", "<module>")
    comments, only = _comment_map(source)
    ctx = ModuleContext(relpath, tree, comments)
    ctx.suppressions = _suppressions(comments, only)  # type: ignore
    return ctx, None


def scan_file(path: str, root: Optional[str] = None,
              project: Optional[ProjectIndex] = None) -> List[Finding]:
    """Analyze one file (convenience for tests); cross-file GC05 parity
    only sees stubs defined in this file unless ``project`` is given."""
    rel = os.path.relpath(path, root or os.getcwd()).replace(os.sep, "/")
    ctx, err = _parse_one(path, rel)
    if err is not None:
        return [err]
    assert ctx is not None
    if project is None:
        project = collect_project([ctx])
    return _apply_suppressions(ctx, run_rules(ctx, project))


def _apply_suppressions(ctx: ModuleContext,
                        findings: List[Finding]) -> List[Finding]:
    supp = getattr(ctx, "suppressions", {})
    return [f for f in findings if f.code not in supp.get(f.line, set())]


def run_paths(paths: Iterable[str], root: Optional[str] = None) \
        -> List[Finding]:
    """Scan every .py under ``paths``; returns suppression-filtered
    findings (baseline is the caller's concern). Paths in findings are
    relative to ``root`` (default: cwd), '/'-separated — baseline
    fingerprints stay stable across machines."""
    root = os.path.abspath(root or os.getcwd())
    contexts: List[ModuleContext] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root) \
            .replace(os.sep, "/")
        ctx, err = _parse_one(path, rel)
        if err is not None:
            findings.append(err)
            continue
        assert ctx is not None
        contexts.append(ctx)
    project = collect_project(contexts)
    for ctx in contexts:
        findings.extend(_apply_suppressions(ctx, run_rules(ctx, project)))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        data = data.get("findings", [])
    if not isinstance(data, list) \
            or not all(isinstance(x, str) for x in data):
        raise ValueError(f"{path}: baseline must be a JSON list of "
                         f"fingerprint strings (or {{'findings': [...]}})")
    return data


def write_baseline(path: str, findings: List[Finding]) -> None:
    data = {"version": 1,
            "comment": "graftcheck debt list — fixing a finding MUST "
                       "remove its entry (the gate flags stale entries); "
                       "see docs/STATIC_ANALYSIS.md",
            "findings": sorted(f.fingerprint for f in findings)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def gate(findings: List[Finding], baseline: List[str],
         covered: Optional[List[str]] = None) \
        -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline entries).

    ``covered`` — scan-root prefixes (relpaths, '/'-separated): an entry
    is judged stale only when its file lies UNDER a scanned root; a
    partial scan (one file/dir) must not flag the rest of the repo's
    baseline as stale. ``None`` = the scan covered everything."""
    prints = {f.fingerprint for f in findings}
    base = set(baseline)
    fresh = [f for f in findings if f.fingerprint not in base]

    def in_scope(fp: str) -> bool:
        if covered is None:
            return True
        path = fp.split("::", 1)[0]
        return any(p in (".", "") or path == p or path.startswith(p + "/")
                   for p in covered)

    stale = sorted(fp for fp in base - prints if in_scope(fp))
    return fresh, stale


# -- selfcheck --------------------------------------------------------------

_FIXTURES = {
    # one seeded violation per rule — the gate must catch every one
    "pkg/models/bad_model.py": (
        "import jax\n"
        "from functools import lru_cache\n\n"
        "def per_call_predict(f, x):\n"
        "    g = jax.jit(f)\n"
        "    return g(x)\n\n"
        "def nested_factory():\n"
        "    @lru_cache(maxsize=8)\n"
        "    def build(n):\n"
        "        return jax.jit(lambda v: v * n)\n"
        "    return build\n",
        {"GC01"}),
    "pkg/io/bad_io.py": (
        "import time\n\n"
        "def save_pointer(path, blob):\n"
        "    with open(path, 'w') as f:\n"
        "        f.write(blob)\n\n"
        "def wait(deadline_s):\n"
        "    deadline = time.time() + deadline_s\n"
        "    while time.time() < deadline:\n"
        "        pass\n",
        {"GC02", "GC03"}),
    "pkg/serve/bad_serve.py": (
        "import threading\n\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "        threading.Thread(target=self._a).start()\n"
        "        threading.Thread(target=self._b).start()\n"
        "    def _a(self):\n"
        "        self.count += 1\n"
        "    def _b(self):\n"
        "        try:\n"
        "            self.count -= 1\n"
        "        except Exception:\n"
        "            pass\n",
        {"GC04", "GC06"}),
    "pkg/obs/registry.py": (
        "FOO_STUB = {'ok': 0, 'bad-dash': 0}\n\n"
        "class P:\n"
        "    def obs_section(self):\n"
        "        return {'ok': 0, 'extra': 1}\n"
        "    def _register_obs(self):\n"
        "        def p():\n"
        "            return (self.obs_section() if self is not None\n"
        "                    else dict(FOO_STUB))\n"
        "        registry.register('bad.name', p)\n",
        {"GC05"}),
}


def selfcheck() -> int:
    """Prove the gate in both directions before trusting a clean run:
    every rule fires on its seeded fixture; a baseline silences them; a
    fixed finding turns its baseline entry stale (nonzero)."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="graftcheck_selfcheck_")
    try:
        for rel, (src, _want) in _FIXTURES.items():
            p = os.path.join(tmp, rel)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w", encoding="utf-8") as f:
                f.write(src)
        findings = run_paths([os.path.join(tmp, "pkg")], root=tmp)
        got = {}
        for f in findings:
            got.setdefault(f.path, set()).add(f.code)
        failures = []
        for rel, (_src, want) in _FIXTURES.items():
            missing = want - got.get(rel, set())
            if missing:
                failures.append(f"{rel}: rule(s) {sorted(missing)} did "
                                f"not fire on the seeded violation")
        if not findings:
            failures.append("no findings at all on the seeded tree")
        # direction 2: baseline silences, then goes stale after a "fix"
        bl = os.path.join(tmp, "baseline.json")
        write_baseline(bl, findings)
        fresh, stale = gate(findings, load_baseline(bl))
        if fresh or stale:
            failures.append("baselined tree did not gate clean")
        kept = [f for f in findings if f.code != "GC03"]
        fresh, stale = gate(kept, load_baseline(bl))
        if not stale:
            failures.append("fixed finding did not turn its baseline "
                            "entry stale")
        if failures:
            for msg in failures:
                print(f"graftcheck --selfcheck FAIL: {msg}",
                      file=sys.stderr)
            return 1
        print(f"graftcheck --selfcheck: {len(findings)} seeded findings "
              f"caught across {len(_FIXTURES)} fixtures; baseline gate "
              f"bidirectional (silences fresh, flags stale)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# -- CLI --------------------------------------------------------------------

def _default_paths() -> List[str]:
    """The installed package tree (works from any cwd)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return [pkg]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hivemall_tpu.tools.graftcheck",
        description="project-invariant static analyzer "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the hivemall_tpu "
                         "package)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: ./graftcheck_baseline"
                         ".json when present)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current findings as the new baseline and "
                         "exit 0")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--selfcheck", action="store_true",
                    help="prove every rule fires on seeded violations "
                         "and the baseline gate works both ways")
    ap.add_argument("--root", default=None,
                    help="path-relativity root for fingerprints "
                         "(default: cwd)")
    args = ap.parse_args(argv)

    if args.selfcheck:
        return selfcheck()

    paths = args.paths or _default_paths()
    root = args.root
    if root is None and not args.paths:
        # default scan: relative to the repo root (the package's parent)
        root = os.path.dirname(_default_paths()[0])
    findings = run_paths(paths, root=root)

    if args.write_baseline:
        write_baseline(args.write_baseline, findings)
        print(f"graftcheck: wrote {len(findings)} fingerprint(s) to "
              f"{args.write_baseline}")
        return 0

    baseline_path = args.baseline
    if baseline_path is None and os.path.exists("graftcheck_baseline.json"):
        baseline_path = "graftcheck_baseline.json"
    baseline: List[str] = []
    if baseline_path:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"graftcheck: cannot read baseline: {e}",
                  file=sys.stderr)
            return 2
    abs_root = os.path.abspath(root or os.getcwd())
    covered = [os.path.relpath(os.path.abspath(p), abs_root)
               .replace(os.sep, "/") for p in paths]
    fresh, stale = gate(findings, baseline, covered)

    if args.json:
        print(json.dumps({
            "findings": [vars(f) | {"fingerprint": f.fingerprint}
                         for f in fresh],
            "baselined": len(findings) - len(fresh),
            "stale_baseline": stale}, indent=1))
    else:
        for f in fresh:
            print(f.render())
        for fp in stale:
            print(f"graftcheck: STALE baseline entry (fixed finding must "
                  f"leave the baseline): {fp}")
        n_base = len(findings) - len(fresh)
        status = "clean" if not (fresh or stale) else "FAIL"
        print(f"graftcheck: {status} — {len(fresh)} finding(s)"
              + (f", {n_base} baselined" if n_base else "")
              + (f", {len(stale)} stale baseline entr"
                 + ("y" if len(stale) == 1 else "ies") if stale else ""))
    return 1 if (fresh or stale) else 0
